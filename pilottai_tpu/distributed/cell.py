"""Serving cell: N engine replicas behind one KV-affinity front door.

ISSUE 11 / ROADMAP item 2 — the million-user shape is many engine
replicas behind one admission point, not one bigger engine. A
:class:`ServingCell` hosts N replicas in one process (each its own
``LLMHandler`` + batcher + per-replica SLO registry, so tests and bench
run a realistic cell without N processes) and routes every request with
:class:`~pilottai_tpu.distributed.router.ReplicaRouter`:

* **KV affinity** — a cell-level radix routing table (prompt byte
  prefixes → last-serving replica) plus sticky session pins, so a
  session's next turn lands where its KV already lives (a restore or a
  hot prefix hit instead of a full re-prefill).
* **SLO headroom** — each replica carries its own
  :class:`~pilottai_tpu.obs.SLOTracker` (own ``MetricsRegistry``); the
  router reads per-class burn rate per replica, and the cell sheds a
  class at the boundary once *every* routable replica is past that
  class's admission threshold — before any replica's own queue shed.
* **Fault routing** — a watchdog-stalled, breaker-open or draining
  replica never receives new work; a replica-level failure re-routes
  the request to a sibling (bounded attempts), so one dying replica
  reads as latency, not errors, at the cell boundary.

The creative rung: the host cold tier's spill format is also the
**transfer** format. ``migrate_session`` exports a session's KV lineage
from its owner (host entries move, device-resident panels/pages copy to
host numpy) and imports it into another replica's host tier — the
session's next turn restores there, byte-identical by the tier's parity
contract (same weights across replicas by construction). ``drain``
composes that with request re-admission for zero-downtime replica
removal: new work routes away instantly, pinned sessions migrate, and
in-flight unary requests past the grace window are cancelled and
re-admitted on a sibling (full greedy re-execution — the cell-level
analogue of PR 8's snapshot + re-admit). Mid-stream requests are the
non-migratable shape (their deltas are already on the wire; the drain
waits for them within grace), same boundary as PR 8's mid-stream
json/schema recovery rule — see docs/SERVING.md "Serving cell".

The cell duck-types ``LLMHandler`` (``generate_response`` / ``astream``
/ ``apredict`` / ``config`` / ``get_metrics``), so ``APIServer`` serves
a cell exactly like a single engine; ``/healthz`` and ``/slo.json``
aggregate across replicas via ``health_snapshot`` / ``slo_snapshot``.

Import cost: stdlib + numpy + handler/obs/reliability — no jax at
import time (the engines themselves import it lazily when they boot).
"""

from __future__ import annotations

import asyncio
import base64
import re
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from pilottai_tpu.distributed.router import (
    CellOverloaded,
    ReplicaRouter,
    ReplicaSignals,
    RoutingTable,
    route_key,
)
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.kvcache.integrity import KV_FRAME_VERSION
from pilottai_tpu.engine.types import ChatMessage, GenerationParams, ToolSpec
from pilottai_tpu.obs import DEFAULT_CLASS, SLOTracker, global_flight
from pilottai_tpu.reliability import (
    CircuitOpenError,
    DeadlineExceeded,
    EngineOverloaded,
    global_engine_health,
)
from pilottai_tpu.reliability.inject import global_injector
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics


class _HandoffUnavailable(Exception):
    """Internal: this handoff attempt can't complete (nothing cached,
    frame rejected, target refused) — serve the request colocated."""


def parse_disagg_spec(spec: str) -> Tuple[int, int]:
    """``"<P>p<D>d"`` → ``(prefill_count, decode_count)`` — the
    ``cell_disagg`` knob's shape (core/config.py validates the same
    grammar at config time; this is the one parser both share a regex
    with). Replicas beyond P+D stay ``mixed``."""
    m = re.fullmatch(r"(\d+)p\+?(\d+)d", str(spec).strip().lower())
    if m is None:
        raise ValueError(
            f"cell_disagg must be '<P>p<D>d' (e.g. '1p2d'); got {spec!r}"
        )
    return int(m.group(1)), int(m.group(2))


class CellReplica:
    """One replica: an ``LLMHandler`` plus the cell-side bookkeeping the
    router reads (per-replica SLO tracker on its own registry, in-flight
    count, draining flag)."""

    def __init__(
        self,
        replica_id: str,
        handler: LLMHandler,
        slo_classes=None,
        soft_inflight: Optional[int] = None,
        tier: str = "mixed",
    ) -> None:
        self.replica_id = replica_id
        self.handler = handler
        #: disaggregated-serving role (ISSUE 19): "prefill" / "decode" /
        #: "mixed". Assigned by the cell from ``cell_disagg``; "mixed"
        #: (every replica, colocated cells) serves both phases.
        self.tier = tier
        #: Per-replica obs registry: the replica's SLO series live here,
        #: namespaced by object instead of by string prefix — N replicas
        #: in one process can't collide on ``slo.interactive.*``.
        self.registry = MetricsRegistry()
        self.slo = SLOTracker(classes=slo_classes, registry=self.registry)
        self.draining = False
        self.inflight = 0
        #: Soft in-flight norm for queue_frac when the backend exposes no
        #: engine queue (mock replicas, engine not yet booted).
        self.soft_inflight = soft_inflight or max(
            getattr(handler.config, "max_concurrent_requests", 8) or 8, 1
        )
        self._calls: set = set()
        #: Tasks the DRAIN cancelled (vs the caller): the execute loop
        #: re-admits exactly these — inferring from the draining flag
        #: would misread a client disconnect racing a drain as a
        #: re-admission and resurrect an abandoned request.
        self._drain_cancelled: set = set()

    @property
    def health_source(self) -> Optional[str]:
        """This replica's ``EngineHealth`` source (the engine watchdog's
        name when it has one, else a cell-scoped name tests can trip)."""
        batcher = getattr(self.handler.backend, "batcher", None)
        src = getattr(batcher, "watchdog_source", None)
        return src if src is not None else f"cell:{self.replica_id}"

    def signals(self) -> ReplicaSignals:
        """The router's view of this replica, combining engine-side
        signals (queue/degrade/watchdog, when an engine is up) with
        cell-side ones (in-flight count, per-class burn, breaker,
        draining)."""
        raw = getattr(self.handler.backend, "routing_signals", None)
        sig = raw() if callable(raw) else {}
        depth = int(sig.get("queue_depth", 0)) + self.inflight
        queue_frac = max(
            float(sig.get("queue_frac", 0.0)),
            self.inflight / self.soft_inflight,
        )
        self.slo.refresh_gauges()
        burn = {
            cls: self.registry.get(f"slo.{cls}.burn_rate")
            for cls in self.slo.classes
        }
        breaker = self.handler.breaker
        breaker_open = breaker is not None and breaker.state == "open"
        healthy = bool(
            sig.get("healthy", True)
        ) and global_engine_health.source_healthy(self.health_source)
        return ReplicaSignals(
            replica_id=self.replica_id,
            queue_depth=depth,
            queue_frac=queue_frac,
            degrade_level=int(sig.get("degrade_level", 0)),
            mesh_rung=int(sig.get("mesh_rung", 0)),
            burn_rate=burn,
            healthy=healthy,
            breaker_open=breaker_open,
            draining=self.draining,
            tier=self.tier,
        )


class ServingCell:
    """The cell front door (see module docstring)."""

    def __init__(
        self,
        replicas: Iterable[CellReplica | LLMHandler],
        router: Optional[ReplicaRouter] = None,
        *,
        slo_classes=None,
        reroute_attempts: int = 2,
        table_capacity: int = 4096,
        max_sessions: int = 4096,
        cell_disagg: Optional[str] = None,
        #: prefix-hot bypass threshold (ISSUE 19): a prompt whose
        #: routing-table hit covers at least this fraction of its key
        #: skips the prefill tier — its KV mostly exists already, so a
        #: handoff would move less than it costs.
        prefix_hot_frac: float = 0.5,
        #: prompts with keys shorter than this (bytes) route straight to
        #: the decode tier: their prefill is too small to interfere.
        disagg_min_key: int = 64,
    ) -> None:
        self.replicas: Dict[str, CellReplica] = {}
        for i, rep in enumerate(replicas):
            if isinstance(rep, LLMHandler):
                rep = CellReplica(f"r{i}", rep, slo_classes=slo_classes)
            self.replicas[rep.replica_id] = rep
        if not self.replicas:
            raise ValueError("a serving cell needs at least one replica")
        self.router = router if router is not None else ReplicaRouter(
            RoutingTable(capacity=table_capacity)
        )
        # Disaggregated topology (ISSUE 19): assign tier roles from the
        # explicit kwarg or the shared config knob. Unset → every
        # replica stays "mixed" and every disagg branch below is dead
        # code — the exact-no-op contract of the colocated cell.
        spec = cell_disagg
        if spec is None:
            first_cfg = next(iter(self.replicas.values())).handler.config
            spec = getattr(first_cfg, "cell_disagg", None)
        self.prefix_hot_frac = float(prefix_hot_frac)
        self.disagg_min_key = int(disagg_min_key)
        self._disagg = False
        if spec:
            n_p, n_d = parse_disagg_spec(spec)
            order = list(self.replicas.values())
            for rep in order[:n_p]:
                rep.tier = "prefill"
            for rep in order[n_p:n_p + n_d]:
                rep.tier = "decode"
            # Handoff needs a prefill source AND a distinct target; a
            # degenerate spec (0 prefill, or prefill-only) keeps the
            # colocated path.
            self._disagg = (
                any(r.tier == "prefill" for r in order)
                and any(r.tier != "prefill" for r in order)
            )
        self.reroute_attempts = max(0, int(reroute_attempts))
        #: session id → owning replica id (sticky affinity pins).
        #: Bounded LRU, same rationale as ``HostTier``'s session table:
        #: client-minted ids must not grow cell state without bound.
        self.sessions: "OrderedDict[str, str]" = OrderedDict()
        self.max_sessions = max(1, int(max_sessions))
        first = next(iter(self.replicas.values()))
        self._classes = set(first.slo.classes)
        for cls in self._classes:
            # Non-default classes: the cell's per-class counters must
            # exist in the exported surface too (obs/__init__ declares
            # the default interactive/batch pair at import).
            global_metrics.declare(f"cell.routed.{cls}", "counter")
            global_metrics.declare(f"cell.shed.{cls}", "counter")
        self._log = get_logger("cell")
        self._started = False
        global_metrics.set_gauge("cell.replicas", float(len(self.replicas)))

    # ------------------------------------------------------------------ #
    # LLMHandler duck-type surface (APIServer compatibility)
    # ------------------------------------------------------------------ #

    @property
    def config(self):
        return next(iter(self.replicas.values())).handler.config

    @property
    def backend(self):
        """First replica's backend — replicas are identical by
        construction, so schema-support checks hold cell-wide."""
        return next(iter(self.replicas.values())).handler.backend

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._started:
            return
        for rep in self.replicas.values():
            await rep.handler.start()
            self._wire_eviction_decay(rep)
            if rep.handler.breaker is not None:
                # Scope the breaker's stall subscription to THIS
                # replica's engine: a sibling's watchdog stall must not
                # force-open every breaker in the process (one hung
                # replica would ground the whole cell).
                rep.handler.breaker.health_sources = {rep.health_source}
        self._started = True
        self._refresh_gauges()

    async def stop(self) -> None:
        for rep in self.replicas.values():
            await rep.handler.stop()
        self._started = False

    def _wire_eviction_decay(self, rep: CellReplica) -> None:
        """Affinity must not outlive the KV it points at: when a
        replica's host tier drops an entry for good (budget eviction —
        the KV is gone from BOTH tiers), ``HostTier.on_evict`` offers
        the evicted key to the routing table. The decay is EXACT when
        the table is keyed by the same token ids the engine caches
        (token-level router deployments; pinned by the unit test). The
        cell's own table keys are rendered-prompt bytes, which the
        engine's tokenization/chat rendering generally shifts — there
        the forget is a best-effort no-op and the table's LRU bound +
        ``forget_replica`` on drain/death are the decay that holds."""
        batcher = getattr(rep.handler.backend, "batcher", None)
        kvcache = getattr(batcher, "kvcache", None)
        host = getattr(kvcache, "host", None)
        if host is not None:
            # Ownership-checked: replica A evicting its copy of a shared
            # preamble must not decay an entry pointing at replica B,
            # whose copy is still live.
            rid = rep.replica_id
            host.on_evict = (
                lambda key, _rid=rid: self.router.table.forget_owned(
                    key, _rid
                )
            )

    # ------------------------------------------------------------------ #
    # Routing
    # ------------------------------------------------------------------ #

    @staticmethod
    def _route_text(messages) -> str:
        if isinstance(messages, str):
            return messages
        parts = []
        for m in messages:
            if isinstance(m, str):
                parts.append(m)
            elif isinstance(m, dict):
                parts.append(str(m.get("content", "")))
            else:
                parts.append(str(getattr(m, "content", "")))
        return "\n".join(parts)

    def _classify(self, slo_class: Optional[str]) -> str:
        return slo_class if slo_class in self._classes else DEFAULT_CLASS

    def signals(self) -> List[ReplicaSignals]:
        return [rep.signals() for rep in self.replicas.values()]

    def _refresh_gauges(
        self, sigs: Optional[List[ReplicaSignals]] = None
    ) -> None:
        # Callers on the routing hot path pass the sweep they already
        # computed — per-replica signals (SLO window refresh, health
        # lock, engine probe) are not free twice per request.
        if sigs is None:
            sigs = self.signals()
        global_metrics.set_gauge("cell.replicas", float(len(sigs)))
        global_metrics.set_gauge(
            "cell.replicas_routable",
            float(sum(s.routable() for s in sigs)),
        )
        # Replicas serving on a degraded mesh rung (shard loss survived
        # via re-plan): still routable, but the router down-scores them
        # and rebalance_degraded migrates sessions off.
        global_metrics.set_gauge(
            "cell.degraded_replicas",
            float(sum(s.mesh_rung > 0 for s in sigs)),
        )
        global_metrics.set_gauge("cell.sessions", float(len(self.sessions)))
        if self._disagg:
            for t in ("prefill", "decode", "mixed"):
                global_metrics.set_gauge(
                    f"cell.tier.{t}_replicas",
                    float(sum(s.tier == t for s in sigs)),
                )
        lookups = global_metrics.get("cell.affinity_lookups")
        if lookups:
            global_metrics.set_gauge(
                "cell.affinity_hit_rate",
                global_metrics.get("cell.affinity_hits") / lookups,
            )

    def _route(
        self,
        key: Sequence[int],
        cls: str,
        session_id: Optional[str],
        exclude: List[str],
        tier: Optional[str] = None,
    ) -> tuple:
        pinned = self.sessions.get(session_id) if session_id else None
        sigs = self.signals()
        try:
            rid, lcp = self.router.pick(
                key, sigs, slo_class=cls, pinned=pinned, exclude=exclude,
                tier=tier,
            )
        except CellOverloaded as exc:
            global_metrics.inc(f"cell.shed.{cls}")
            self._refresh_gauges(sigs)
            raise EngineOverloaded(str(exc)) from exc
        global_metrics.inc(f"cell.routed.{cls}")
        global_metrics.inc("cell.affinity_lookups")
        if lcp > 0 or (pinned is not None and pinned == rid):
            global_metrics.inc("cell.affinity_hits")
        self._refresh_gauges(sigs)
        return rid, lcp

    def _after_success(
        self, rid: str, key: Sequence[int], session_id: Optional[str]
    ) -> None:
        self.router.table.note(key, rid)
        if not session_id:
            return
        rep = self.replicas.get(rid)
        if rep is None or rep.draining:
            # Never (re-)pin to a draining/detached replica — a request
            # finishing inside the drain's grace window must not undo
            # the drain's migration.
            return
        cur = self.sessions.get(session_id)
        if cur is not None and cur != rid:
            cur_rep = self.replicas.get(cur)
            if cur_rep is not None and not cur_rep.draining:
                # The pin moved (migration/rebalance) while this request
                # was in flight: the newer LIVE pin owns the session's
                # KV now — a stale completion must not re-pin the old
                # owner and strand the migrated KV. (A dead/draining
                # current pin DOES yield: failover re-pins here.)
                return
        self.sessions[session_id] = rid
        self.sessions.move_to_end(session_id)
        while len(self.sessions) > self.max_sessions:
            self.sessions.popitem(last=False)

    # ------------------------------------------------------------------ #
    # Disaggregated prefill/decode (ISSUE 19)
    # ------------------------------------------------------------------ #

    def _disagg_decision(
        self, key: Sequence[int], sid: Optional[str],
        gang_id: Optional[str],
    ) -> str:
        """Admission policy of the disaggregated cell: ``"handoff"``
        sends the request through the prefill tier + KV handoff;
        ``"decode"`` admits it to the decode tier directly. Decode-
        direct shapes: sticky sessions (their KV lives on the decode
        tier already), gang members (the DAG scheduler co-schedules a
        gang on ONE engine's backlog), short prompts (nothing to
        disaggregate), and prefix-hot prompts — a routing-table hit
        covering ``prefix_hot_frac`` of the key means most of the
        prefill is a cache restore wherever it lands."""
        if sid and sid in self.sessions:
            return "decode"
        if gang_id:
            return "decode"
        if len(key) < self.disagg_min_key:
            return "decode"
        alive = [
            s.replica_id for s in self.signals()
            if s.routable() and s.tier != "prefill"
        ]
        if alive:
            _owner, lcp = self.router.table.lookup(key, alive=alive)
            if lcp >= self.prefix_hot_frac * len(key):
                global_metrics.inc("cell.tier.bypass")
                return "decode"
        return "handoff"

    async def _handoff(
        self,
        messages,
        tools,
        params: Optional[GenerationParams],
        json_mode,
        json_schema,
        *,
        cls: str,
        sid: Optional[str],
        priority: Optional[int],
        key: Sequence[int],
        t0: float,
    ):
        """The disaggregated hot path: prefill on the prefill tier,
        stream the fresh KV to a decode-tier replica over the PR 14
        checksummed wire frames, then serve the FULL request there in
        decode-resume mode — admission restores the imported KV
        (``_PreparedAdmission`` prefix / prefix_paged, a PR 9 host-tier
        restore), so the decode replica never re-prefills. Greedy output
        is byte-identical to the colocated path by the KV tier's parity
        contract.

        Returns ``(response, params)``; ``response is None`` means the
        caller must serve colocated (empty/ineligible prefill tier, a
        non-migratable shape, or a failed handoff — ``params`` rides
        back so a flight the handoff already opened closes on the
        fallback attempt). Client-semantic failures (deadline, cancel)
        propagate — a dead budget is dead on every tier."""
        # Normalize params exactly like the handler would, so the
        # prompt ids rendered here match both legs' submissions.
        if params is None:
            s = self.config.sampling
            params = GenerationParams(
                max_new_tokens=s.max_new_tokens, temperature=s.temperature,
                top_k=s.top_k, top_p=s.top_p, seed=s.seed,
                json_mode=s.json_mode,
            )
        if params.max_new_tokens <= 1:
            return None, params  # no decode phase to protect
        sigs = self.signals()
        try:
            pre_rid, _ = self.router.pick(
                key, sigs, slo_class=cls, tier="prefill",
            )
        except CellOverloaded:
            return None, params
        pre = self.replicas[pre_rid]
        if pre.tier != "prefill":
            # The prefill tier is empty/unroutable and pick degraded to
            # a mixed sibling — that IS the colocated path; a same-
            # replica "handoff" would only add wire overhead.
            return None, params
        render = getattr(pre.handler.backend, "render_request_ids", None)
        exporter = getattr(pre.handler.backend, "export_request_kv", None)
        if not callable(render) or not callable(exporter):
            return None, params  # backend without the engine surface
        try:
            # Same coercion as the handler's normalize path — the ids
            # rendered here must be the ids both legs submit.
            msgs = [ChatMessage.coerce(m) for m in messages]
            specs = [
                t if isinstance(t, ToolSpec) else ToolSpec(**t)
                for t in (tools or [])
            ]
            ids, truncated = render(msgs, specs, params)
        except Exception:  # noqa: BLE001 — engine not booted etc.
            return None, params
        if truncated or not ids:
            # Non-migratable shape: the keep-window truncation depends
            # on max_new_tokens, which differs between the legs — the
            # two would prefill DIFFERENT ids (docs/SERVING.md).
            return None, params
        try:
            dst_rid, _ = self.router.pick(
                key, sigs, slo_class=cls, exclude=[pre_rid], tier="decode",
            )
        except CellOverloaded:
            return None, params
        dst = self.replicas[dst_rid]
        importer = getattr(dst.handler.backend, "import_request_kv", None)
        if not callable(importer):
            return None, params
        # Committed: both legs picked, the shape is migratable. The
        # client flight opens HERE so its ledger carries the handoff
        # span; both legs (and any fallback) ride the same id, so the
        # serving attempt's handler closes it — never a leaked flight.
        update: Dict[str, Any] = {}
        if params.flight_id is None:
            update["flight_id"] = uuid.uuid4().hex[:16]
        if params.trace_id is None:
            update["trace_id"] = uuid.uuid4().hex[:16]
        if update:
            params = params.model_copy(update=update)
        fid = params.flight_id
        global_flight.start(
            fid, trace_id=params.trace_id, model=self.config.model_name,
            slo_class=cls, session_id=sid,
        )
        global_metrics.inc("cell.handoffs")
        global_metrics.inc("cell.tier.prefill_routed")
        h0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        try:
            # Prefill leg: one token on the prefill replica. Its own
            # flight is deliberately NOT the client's (the client flight
            # must finish exactly once, on the serving leg); admission
            # caches the prompt KV (dense panel / pinned page chain), so
            # the export below finds it. max_new_tokens=1 keeps the
            # keep-window maximal — ``truncated`` above was checked
            # against the CLIENT's window, the stricter of the two.
            pre_params = params.model_copy(update={
                "max_new_tokens": 1, "flight_id": None,
            })
            pre.inflight += 1
            try:
                await pre.handler.generate_response(
                    messages, tools=tools, params=pre_params,
                    json_mode=json_mode, json_schema=json_schema,
                    slo_class=cls, session_id=sid, priority=priority,
                )
            finally:
                pre.inflight -= 1
            global_flight.mark(fid, "handoff")
            # Export the fresh KV (blocking device→host gathers — off
            # the event loop) and round-trip the canonical wire frame,
            # same as migrate_session: the integrity framing is live on
            # the hot path and ``cell.handoff.corrupt`` has a real
            # payload to rot.
            export = await loop.run_in_executor(None, exporter, ids, sid)
            if not export:
                raise _HandoffUnavailable("nothing cached to hand off")

            def _wire_roundtrip(exp):
                # Serialization + checksums over a whole prompt's KV —
                # executor work, or it would stall every in-flight
                # request's bookkeeping on the event loop.
                w = session_kv_to_wire(exp)
                if global_injector.fire("cell.handoff.corrupt"):
                    corrupt_wire_payload(w)
                return session_kv_from_wire(w), len(w.get("entries", ()))

            try:
                export, _ = await loop.run_in_executor(
                    None, _wire_roundtrip, export
                )
            except ValueError as exc:
                n = len(export.get("entries", ()))
                global_metrics.inc(
                    "engine.kvcache.integrity_failures", n
                )
                global_metrics.inc("cell.handoff_rejected", n)
                raise _HandoffUnavailable(f"frame rejected: {exc}")
            landed = await loop.run_in_executor(None, importer, export)
            accepted = int(landed.get("accepted", 0))
            rejected = int(landed.get("rejected", 0))
            if rejected:
                global_metrics.inc("cell.handoff_rejected", rejected)
            if not accepted:
                raise _HandoffUnavailable("no entry landed on the target")
            global_flight.mark(fid, "handoff_done")
            global_metrics.inc(
                "cell.handoff_tokens", int(landed.get("tokens", 0))
            )
            global_metrics.observe(
                "cell.handoff_ms", (time.perf_counter() - h0) * 1e3
            )
        except (asyncio.CancelledError, DeadlineExceeded):
            raise
        except Exception as exc:  # noqa: BLE001 — any leg failure
            # Prefill replica died mid-handoff, export raced a rebuild,
            # frame rotted, target rejected: all one outcome — colocated
            # fallback, full re-execution, byte-identical output. The
            # open flight rides back on ``params`` and closes there.
            global_metrics.inc("cell.handoff_fallbacks")
            self._log.warning(
                "handoff via %s -> %s fell back to colocated: %s",
                pre_rid, dst_rid, exc,
            )
            return None, params
        # Decode leg: the FULL original request on the target. Its
        # admission takes the prefix restore from the imported KV
        # (lcp = n-1 dense, the page chain paged) — decode resumes with
        # no re-prefill. Failures here re-route through the caller's
        # loop like any replica fault.
        global_metrics.inc("cell.tier.decode_routed")
        dst.inflight += 1
        task = asyncio.ensure_future(dst.handler.generate_response(
            messages, tools=tools, params=params, json_mode=json_mode,
            json_schema=json_schema, slo_class=cls, session_id=sid,
            priority=priority,
        ))
        dst._calls.add(task)
        try:
            response = await task
        except asyncio.CancelledError:
            if task in dst._drain_cancelled:
                dst._drain_cancelled.discard(task)
                global_metrics.inc("cell.handoff_fallbacks")
                return None, params
            task.cancel()
            raise
        except DeadlineExceeded:
            dst.slo.record(cls, ok=False)
            raise
        except Exception:
            dst.slo.record(cls, ok=False)
            global_metrics.inc("cell.handoff_fallbacks")
            return None, params
        finally:
            dst.inflight -= 1
            dst._calls.discard(task)
        dst.slo.record(cls, e2e_s=time.perf_counter() - t0, ok=True)
        global_metrics.inc(f"cell.routed.{cls}")
        self._after_success(dst_rid, key, sid)
        return response, params

    # ------------------------------------------------------------------ #
    # Request execution
    # ------------------------------------------------------------------ #

    async def generate_response(
        self,
        messages,
        tools=None,
        params=None,
        json_mode=None,
        json_schema=None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        priority: Optional[int] = None,
        gang_id: Optional[str] = None,
        gang_size: int = 0,
    ):
        """Route-and-execute with bounded re-routing: replica faults
        (including a drain cancelling the in-flight call) re-admit on a
        sibling; client-semantic failures (deadline, cell shed) do not."""
        cls = self._classify(
            slo_class or getattr(params, "slo_class", None)
        )
        sid = session_id or getattr(params, "session_id", None)
        key = route_key(self._route_text(messages))
        excluded: List[str] = []
        attempts = 0
        # Client-observed clock: started ONCE, before any attempt — a
        # rerouted request's recorded e2e must include the failed
        # attempts the client also waited through, charged to the
        # replica that finally served it.
        t0 = time.perf_counter()
        tier = None
        if self._disagg:
            if self._disagg_decision(key, sid, gang_id) == "handoff":
                response, params = await self._handoff(
                    messages, tools, params, json_mode, json_schema,
                    cls=cls, sid=sid, priority=priority, key=key, t0=t0,
                )
                if response is not None:
                    return response
                # Colocated fallback: no tier filter — a dead prefill
                # replica is already excluded by its health signals, and
                # the decode tier alone may not have the headroom.
            else:
                global_metrics.inc("cell.tier.decode_routed")
                tier = "decode"
        while True:
            rid, _lcp = self._route(key, cls, sid, excluded, tier=tier)
            rep = self.replicas[rid]
            rep.inflight += 1
            task = asyncio.ensure_future(rep.handler.generate_response(
                messages, tools=tools, params=params, json_mode=json_mode,
                json_schema=json_schema, slo_class=cls, session_id=sid,
                priority=priority, gang_id=gang_id, gang_size=gang_size,
            ))
            rep._calls.add(task)
            try:
                response = await task
            except asyncio.CancelledError:
                was_drain = task in rep._drain_cancelled
                rep._drain_cancelled.discard(task)
                if task.cancelled() and was_drain:
                    # Drain re-admission: the DRAIN cancelled this task
                    # (explicit marker — a client disconnect racing the
                    # drain must keep propagating as a cancel, not
                    # resurrect the request on a sibling). Re-route the
                    # whole request: pure re-execution, byte-identical
                    # greedy output on an identical sibling. Routine
                    # operation — no SLO miss recorded.
                    global_metrics.inc("cell.rerouted")
                    excluded.append(rid)
                    continue
                task.cancel()
                raise
            except DeadlineExceeded:
                # Terminal client outcome: the budget is gone wherever
                # we'd route next.
                rep.slo.record(cls, ok=False)
                raise
            except (EngineOverloaded, CircuitOpenError):
                # Backpressure / fast-fail below the cell's threshold
                # (racy burst, breaker race): try a sibling. The queue
                # and breaker signals already carry this state — a miss
                # is recorded only when the request terminally fails,
                # else a retried-then-served request would count twice
                # (once as a phantom miss) and sink reported attainment
                # below what clients actually observed.
                excluded.append(rid)
                attempts += 1
                if attempts <= self.reroute_attempts:
                    global_metrics.inc("cell.rerouted")
                    continue
                rep.slo.record(cls, ok=False)
                raise
            except Exception:
                # Replica fault: burn THIS replica's budget (the router
                # reads it) and re-route, bounded.
                rep.slo.record(cls, ok=False)
                excluded.append(rid)
                attempts += 1
                if attempts <= self.reroute_attempts:
                    global_metrics.inc("cell.rerouted")
                    continue
                raise
            finally:
                rep.inflight -= 1
                rep._calls.discard(task)
            rep.slo.record(
                cls, e2e_s=time.perf_counter() - t0, ok=True
            )
            self._after_success(rid, key, sid)
            return response

    async def apredict(self, prompt: str, **kwargs: Any) -> str:
        response = await self.generate_response([prompt], **kwargs)
        return response.content

    async def astream(
        self,
        messages,
        tools=None,
        params=None,
        json_mode=None,
        json_schema=None,
        slo_class: Optional[str] = None,
        session_id: Optional[str] = None,
        info: Optional[Dict[str, Any]] = None,
    ):
        """Streaming path: routed once — a stream whose deltas reached
        the consumer is the non-migratable shape (drain waits for it
        within grace; docs/SERVING.md), so no mid-stream re-route."""
        cls = self._classify(
            slo_class or getattr(params, "slo_class", None)
        )
        sid = session_id or getattr(params, "session_id", None)
        key = route_key(self._route_text(messages))
        # Streams are the non-migratable shape (deltas on the wire), so
        # a disaggregated cell admits them to the decode tier directly.
        rid, _lcp = self._route(
            key, cls, sid, [], tier="decode" if self._disagg else None,
        )
        rep = self.replicas[rid]
        t0 = time.perf_counter()
        rep.inflight += 1
        ok = False
        abandoned = False
        try:
            async for delta in rep.handler.astream(
                messages, tools=tools, params=params, json_mode=json_mode,
                json_schema=json_schema, slo_class=cls, session_id=sid,
                info=info,
            ):
                yield delta
            ok = True
        except (GeneratorExit, asyncio.CancelledError):
            # Consumer walked away — not the replica's failure. Charging
            # it as a miss would raise this replica's burn rate and
            # steer the router away from a healthy replica that merely
            # served flaky clients.
            abandoned = True
            raise
        finally:
            rep.inflight -= 1
            if not abandoned:
                rep.slo.record(
                    cls, e2e_s=time.perf_counter() - t0, ok=ok
                )
            if ok:
                self._after_success(rid, key, sid)

    # ------------------------------------------------------------------ #
    # Session migration + drain (the transfer-format rung)
    # ------------------------------------------------------------------ #

    def _pick_target(self, exclude: Sequence[str]) -> str:
        """Migration target: the least-loaded ROUTABLE sibling, full-
        mesh replicas before degraded ones (a replica surviving shard
        loss on a sub-mesh rung is a worse home for a session than an
        intact sibling, whatever its queue says). This is a
        control-plane move, not an admission — class shed thresholds
        don't apply (a saturated-but-healthy sibling still accepts a
        session's KV; it just serves the next turn slower)."""
        excluded = set(exclude)
        candidates = [
            s for s in self.signals()
            if s.routable() and s.replica_id not in excluded
        ]
        if not candidates:
            raise CellOverloaded(
                "no routable replica to migrate the session to"
            )
        # Tier preference (disaggregated cells): a migrated session's
        # next turns are decode traffic — parking its KV on a prefill-
        # tier replica guarantees a second move. Colocated cells are
        # all-"mixed", so the extra sort key is a constant there.
        return min(
            candidates,
            key=lambda s: (
                s.tier == "prefill", s.mesh_rung > 0, s.queue_frac,
                s.replica_id,
            ),
        ).replica_id

    async def migrate_session(
        self, session_id: str, target_id: Optional[str] = None
    ) -> Dict[str, Any]:
        """Move a session's KV lineage (and its affinity pin) to another
        replica via the host tier's transfer format. Safe to call on a
        backend without the KV tier — only the pin moves and the target
        re-prefills (correct, just slower)."""
        src_id = self.sessions.get(session_id)
        if src_id is None:
            raise ValueError(f"unknown session {session_id!r}")
        if target_id is None:
            target_id = self._pick_target(exclude=[src_id])
        if target_id == src_id:
            raise ValueError("migration target is the session's owner")
        src = self.replicas[src_id]
        dst = self.replicas[target_id]
        t0 = time.perf_counter()
        loop = asyncio.get_running_loop()
        export = None
        exporter = getattr(src.handler.backend, "export_session_kv", None)
        if callable(exporter):
            # Blocking device→host gathers: off the event loop.
            export = await loop.run_in_executor(None, exporter, session_id)
        accepted = 0
        tokens = 0
        rejected = 0
        n_entries = len(export["entries"]) if export else 0
        if export:
            # The spill format is the transfer format, and the WIRE form
            # is its canonical frame: round-trip every migration through
            # it (even in-process) so the integrity framing — per-entry
            # header+CRC sealed at export, top-level frame version — is
            # exercised on the path that matters, and so the
            # ``cell.migrate.corrupt`` chaos point has a real payload to
            # rot. A corrupted or version-drifted frame rejects cleanly
            # at import (counted, dropped, session re-prefills on the
            # target) — never lands as silent wrong KV.
            wire = session_kv_to_wire(export)
            if global_injector.fire("cell.migrate.corrupt"):
                corrupt_wire_payload(wire)
            try:
                export = session_kv_from_wire(wire)
            except ValueError as exc:
                self._log.warning(
                    "migration frame for session %s rejected: %s",
                    session_id, exc,
                )
                export = None
                rejected = n_entries
                global_metrics.inc(
                    "engine.kvcache.integrity_failures", n_entries
                )
        if export:
            importer = getattr(dst.handler.backend, "import_session_kv", None)
            if callable(importer):
                landed = await loop.run_in_executor(None, importer, export)
                accepted = int(landed.get("accepted", 0))
                # Only KV that actually LANDED on the target counts as
                # migrated — budget-rejected entries stay source-side
                # copies and will re-prefill, and the metric must not
                # claim otherwise.
                tokens = int(landed.get("tokens", 0))
                rejected = int(landed.get("rejected", 0))
        self.sessions[session_id] = target_id
        wall_ms = (time.perf_counter() - t0) * 1e3
        global_metrics.inc("cell.migrations")
        global_metrics.inc("cell.migrated_entries", accepted)
        global_metrics.inc("cell.migrated_tokens", tokens)
        if rejected:
            global_metrics.inc("cell.migrate_rejected", rejected)
        global_metrics.observe("cell.migration_ms", wall_ms)
        self._log.info(
            "migrated session %s: %s -> %s (%d/%d entries, %d rejected, "
            "%d tokens, %.1f ms)",
            session_id, src_id, target_id, accepted, n_entries, rejected,
            tokens, wall_ms,
        )
        return {
            "session_id": session_id,
            "from": src_id,
            "to": target_id,
            "entries": n_entries,
            "accepted": accepted,
            "rejected": rejected,
            "tokens": tokens,
            "migration_ms": round(wall_ms, 3),
        }

    async def rebalance_degraded(self) -> Dict[str, Any]:
        """Migrate pinned sessions OFF replicas serving on a degraded
        mesh rung, onto intact siblings — the second half of the
        drain-then-restore runbook (degrade → rebalance → rebuild the
        replica at full mesh → sessions migrate back on the next
        rebalance). No-op when nothing is degraded or no full-mesh
        routable sibling exists (migrating between two degraded
        replicas helps nobody)."""
        sigs = {s.replica_id: s for s in self.signals()}
        degraded = sorted(
            rid for rid, s in sigs.items() if s.mesh_rung > 0
        )
        intact = [
            rid for rid, s in sigs.items()
            if s.mesh_rung == 0 and s.routable()
        ]
        moved: List[Dict[str, Any]] = []
        if degraded and intact:
            for sid, owner in list(self.sessions.items()):
                if owner not in degraded:
                    continue
                try:
                    moved.append(await self.migrate_session(sid))
                except Exception as exc:  # noqa: BLE001 — keep sweeping
                    self._log.warning(
                        "session %s could not rebalance off degraded "
                        "replica %s: %s", sid, owner, exc,
                    )
        self._refresh_gauges()
        return {
            "degraded": degraded,
            "moved": len(moved),
            "migrations": moved,
        }

    async def drain(
        self, replica_id: str, grace_s: float = 5.0,
    ) -> Dict[str, Any]:
        """Zero-downtime replica drain: stop routing to it immediately,
        migrate its pinned sessions, give in-flight work ``grace_s`` to
        finish, then cancel the stragglers — the cell's execute loop
        re-admits each cancelled unary request on a sibling (snapshot +
        re-admit at request granularity). The replica stays registered
        (and stopped-routable) until ``undrain`` or ``remove_replica``."""
        rep = self.replicas[replica_id]
        t0 = time.perf_counter()
        rep.draining = True
        self._refresh_gauges()
        migrated = []
        others = [r for r in self.replicas if r != replica_id]
        if others:
            for sid, owner in list(self.sessions.items()):
                if owner != replica_id:
                    continue
                try:
                    migrated.append(await self.migrate_session(sid))
                except Exception as exc:  # noqa: BLE001 — drain proceeds
                    # No routable target / export race: drop the pin so
                    # the session's next turn routes fresh (it
                    # re-prefills — correct, just slower) instead of
                    # sticking to a draining replica.
                    self.sessions.pop(sid, None)
                    self._log.warning(
                        "session %s could not migrate during drain of "
                        "%s: %s", sid, replica_id, exc,
                    )
        deadline = time.monotonic() + max(grace_s, 0.0)
        while rep.inflight and time.monotonic() < deadline:
            await asyncio.sleep(0.01)
        readmitted = 0
        for task in list(rep._calls):
            if not task.done():
                # Mark BEFORE cancelling: the execute loop re-admits
                # exactly the tasks the drain cancelled.
                rep._drain_cancelled.add(task)
                task.cancel()
                readmitted += 1
        # Let the re-admissions detach before reporting — bounded: a
        # straggler stuck in a non-cancellable section must not wedge
        # the drain (it finishes or fails on its own; routing to this
        # replica is already off either way).
        cancel_deadline = time.monotonic() + 30.0
        while rep.inflight and time.monotonic() < cancel_deadline:
            await asyncio.sleep(0.01)
        self.router.table.forget_replica(replica_id)
        wall = time.perf_counter() - t0
        global_metrics.inc("cell.drains")
        global_metrics.observe("cell.drain_s", wall)
        self._refresh_gauges()
        self._log.info(
            "drained %s in %.2fs (%d sessions migrated, %d re-admitted)",
            replica_id, wall, len(migrated), readmitted,
        )
        return {
            "replica_id": replica_id,
            "drain_s": round(wall, 3),
            "migrated_sessions": len(migrated),
            "migrations": migrated,
            "readmitted": readmitted,
        }

    def undrain(self, replica_id: str) -> None:
        self.replicas[replica_id].draining = False
        self._refresh_gauges()

    async def remove_replica(self, replica_id: str) -> Dict[str, Any]:
        """Drain then detach and stop a replica (rolling rebuild)."""
        report = await self.drain(replica_id)
        rep = self.replicas.pop(replica_id)
        await rep.handler.stop()
        self._refresh_gauges()
        return report

    # ------------------------------------------------------------------ #
    # Aggregated health / SLO / metrics surfaces
    # ------------------------------------------------------------------ #

    def health_snapshot(self) -> Dict[str, Any]:
        """The cell ``/healthz`` shape: ok while at least one replica is
        routable; per-replica verdicts attached so an operator sees
        WHICH replica grounded."""
        sigs = self.signals()
        routable = [s for s in sigs if s.routable()]
        # PR 8 503 contract: a grounded cell still hints when to come
        # back (the largest retry_after across stalled engine sources;
        # breakers' own recovery_timeout is the same order).
        health = global_engine_health.snapshot()
        return {
            "ok": bool(routable),
            "replicas": len(sigs),
            "routable": len(routable),
            "retry_after": health.get("retry_after", 0.0),
            "draining": sorted(
                s.replica_id for s in sigs if s.draining
            ),
            "stalled": sorted(
                s.replica_id for s in sigs if not s.healthy
            ),
            "tiers": {s.replica_id: s.tier for s in sigs},
            "per_replica": {s.replica_id: s.to_payload() for s in sigs},
        }

    def slo_snapshot(self) -> Dict[str, Any]:
        """The cell ``/slo.json`` shape: per-class aggregate (request-
        weighted attainment/burn, worst-replica p99) plus each replica's
        own tracker snapshot."""
        per: Dict[str, Any] = {
            rid: rep.slo.snapshot() for rid, rep in self.replicas.items()
        }
        agg: Dict[str, Any] = {}
        for cls in sorted(self._classes):
            entries = [
                snap[cls] for snap in per.values() if cls in snap
            ]
            if not entries:
                continue
            requests = sum(e["requests"] for e in entries)
            missed = sum(e["missed"] for e in entries)
            windows = sum(e["window"] for e in entries)
            # No traffic = no misses: an idle cell reports attainment
            # 1.0 / burn 0.0, matching the single-engine surface (a
            # zero-filled aggregate would fire attainment alerts on
            # every fresh boot).
            agg[cls] = {
                "requests": requests,
                "missed": missed,
                "attainment": round(sum(
                    e["attainment"] * e["window"] for e in entries
                ) / windows, 4) if windows else 1.0,
                "burn_rate": round(sum(
                    e["burn_rate"] * e["window"] for e in entries
                ) / windows, 4) if windows else 0.0,
                "ttft_p99_s": max(
                    (e["ttft_p99_s"] for e in entries
                     if e.get("ttft_p99_s") is not None), default=None,
                ),
                "e2e_p99_s": max(
                    (e["e2e_p99_s"] for e in entries
                     if e.get("e2e_p99_s") is not None), default=None,
                ),
                "targets": entries[0]["targets"],
            }
        return {"aggregate": True, "classes": agg, "replicas": per}

    def get_metrics(self) -> Dict[str, Any]:
        self._refresh_gauges()
        cell = {
            name.split("cell.", 1)[1]: global_metrics.get(name)
            for name in (
                "cell.affinity_lookups", "cell.affinity_hits",
                "cell.affinity_hit_rate", "cell.rerouted",
                "cell.migrations", "cell.migrated_tokens",
                "cell.migrate_rejected", "cell.degraded_replicas",
                "cell.drains", "cell.handoffs", "cell.handoff_fallbacks",
                "cell.handoff_rejected", "cell.handoff_tokens",
                "cell.tier.bypass", "cell.tier.prefill_routed",
                "cell.tier.decode_routed",
            )
        }
        for cls in sorted(self._classes):
            cell[f"routed.{cls}"] = global_metrics.get(f"cell.routed.{cls}")
            cell[f"shed.{cls}"] = global_metrics.get(f"cell.shed.{cls}")
        return {
            "cell": cell,
            "sessions": len(self.sessions),
            "replicas": {
                rid: rep.handler.get_metrics()
                for rid, rep in self.replicas.items()
            },
        }


# --------------------------------------------------------------------- #
# Wire form of the transfer format (control-plane ready)
# --------------------------------------------------------------------- #

def session_kv_to_wire(export: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe form of ``export_session_kv``'s record: arrays as
    base64 + dtype + shape — the shape a control-plane frame can carry
    to a remote worker's ``import_session_kv``. The integrity frame
    rides along verbatim: the top-level ``v`` (frame version) gates
    interpretation at ``session_kv_from_wire``, and each entry's sealed
    ``header``/``crc`` (from export) gate the bytes at import — a
    flipped bit anywhere between the two replicas rejects cleanly."""
    def pack(a: np.ndarray) -> Dict[str, Any]:
        a = np.ascontiguousarray(a)
        return {
            "dtype": str(a.dtype),
            "shape": list(a.shape),
            "data": base64.b64encode(a.tobytes()).decode("ascii"),
        }

    return {
        "v": KV_FRAME_VERSION,
        "session_id": export["session_id"],
        "ids": list(export["ids"]),
        "entries": [
            {
                "key": list(e["key"]),
                "tokens": e["tokens"], "rows": e["rows"],
                "meta": e["meta"], "kind": e["kind"],
                "header": e.get("header"), "crc": e.get("crc"),
                "k": pack(e["k"]), "v": pack(e["v"]),
            }
            for e in export["entries"]
        ],
    }


def session_kv_from_wire(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of :func:`session_kv_to_wire`. Raises ``ValueError`` on
    an unknown frame version — a replica on a different wire format
    must reject the whole payload before interpreting a byte (the
    per-entry header/crc checks at ``import_session`` then catch
    rot/drift inside a well-versioned frame)."""
    v = payload.get("v", KV_FRAME_VERSION)
    if v != KV_FRAME_VERSION:
        raise ValueError(
            f"unknown KV wire frame version {v!r} "
            f"(expected {KV_FRAME_VERSION})"
        )

    def unpack(p: Dict[str, Any]) -> np.ndarray:
        return np.frombuffer(
            base64.b64decode(p["data"]), dtype=np.dtype(p["dtype"])
        ).reshape(p["shape"])

    return {
        "session_id": payload["session_id"],
        "ids": list(payload["ids"]),
        "entries": [
            {
                "key": list(e["key"]),
                "tokens": e["tokens"], "rows": e["rows"],
                "meta": e["meta"], "kind": e["kind"],
                "header": e.get("header"), "crc": e.get("crc"),
                "k": unpack(e["k"]), "v": unpack(e["v"]),
            }
            for e in payload["entries"]
        ],
    }


def corrupt_wire_payload(wire: Dict[str, Any]) -> bool:
    """Chaos helper for ``cell.migrate.corrupt``: flip one byte of the
    first non-empty packed array IN the wire frame (after its CRC was
    sealed at export) — the canonical 'frame rotted in transit'
    injection. Returns True when a byte was flipped."""
    for e in wire.get("entries", ()):
        for part in ("k", "v"):
            raw = bytearray(base64.b64decode(e[part]["data"]))
            if not raw:
                continue
            raw[0] ^= 0xFF
            e[part]["data"] = base64.b64encode(bytes(raw)).decode("ascii")
            return True
    return False


__all__ = [
    "CellReplica",
    "ServingCell",
    "corrupt_wire_payload",
    "parse_disagg_spec",
    "session_kv_from_wire",
    "session_kv_to_wire",
]
