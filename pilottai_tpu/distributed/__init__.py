"""Cross-host serving & orchestration plane.

Two layers live here:

* **Control plane** (SURVEY §2.14): ``ServeEndpoint`` attaches a TCP
  listener to a :class:`~pilottai_tpu.serve.Serve`, ``AgentWorker``
  hosts real agents in other processes/hosts (each with its own TPU
  engine), and :class:`RemoteAgent` proxies make remote agents
  first-class citizens of routing, fault tolerance and retry. Worker
  heartbeats carry the replica routing signals (SLO burn, degrade
  rung, queue depth) so remote engines are routable by the same policy
  as in-process ones.
* **Serving cell** (ISSUE 11 / ROADMAP item 2): :class:`ServingCell`
  fronts N engine replicas with a KV-affinity router
  (:class:`ReplicaRouter` over a radix :class:`RoutingTable`),
  SLO-aware cell-boundary shedding, cross-replica session migration in
  the host tier's transfer format, and zero-downtime replica drain.
"""

from pilottai_tpu.distributed.cell import (
    CellReplica,
    ServingCell,
    parse_disagg_spec,
    session_kv_from_wire,
    session_kv_to_wire,
)
from pilottai_tpu.distributed.control_plane import (
    AgentWorker,
    FrameAuth,
    RemoteAgent,
    ServeEndpoint,
)
from pilottai_tpu.distributed.router import (
    CellOverloaded,
    ReplicaRouter,
    ReplicaSignals,
    RoutingTable,
    route_key,
)

__all__ = [
    "AgentWorker",
    "CellOverloaded",
    "CellReplica",
    "FrameAuth",
    "RemoteAgent",
    "ReplicaRouter",
    "ReplicaSignals",
    "RoutingTable",
    "ServeEndpoint",
    "ServingCell",
    "parse_disagg_spec",
    "route_key",
    "session_kv_from_wire",
    "session_kv_to_wire",
]
