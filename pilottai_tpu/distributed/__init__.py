"""Cross-host orchestrator↔agent control plane (SURVEY §2.14).

The reference declared networking intent it never built (websockets dep,
``pilott/pyproject.toml:19``; dead websocket config fields,
``pilott/core/config.py:153-156``). Here it exists: ``ServeEndpoint``
attaches a TCP listener to a :class:`~pilottai_tpu.serve.Serve`,
``AgentWorker`` hosts real agents in other processes/hosts (each with its
own TPU engine), and :class:`RemoteAgent` proxies make remote agents
first-class citizens of routing, fault tolerance and retry.
"""

from pilottai_tpu.distributed.control_plane import (
    AgentWorker,
    FrameAuth,
    RemoteAgent,
    ServeEndpoint,
)

__all__ = ["AgentWorker", "FrameAuth", "RemoteAgent", "ServeEndpoint"]
