"""Orchestrator↔worker control plane over asyncio TCP (SURVEY §2.14).

Topology: ``Serve`` runs on host 0 with a :class:`ServeEndpoint` listener;
each worker host runs an :class:`AgentWorker` hosting real
:class:`~pilottai_tpu.core.agent.BaseAgent`\\ s (backed by that host's own
TPU engine). Workers DIAL the orchestrator and register; per registered
agent the endpoint installs a :class:`RemoteAgent` proxy into
``serve.agents``, so the router scores remote agents exactly like local
ones and ``FaultTolerance`` sees their (heartbeat-fed) liveness.

Wire format: newline-delimited JSON on one persistent connection per
worker. Messages: ``register``/``registered``, ``heartbeat`` (per-agent
status + load stats), ``execute`` (task payload), ``result``. Tasks and
results cross the wire as their pydantic JSON dumps — at-least-once
semantics: a worker death mid-execution fails the proxy's pending futures
with an unsuccessful :class:`TaskResult`, which flows into Serve's normal
retry path and re-routes to a healthy agent; Serve's journal covers
orchestrator death (``checkpoint/journal.py``).

Trust model (docs/SERVING.md "Security"): the listener is meant for a
private interconnect (TPU-pod DCN / VPC). Two layers, both optional:
``token`` rejects accidental cross-talk (NOT cryptographic); ``secret``
enables HMAC-SHA256 frame signing with timestamp + nonce replay
rejection — authenticity and integrity, but NOT confidentiality (frames
travel in cleartext; wrap the link in TLS/WireGuard when the network is
not trusted). Execution is at-least-once; workers dedupe re-delivered
tasks by id (a cached successful result is returned instead of
re-running side-effecting tools — see AgentWorker._execute).

Reference intent with no implementation behind it:
``pilott/pyproject.toml:19`` (websockets dep),
``pilott/core/config.py:153-156`` (websocket fields nothing reads).
"""

from __future__ import annotations

import asyncio
import hashlib
import hmac as _hmac
import json
import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, List, Optional

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.core.task import Task, TaskResult
from pilottai_tpu.obs import global_slo
from pilottai_tpu.reliability import global_engine_health
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics

_MAX_LINE = 16 * 1024 * 1024  # one message; tasks carry prompts, not tensors


class RegistrationRejected(ConnectionError):
    """The orchestrator refused this worker (bad token / malformed
    register) — permanent; reconnecting with the same credentials would
    hammer the endpoint forever."""


class FrameAuth:
    """HMAC-SHA256 frame signing for the control plane.

    Each outgoing frame gains ``_ts`` (sender clock), ``_nonce`` and
    ``_sig`` = HMAC(secret, canonical-json of the frame minus ``_sig``).
    Verification rejects bad signatures, frames older than ``max_skew``
    seconds, and replayed nonces (bounded memory). This authenticates
    the peer and protects integrity; it does NOT encrypt — put TLS or a
    WireGuard tunnel underneath when the wire itself is untrusted."""

    def __init__(self, secret: str, max_skew: float = 60.0) -> None:
        self._key = secret.encode()
        self.max_skew = max_skew
        # nonce -> arrival time, insertion-ordered. Eviction is by AGE:
        # every nonce is remembered for the full max_skew window (a
        # count-capped set could roll a nonce out while its frame's
        # timestamp was still valid, re-opening replay — review
        # finding). Memory is bounded by frame rate x max_skew.
        self._seen: "OrderedDict[str, float]" = OrderedDict()

    def _mac(self, msg: Dict[str, Any]) -> str:
        payload = json.dumps(
            msg, default=str, sort_keys=True, separators=(",", ":")
        ).encode()
        return _hmac.new(self._key, payload, hashlib.sha256).hexdigest()

    def sign(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        out = dict(msg)
        out["_ts"] = time.time()
        out["_nonce"] = uuid.uuid4().hex
        out["_sig"] = self._mac(out)
        return out

    def verify(self, msg: Dict[str, Any]) -> Dict[str, Any]:
        sig = msg.pop("_sig", None)
        if sig is None or not _hmac.compare_digest(sig, self._mac(msg)):
            raise ConnectionError("control-plane frame failed HMAC check")
        ts = float(msg.pop("_ts", 0.0))
        nonce = str(msg.pop("_nonce", ""))
        if abs(time.time() - ts) > self.max_skew:
            raise ConnectionError("control-plane frame outside clock skew")
        now = time.time()
        while self._seen:
            _, t0 = next(iter(self._seen.items()))
            if now - t0 <= self.max_skew:
                break
            self._seen.popitem(last=False)
        if not nonce or nonce in self._seen:
            raise ConnectionError("control-plane frame replayed")
        self._seen[nonce] = now
        return msg


async def _send(
    writer: asyncio.StreamWriter, msg: Dict[str, Any],
    auth: Optional[FrameAuth] = None,
) -> None:
    if auth is not None:
        msg = auth.sign(msg)
    data = json.dumps(msg, default=str).encode() + b"\n"
    if len(data) > _MAX_LINE:
        # The peer's readline would raise at its limit and tear the
        # session down; failing the SEND keeps the error with the
        # oversized message instead of poisoning the connection.
        raise ValueError(
            f"control-plane message of {len(data)} bytes exceeds the "
            f"{_MAX_LINE}-byte frame limit"
        )
    writer.write(data)
    await writer.drain()


async def _recv(
    reader: asyncio.StreamReader,
    auth: Optional[FrameAuth] = None,
) -> Dict[str, Any]:
    line = await reader.readline()
    if not line:
        raise ConnectionError("peer closed")
    msg = json.loads(line)
    if auth is not None:
        msg = auth.verify(msg)
    return msg


class RemoteAgent:
    """Orchestrator-side proxy for an agent hosted by an AgentWorker.

    Implements the surface Serve/TaskRouter/FaultTolerance actually read
    from :class:`BaseAgent`: identity, ``config`` (role/specializations/
    capabilities), availability ``status``, load stats, suitability
    scoring, ``execute_task``, heartbeat age. Load stats arrive with
    worker heartbeats instead of being computed locally.
    """

    is_remote = True

    def __init__(self, endpoint: "ServeEndpoint", worker_id: str,
                 desc: Dict[str, Any]) -> None:
        self._endpoint = endpoint
        self.worker_id = worker_id
        self.id = desc["agent_id"]
        self.config = AgentConfig(
            role=desc.get("role", "worker"),
            specializations=list(desc.get("specializations", [])),
            required_capabilities=list(desc.get("required_capabilities", [])),
        )
        self.role = self.config.role
        self.status = AgentStatus.IDLE
        self.dependency_resolver = None  # Serve.start assigns; unused here
        self._stats: Dict[str, float] = {
            "queue_utilization": 0.0, "load": 0.0, "success_rate": 1.0,
        }
        self._inflight = 0
        self._last_heartbeat = time.time()
        # Replica routing signals (ISSUE 11): the worker's heartbeat
        # ships its host's SLO burn / degrade / queue / health snapshot
        # so a cell-style router can rank this worker's engine by the
        # same policy as an in-process replica.
        self.signals: Dict[str, Any] = {}
        self._log = get_logger(
            "remote_agent", agent_id=self.id[:8], role=self.role
        )

    # ----- surface read by TaskRouter / Serve / FaultTolerance -------- #

    @property
    def queue_utilization(self) -> float:
        # Availability gating (router load_threshold) stays purely
        # heartbeat-driven: folding local in-flight here would EXCLUDE a
        # proxy with capacity from routing entirely ("no available
        # agent" hard failures on bursts) instead of just deprioritizing
        # it.
        return float(self._stats.get("queue_utilization", 0.0))

    @property
    def load(self) -> float:
        # Score penalty: heartbeat load lags by an interval, so fold in
        # the requests THIS orchestrator already routed — known load
        # right now. Affects ranking only, never availability.
        inflight = min(1.0, self._inflight / 4.0)
        return max(float(self._stats.get("load", 0.0)), inflight)

    @property
    def success_rate(self) -> float:
        return float(self._stats.get("success_rate", 1.0))

    def evaluate_task_suitability(self, task: Task) -> float:
        """MIRRORS ``BaseAgent.evaluate_task_suitability`` term for term
        (minus the tools set, unknowable remotely) so TaskRouter ranks
        local and remote agents on one scale — a divergent formula
        systematically biased routing in mixed deployments (advisor r3).
        Reference shape: ``pilott/core/agent.py:549-575``."""
        if not self.status.is_available:
            return 0.0
        score = 0.7
        if task.type in self.config.specializations:
            score += 0.2
        caps = set(self.config.required_capabilities)
        needed = set(task.required_capabilities)
        if needed:
            if not needed.issubset(caps):
                return 0.1
            score += 0.1
        score -= 0.3 * self.load
        return max(0.0, min(1.0, score))

    def heartbeat(self) -> float:
        return self._last_heartbeat

    def send_heartbeat(self) -> float:
        # Liveness is owned by the WORKER's heartbeats; a local poke must
        # not mask a dead connection, so this is a read, not a write.
        return self._last_heartbeat

    # FaultTolerance replacement hooks: a remote agent's queue lives with
    # the worker, so there is nothing to detach locally — its in-flight
    # futures already fail (and re-route) on connection loss.
    _worker_task = None

    def remove_task(self, task_id: str) -> Optional[Task]:
        return None

    async def start(self) -> None:
        if self.status == AgentStatus.CREATED:
            self.status = AgentStatus.IDLE

    async def stop(self) -> None:
        self.status = AgentStatus.STOPPED

    async def reset(self) -> None:
        """FaultTolerance's in-place recovery hook: re-arm the proxy; the
        next worker heartbeat restores the true remote status."""
        if self._endpoint._writers.get(self.worker_id) is not None:
            self.status = AgentStatus.IDLE

    def queued_tasks(self) -> List[Task]:
        return []  # the remote queue lives with the worker's real agent

    async def add_task(self, task: Task) -> None:
        """Queue-style submission: run remotely in the background (the
        balancer/scaler move work through this entry point)."""
        t = asyncio.get_running_loop().create_task(self.execute_task(task))
        # The loop holds only weak refs to tasks — keep one until done.
        self._bg = getattr(self, "_bg", set())
        self._bg.add(t)
        t.add_done_callback(self._bg.discard)

    async def execute_task(self, task: Task) -> TaskResult:
        """Mirror BaseAgent.execute_task's local bookkeeping (started/
        completed marks, BUSY while in flight) around the remote call —
        the worker's agent marks ITS copy, not the orchestrator's."""
        task.mark_started(agent_id=self.id)
        if self.status == AgentStatus.IDLE:
            self.status = AgentStatus.BUSY
        self._inflight += 1
        try:
            result = await self._endpoint.execute(self, task)
        finally:
            self._inflight -= 1
            if self._inflight == 0 and self.status == AgentStatus.BUSY:
                self.status = AgentStatus.IDLE
        if result.success:
            task.mark_completed(result)
        else:
            task.mark_failed(result.error or "remote execution failed", result)
        return result

    @property
    def current_tasks(self) -> Dict[str, Task]:
        return {}  # in-flight work tracked on the worker side

    def get_health(self) -> Dict[str, Any]:
        return {
            "agent_id": self.id,
            "status": self.status.value,
            "error_count": 0,
            "last_heartbeat": self._last_heartbeat,
            "queue_utilization": self.queue_utilization,
            "current_tasks": self._inflight,
        }

    def routing_signals(self) -> Dict[str, Any]:
        """The heartbeat-fed signals in ``ReplicaSignals.from_payload``
        shape — remote engines rank on the same scale as in-process
        cell replicas (``distributed/router.py``)."""
        eng = self.signals.get("engine") or {}
        slo = self.signals.get("slo") or {}
        return {
            "replica_id": self.id,
            "queue_depth": int(eng.get("queue_depth", 0) or 0),
            "queue_frac": float(eng.get("queue_frac", 0.0) or 0.0),
            "degrade_level": int(eng.get("degrade_level", 0) or 0),
            "healthy": bool(eng.get("healthy", True)),
            "mesh_rung": int(eng.get("mesh_rung", 0) or 0),
            "burn_rate": {
                cls: float((v or {}).get("burn_rate", 0.0))
                for cls, v in slo.items()
            },
        }

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "agent_id": self.id,
            "role": self.role,
            "status": self.status.value,
            "remote": True,
            "worker_id": self.worker_id,
            **self._stats,
        }


class ServeEndpoint:
    """TCP listener that attaches remote workers to a running Serve."""

    def __init__(self, serve, host: str = "127.0.0.1", port: int = 0,
                 token: Optional[str] = None,
                 secret: Optional[str] = None) -> None:
        self.serve = serve
        self.host = host
        self.port = port
        self.token = token
        # HMAC frame signing (FrameAuth): authenticity + integrity +
        # replay rejection when both sides share ``secret``.
        self._auth = FrameAuth(secret) if secret else None
        self._server: Optional[asyncio.base_events.Server] = None
        self._writers: Dict[str, asyncio.StreamWriter] = {}
        self._proxies: Dict[str, List[RemoteAgent]] = {}
        self._pending: Dict[str, asyncio.Future] = {}
        #: worker_id -> latest heartbeat routing-signal snapshot (SLO
        #: burn per class, degrade level, queue depth, engine health).
        self.worker_signals: Dict[str, Dict[str, Any]] = {}
        self._log = get_logger("serve_endpoint")

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port, limit=_MAX_LINE
        )
        self.port = self._server.sockets[0].getsockname()[1]
        self._log.info("control plane listening on %s:%d", self.host, self.port)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # Drop workers BEFORE wait_closed(): on Python >= 3.12.1
        # wait_closed blocks until every connection handler exits, and
        # the handlers sit in _recv on their persistent connections —
        # waiting first deadlocks shutdown with any live worker.
        for worker_id in list(self._writers):
            await self._drop_worker(worker_id, "endpoint stopped")
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    # ------------------------------------------------------------------ #

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        worker_id = None
        try:
            msg = await _recv(reader, self._auth)
            if msg.get("type") != "register" or (
                self.token is not None and msg.get("token") != self.token
            ):
                await _send(writer, {"type": "error", "error": "bad register"},
                            self._auth)
                writer.close()
                return
            worker_id = msg["worker_id"]
            self._writers[worker_id] = writer
            proxies = []
            for desc in msg.get("agents", []):
                proxy = RemoteAgent(self, worker_id, desc)
                # Re-registration after a connection blip: the dead proxy
                # from the previous session still sits in serve.agents
                # (kept ERROR so FaultTolerance can observe the outage) —
                # replace it, or add_agent's duplicate-id guard would kill
                # this handler and strand the reconnecting worker forever.
                stale = self.serve.agents.get(proxy.id)
                if isinstance(stale, RemoteAgent):
                    await self.serve.remove_agent(proxy.id)
                self.serve.add_agent(proxy)
                proxies.append(proxy)
            self._proxies[worker_id] = proxies
            await _send(writer, {"type": "registered"}, self._auth)
            self._log.info(
                "worker %s registered %d agents", worker_id[:8], len(proxies)
            )
            global_metrics.inc("control_plane.workers_registered")
            while True:
                msg = await _recv(reader, self._auth)
                kind = msg.get("type")
                if kind == "heartbeat":
                    now = time.time()
                    stats = msg.get("agents", {})
                    signals = msg.get("signals")
                    if isinstance(signals, dict):
                        self.worker_signals[worker_id] = signals
                    for proxy in proxies:
                        proxy._last_heartbeat = now
                        if isinstance(signals, dict):
                            proxy.signals = signals
                        s = stats.get(proxy.id)
                        if s:
                            proxy._stats.update({
                                k: s[k] for k in
                                ("queue_utilization", "load", "success_rate")
                                if k in s
                            })
                            try:
                                proxy.status = AgentStatus(s["status"])
                            except (KeyError, ValueError):
                                pass
                elif kind == "result":
                    fut = self._pending.pop(msg["req_id"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(
                            TaskResult.model_validate(msg["result"])
                        )
                else:
                    self._log.warning("unknown message type %r", kind)
        except (ConnectionError, asyncio.IncompleteReadError,
                ValueError) as exc:
            # ValueError covers json.JSONDecodeError AND the
            # LimitOverrunError-wrapping readline raises on an oversized
            # line — previously uncaught, which killed the handler task
            # silently (advisor r3).
            if worker_id is not None:
                self._log.warning(
                    "worker %s connection lost: %s", worker_id[:8], exc
                )
        finally:
            # Identity check: a silently-partitioned connection can linger
            # in _recv until TCP timeout while the worker re-dials and
            # re-registers; when the dead handler finally errors out it
            # must not tear down the NEW session it no longer owns.
            if worker_id is not None and self._writers.get(worker_id) is writer:
                await self._drop_worker(worker_id, "worker connection lost")
            elif worker_id is None:
                # Never registered (bad token / failed HMAC / garbage):
                # close the transport here or stop()'s wait_closed blocks
                # on the half-open connection forever.
                try:
                    writer.close()
                except Exception:  # noqa: BLE001 — already gone
                    pass

    async def _drop_worker(self, worker_id: str, reason: str) -> None:
        self.worker_signals.pop(worker_id, None)
        writer = self._writers.pop(worker_id, None)
        if writer is not None:
            try:
                writer.close()
            except Exception:  # noqa: BLE001 — already gone
                pass
        for proxy in self._proxies.pop(worker_id, []):
            proxy.status = AgentStatus.ERROR
            # Fail this worker's in-flight work so Serve's retry path
            # re-routes it (at-least-once; BASELINE config #5 story).
            for req_id, fut in list(self._pending.items()):
                if req_id.startswith(proxy.id) and not fut.done():
                    self._pending.pop(req_id, None)
                    fut.set_result(TaskResult(
                        success=False,
                        error=f"remote agent {proxy.id[:8]}: {reason}",
                    ))
        global_metrics.inc("control_plane.workers_dropped")

    async def execute(self, proxy: RemoteAgent, task: Task) -> TaskResult:
        writer = self._writers.get(proxy.worker_id)
        if writer is None:
            return TaskResult(
                success=False,
                error=f"worker {proxy.worker_id[:8]} not connected",
            )
        req_id = f"{proxy.id}:{uuid.uuid4()}"
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        t0 = time.perf_counter()
        try:
            await _send(writer, {
                "type": "execute",
                "req_id": req_id,
                "agent_id": proxy.id,
                "task": task.model_dump(mode="json"),
            }, self._auth)
            result = await asyncio.wait_for(fut, timeout=task.timeout)
        except asyncio.TimeoutError:
            self._pending.pop(req_id, None)
            result = TaskResult(
                success=False,
                error=f"remote execution timed out after {task.timeout}s",
            )
        except ConnectionError as exc:
            self._pending.pop(req_id, None)
            result = TaskResult(success=False, error=f"send failed: {exc}")
        result.execution_time = result.execution_time or (
            time.perf_counter() - t0
        )
        global_metrics.inc("control_plane.remote_executions")
        return result


class AgentWorker:
    """Worker-process side: hosts real agents, serves remote executions.

    The worker owns its agents' full lifecycle (their LLM handlers run on
    THIS host's devices), dials the orchestrator, registers, then
    heartbeats its agents' status/load until stopped. Reconnects with
    backoff if the orchestrator restarts."""

    def __init__(self, host: str, port: int, agents: List[BaseAgent],
                 worker_id: Optional[str] = None,
                 heartbeat_interval: float = 1.0,
                 token: Optional[str] = None,
                 reconnect: bool = True,
                 secret: Optional[str] = None,
                 result_cache: int = 512) -> None:
        self.host = host
        self.port = port
        self.worker_id = worker_id or str(uuid.uuid4())
        self.agents = {a.id: a for a in agents}
        self.heartbeat_interval = heartbeat_interval
        self.token = token
        self.reconnect = reconnect
        self._writer: Optional[asyncio.StreamWriter] = None
        self._stopped = asyncio.Event()
        self._tasks: List[asyncio.Task] = []
        # Strong refs to in-flight executions: the loop's task refs are
        # weak, and stop() must be able to wait for them.
        self._inflight: set = set()
        self._auth = FrameAuth(secret) if secret else None
        # Idempotent re-delivery: at-least-once means a task whose result
        # was lost in transit (or whose endpoint timed out) can be routed
        # here AGAIN after it already ran. Side-effecting tools must not
        # run twice, so successful results are cached by task id and
        # returned verbatim on re-delivery; a concurrently in-flight
        # duplicate awaits the first execution instead of starting a
        # second. Failed attempts are NOT cached — a retry after genuine
        # failure should re-execute.
        self._result_cache_cap = result_cache
        self._results_done: "OrderedDict[str, TaskResult]" = OrderedDict()
        self._results_running: Dict[str, asyncio.Future] = {}
        self._log = get_logger("agent_worker", agent_id=self.worker_id[:8])

    async def start(self) -> None:
        for agent in self.agents.values():
            await agent.start()
        self._tasks.append(asyncio.create_task(self._run()))

    async def stop(self) -> None:
        self._stopped.set()
        if self._inflight:
            # Give running executions a moment to report their results
            # before the agents underneath them stop.
            await asyncio.wait(list(self._inflight), timeout=5.0)
        for t in list(self._inflight) + self._tasks:
            t.cancel()
        await asyncio.gather(
            *self._tasks, *list(self._inflight), return_exceptions=True
        )
        self._tasks.clear()
        self._inflight.clear()
        if self._writer is not None:
            self._writer.close()
        for agent in self.agents.values():
            await agent.stop()

    async def run_until_stopped(self) -> None:
        await self._stopped.wait()

    # ------------------------------------------------------------------ #

    async def _run(self) -> None:
        backoff = 0.5
        while not self._stopped.is_set():
            try:
                await self._session()
            except RegistrationRejected as exc:
                self._log.error("giving up: %s", exc)
                self._stopped.set()
                break
            except (ConnectionError, OSError,
                    asyncio.IncompleteReadError, ValueError) as exc:
                # ValueError: garbage JSON from a crashing orchestrator
                # AND readline's wrapped LimitOverrunError on an
                # oversized line must both mean "reconnect", not a
                # silently dead worker loop (advisor r3).
                self._log.warning("control-plane session ended: %s", exc)
            if not self.reconnect or self._stopped.is_set():
                break
            if getattr(self, "_backoff_reset", False):
                backoff = 0.5
                self._backoff_reset = False
            await asyncio.sleep(backoff)
            backoff = min(backoff * 2, 10.0)

    async def _session(self) -> None:
        reader, writer = await asyncio.open_connection(
            self.host, self.port, limit=_MAX_LINE
        )
        self._writer = writer
        await _send(writer, {
            "type": "register",
            "worker_id": self.worker_id,
            "token": self.token,
            "agents": [
                {
                    "agent_id": a.id,
                    "role": a.config.role,
                    "specializations": a.config.specializations,
                    "required_capabilities": a.config.required_capabilities,
                }
                for a in self.agents.values()
            ],
        }, self._auth)
        ack = await _recv(reader, self._auth)
        if ack.get("type") != "registered":
            raise RegistrationRejected(f"registration rejected: {ack}")
        self._log.info("registered with orchestrator %s:%d", self.host, self.port)
        # Successful registration resets the reconnect backoff here —
        # _session only ever EXITS by raising, so a reset after the call
        # would be dead code and blips would ratchet to max permanently.
        self._backoff_reset = True
        hb = asyncio.create_task(self._heartbeat_loop(writer))
        try:
            while True:
                msg = await _recv(reader, self._auth)
                if msg.get("type") == "execute":
                    t = asyncio.get_running_loop().create_task(
                        self._execute(writer, msg)
                    )
                    self._inflight.add(t)
                    t.add_done_callback(self._inflight.discard)
        finally:
            hb.cancel()
            self._writer = None

    def _routing_signals(self) -> Dict[str, Any]:
        """This host's replica routing signals (ISSUE 11): per-class SLO
        burn rate / attainment, the engine's degrade rung and queue
        depth, and the watchdog health verdict — the same surface an
        in-process cell replica exposes, so the orchestrator side can
        rank remote engines with the identical policy. Reads only
        process-global gauges (cheap; no engine lock)."""
        global_slo.refresh_gauges()
        depth = global_metrics.get("engine.queue_depth")
        limit = global_metrics.get("engine.max_queue_depth")
        return {
            "slo": {
                cls: {
                    "burn_rate": round(
                        global_metrics.get(f"slo.{cls}.burn_rate"), 4
                    ),
                    "attainment": round(
                        global_metrics.get(f"slo.{cls}.attainment"), 4
                    ),
                }
                for cls in global_slo.classes
            },
            "engine": {
                "degrade_level": global_metrics.get("engine.degrade_level"),
                "queue_depth": depth,
                # The router's shed thresholds read queue_frac, so the
                # wire must carry it — a depth alone would parse as
                # frac 0.0 and a saturated remote would rank as empty.
                # Without admission control (no max_queue_depth gauge)
                # the same 64-deep soft norm as the in-process default.
                "queue_frac": round(
                    depth / limit if limit else min(depth / 64.0, 2.0), 4
                ),
                "healthy": global_engine_health.healthy(),
                # Degraded-mesh rung (engine.mesh_plan gauge): remote
                # replicas serving on a survivor sub-mesh must rank
                # below intact peers just like in-process ones do.
                "mesh_rung": int(global_metrics.get("engine.mesh_plan") or 0),
            },
        }

    async def _heartbeat_loop(self, writer: asyncio.StreamWriter) -> None:
        while True:
            stats = {}
            for a in self.agents.values():
                stats[a.id] = {
                    "status": a.status.value,
                    "queue_utilization": a.queue_utilization,
                    "load": a.load,
                    "success_rate": a.success_rate,
                }
            try:
                await _send(writer, {
                    "type": "heartbeat",
                    "worker_id": self.worker_id,
                    "agents": stats,
                    # Replica routing signals ride every heartbeat: the
                    # endpoint keeps the latest per worker, so remote
                    # engines are routable by burn rate / degrade level
                    # exactly like in-process cell replicas.
                    "signals": self._routing_signals(),
                }, self._auth)
            except ConnectionError:
                return
            await asyncio.sleep(self.heartbeat_interval)

    async def _execute(self, writer: asyncio.StreamWriter,
                       msg: Dict[str, Any]) -> None:
        result = await self._execute_idempotent(msg)
        try:
            await _send(writer, {
                "type": "result",
                "req_id": msg["req_id"],
                "result": result.model_dump(mode="json"),
            }, self._auth)
        except ConnectionError:
            self._log.warning(
                "result for %s lost (connection closed)", msg["req_id"][:16]
            )

    async def _execute_idempotent(self, msg: Dict[str, Any]) -> TaskResult:
        """Run the task exactly once per worker even under at-least-once
        delivery: a re-delivered id returns the cached successful result
        (side-effecting tools must not run twice); a duplicate arriving
        while the first copy is still executing awaits it. Failures are
        never cached: a deliberate retry after failure re-executes."""
        task_id = str(msg.get("task", {}).get("id", msg.get("req_id")))
        cached = self._results_done.get(task_id)
        if cached is not None:
            self._results_done.move_to_end(task_id)
            global_metrics.inc("control_plane.deduped_redeliveries")
            self._log.info("re-delivery of %s served from cache", task_id[:8])
            return cached
        running = self._results_running.get(task_id)
        if running is not None:
            global_metrics.inc("control_plane.deduped_redeliveries")
            return await asyncio.shield(running)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._results_running[task_id] = fut
        try:
            try:
                task = Task.model_validate(msg["task"])
                agent = self.agents.get(msg["agent_id"])
                if agent is None:
                    result = TaskResult(
                        success=False,
                        error=f"no agent {msg['agent_id'][:8]} on this worker",
                    )
                else:
                    result = await agent.execute_task(task)
            except Exception as exc:  # noqa: BLE001 — report, don't die
                result = TaskResult(success=False, error=str(exc))
            if result.success:
                self._results_done[task_id] = result
                while len(self._results_done) > self._result_cache_cap:
                    self._results_done.popitem(last=False)
            fut.set_result(result)
            return result
        finally:
            self._results_running.pop(task_id, None)
            if not fut.done():
                fut.set_result(TaskResult(success=False, error="cancelled"))
