"""KV-affinity replica router: the serving cell's placement brain.

The million-user shape (ROADMAP item 2) is many engine replicas behind
one front door. A blind load balancer wastes the two things the
substrate PRs made visible: *KV locality* (PR 9's radix prefix index —
routing a session's next turn to the replica already holding its KV
turns a full re-prefill into a restore or a hot hit) and *SLO state*
(PR 6's per-class burn rate, PR 8's degrade ladder and watchdog). This
module is the policy layer that reads both:

* :class:`RoutingTable` — a cell-level radix table (reusing
  ``engine/kvcache/radix.py``) mapping prompt-prefix byte keys to the
  replica that last served them, bounded LRU, decayed when the owning
  replica evicts the underlying KV (``HostTier.on_evict``) or leaves
  the cell. Lookup returns the replica holding the *longest live*
  prefix — dead/draining replicas' entries are skipped, not returned.
* :class:`ReplicaSignals` — one replica's routable state: queue
  depth/fraction, degrade rung, per-class SLO burn rate, watchdog
  health, breaker state, draining flag. In-process replicas read these
  live; remote workers ship the same dict in their control-plane
  heartbeats (``distributed/control_plane.py``).
* :class:`ReplicaRouter` — scores candidates by (a) prefix/session
  affinity, (b) per-class SLO headroom (1/(1+burn)), (c) queue depth
  and degrade rung, and *sheds at the cell boundary* before any
  replica saturates: batch-class traffic sheds once every candidate is
  past ``batch_shed_frac`` of its queue (or degraded to its own
  shed-batch rung), interactive only when every candidate is full.

Hard exclusions are absolute: a draining, watchdog-stalled,
breaker-open or dead replica never receives new work, whatever its
affinity score (acceptance bar of ISSUE 11).

Import cost: stdlib + utils + the (jax-free) radix tree — control-plane
safe, same constraint as the rest of ``distributed/``.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from pilottai_tpu.engine.kvcache.radix import RadixTree
from pilottai_tpu.utils.logging import get_logger


class CellOverloaded(Exception):
    """Cell-boundary shed: no replica can take this class right now.
    Mapped by callers onto the engine's ``EngineOverloaded`` semantics
    (HTTP 429) — the cell sheds *before* any replica's own queue does."""


@dataclass
class ReplicaSignals:
    """One replica's routable state, normalized so in-process replicas
    and control-plane workers rank on the same scale."""

    replica_id: str
    queue_depth: int = 0
    #: queue_depth / the replica's shed limit; >= 1.0 means its own
    #: admission control would shed interactive traffic.
    queue_frac: float = 0.0
    degrade_level: int = 0
    #: active mesh-ladder rung (parallel/meshplan.py): 0 = full boot
    #: mesh, higher = serving degraded on a surviving sub-mesh after
    #: shard loss. The router down-scores degraded replicas and the
    #: cell prefers migrating sessions off them.
    mesh_rung: int = 0
    #: per-class error-budget burn rate (PR 6); missing classes read 0.
    burn_rate: Dict[str, float] = field(default_factory=dict)
    healthy: bool = True          # watchdog / EngineHealth verdict
    breaker_open: bool = False
    draining: bool = False
    #: disaggregated-serving role (ISSUE 19): "prefill" replicas take
    #: cold long prompts, "decode" replicas take sticky/decode traffic,
    #: "mixed" (the default — and the ONLY value in a colocated cell)
    #: serves both. Control-plane heartbeats carry it so remote workers
    #: are tierable by the same policy as in-process replicas.
    tier: str = "mixed"

    def routable(self) -> bool:
        return self.healthy and not self.draining and not self.breaker_open

    def to_payload(self) -> Dict[str, object]:
        """JSON-safe dict (control-plane heartbeat shape)."""
        return {
            "replica_id": self.replica_id,
            "queue_depth": self.queue_depth,
            "queue_frac": round(self.queue_frac, 4),
            "degrade_level": self.degrade_level,
            "mesh_rung": self.mesh_rung,
            "burn_rate": {k: round(v, 4) for k, v in self.burn_rate.items()},
            "healthy": self.healthy,
            "breaker_open": self.breaker_open,
            "draining": self.draining,
            "tier": self.tier,
        }

    @classmethod
    def from_payload(cls, payload: Dict[str, object]) -> "ReplicaSignals":
        return cls(
            replica_id=str(payload.get("replica_id", "")),
            queue_depth=int(payload.get("queue_depth", 0) or 0),
            queue_frac=float(payload.get("queue_frac", 0.0) or 0.0),
            degrade_level=int(payload.get("degrade_level", 0) or 0),
            mesh_rung=int(payload.get("mesh_rung", 0) or 0),
            burn_rate={
                str(k): float(v)
                for k, v in (payload.get("burn_rate") or {}).items()
            },
            healthy=bool(payload.get("healthy", True)),
            breaker_open=bool(payload.get("breaker_open", False)),
            draining=bool(payload.get("draining", False)),
            tier=str(payload.get("tier", "mixed") or "mixed"),
        )


def route_key(text: str, max_bytes: int = 2048) -> Tuple[int, ...]:
    """The routing table's key for a prompt: its UTF-8 bytes, capped.
    Byte keys are tokenizer-independent (for the byte tokenizer they ARE
    the prompt ids) and prefix-of-text == prefix-of-key, which is the
    only property affinity needs."""
    return tuple(text.encode("utf-8")[:max_bytes])


class RoutingTable:
    """Bounded prefix → replica affinity map over a radix tree.

    ``note`` records that a replica served (and therefore likely caches)
    a prefix; ``lookup`` walks the query once and returns the replica
    holding the longest prefix among replicas the caller considers
    live. Entries decay three ways: LRU past ``capacity``, explicit
    ``forget`` when the owning replica reports the KV evicted
    (``HostTier.on_evict`` → the cell's decay hook), and wholesale
    ``forget_replica`` on drain/death. Thread-safe — the cell routes
    from the event loop while eviction callbacks fire from engine
    threads."""

    def __init__(self, capacity: int = 4096) -> None:
        self.capacity = max(1, int(capacity))
        self._tree = RadixTree()
        # key -> replica_id, LRU-ordered (the tree holds the same
        # payload; this dict is the eviction order + per-key owner).
        self._lru: "OrderedDict[Tuple[int, ...], str]" = OrderedDict()
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._lru)

    def note(self, ids: Sequence[int], replica_id: str) -> None:
        """Record ``replica_id`` as the holder of prefix ``ids``."""
        key = tuple(ids)
        if not key:
            return
        with self._lock:
            self._tree.insert(key, replica_id)
            self._lru[key] = replica_id
            self._lru.move_to_end(key)
            while len(self._lru) > self.capacity:
                old, _ = self._lru.popitem(last=False)
                self._tree.remove(old)

    def forget(self, ids: Sequence[int]) -> None:
        """Decay one entry (replica-side eviction of the backing KV)."""
        key = tuple(ids)
        with self._lock:
            if self._lru.pop(key, None) is not None:
                self._tree.remove(key)

    def forget_owned(self, ids: Sequence[int], replica_id: str) -> None:
        """Ownership-checked decay: forget the entry only when
        ``replica_id`` owns it. The per-replica eviction hook must not
        drop an entry pointing at a DIFFERENT replica whose copy of the
        KV is still live (two replicas caching a shared preamble is the
        normal state, not a conflict)."""
        key = tuple(ids)
        with self._lock:
            if self._lru.get(key) == replica_id:
                del self._lru[key]
                self._tree.remove(key)

    def forget_replica(self, replica_id: str) -> int:
        """Drop every entry owned by ``replica_id`` (drain / death)."""
        with self._lock:
            victims = [
                k for k, rid in self._lru.items() if rid == replica_id
            ]
            for key in victims:
                del self._lru[key]
                self._tree.remove(key)
            return len(victims)

    def lookup(
        self,
        ids: Sequence[int],
        alive: Optional[Sequence[str]] = None,
    ) -> Tuple[Optional[str], int]:
        """``(replica_id, lcp)`` for the longest stored prefix of
        ``ids`` whose owner is in ``alive`` (None = any owner). One
        radix walk collects every payload node on the path; the deepest
        live owner wins — a dead replica's deeper entry must not shadow
        a live replica's shallower one."""
        live = set(alive) if alive is not None else None
        key = tuple(ids)
        with self._lock:
            for node in reversed(self._tree.payload_prefixes(key)):
                if live is None or node.payload in live:
                    self._lru.move_to_end(key[: node.key_len])
                    return node.payload, node.key_len
            return None, 0

    def owners(self) -> Dict[str, int]:
        """replica_id -> entry count (metrics / drain bookkeeping)."""
        with self._lock:
            out: Dict[str, int] = {}
            for rid in self._lru.values():
                out[rid] = out.get(rid, 0) + 1
            return out


class ReplicaRouter:
    """Scoring policy over :class:`ReplicaSignals` + the routing table.

    ``pick`` never returns an unroutable replica; it raises
    :class:`CellOverloaded` when the class must shed at the cell
    boundary. Weights are deliberately simple and documented in
    docs/SERVING.md — the router's job is to be *predictable* under
    incident, not optimal in steady state."""

    def __init__(
        self,
        table: Optional[RoutingTable] = None,
        *,
        affinity_weight: float = 1.0,
        slo_weight: float = 1.0,
        queue_weight: float = 1.0,
        degrade_weight: float = 0.5,
        #: penalty per mesh-ladder rung: a replica serving degraded on a
        #: surviving sub-mesh keeps taking traffic (it's correct, just
        #: slower), but loses ties against full-mesh peers.
        mesh_weight: float = 0.5,
        batch_shed_frac: float = 0.75,
        #: degrade rung at or past which a replica sheds batch traffic
        #: itself (reliability/degrade.py SHED_BATCH) — the router skips
        #: it for batch-class work instead of bouncing off its 429.
        batch_shed_level: int = 4,
        #: sticky affinity wins outright unless the owner is more than
        #: this much queue_frac above the least-loaded candidate. Before
        #: this gate existed, a single extra in-flight request
        #: (1/soft_inflight = 0.125 queue_frac at the default 8) was
        #: enough for the queue term to steal a session from the replica
        #: holding its KV — BENCH_r07's CELL affinity_hit_rate of 0.29.
        affinity_tie_margin: float = 0.25,
    ) -> None:
        self.table = table if table is not None else RoutingTable()
        self.affinity_weight = affinity_weight
        self.slo_weight = slo_weight
        self.queue_weight = queue_weight
        self.degrade_weight = degrade_weight
        self.mesh_weight = mesh_weight
        self.batch_shed_frac = batch_shed_frac
        self.batch_shed_level = batch_shed_level
        self.affinity_tie_margin = affinity_tie_margin
        self._rr = 0  # tiebreak rotation
        self._log = get_logger("cell.router")

    # ------------------------------------------------------------------ #

    def _class_candidates(
        self, signals: List[ReplicaSignals], slo_class: str
    ) -> List[ReplicaSignals]:
        """Routable replicas that may still admit ``slo_class`` work —
        the per-class cell-boundary shed policy. Mirrors the engine's
        own ``_shed_reason`` thresholds so the cell sheds *first*:
        batch-class work stops at ``batch_shed_frac`` of a replica's
        queue (or once it degraded to its shed-batch rung); interactive
        only at a full queue."""
        out = []
        for s in signals:
            if not s.routable():
                continue
            if slo_class == "batch":
                if s.queue_frac >= self.batch_shed_frac:
                    continue
                if s.degrade_level >= self.batch_shed_level:
                    continue
            elif s.queue_frac >= 1.0:
                continue
            out.append(s)
        return out

    def score(
        self,
        s: ReplicaSignals,
        slo_class: str,
        affinity_tokens: int,
        key_len: int,
    ) -> float:
        """One replica's desirability for one request. Affinity is the
        matched-prefix fraction of the key; SLO headroom shrinks as the
        class's error budget burns; queue and degrade subtract."""
        affinity = affinity_tokens / max(key_len, 1)
        burn = s.burn_rate.get(slo_class, 0.0)
        headroom = 1.0 / (1.0 + max(burn, 0.0))
        return (
            self.affinity_weight * affinity
            + self.slo_weight * headroom
            - self.queue_weight * min(s.queue_frac, 2.0)
            - self.degrade_weight * s.degrade_level
            - self.mesh_weight * s.mesh_rung
        )

    def pick(
        self,
        key: Sequence[int],
        signals: List[ReplicaSignals],
        *,
        slo_class: str = "interactive",
        pinned: Optional[str] = None,
        exclude: Optional[Sequence[str]] = None,
        tier: Optional[str] = None,
    ) -> Tuple[str, int]:
        """Choose a replica for a request with routing key ``key``.

        Returns ``(replica_id, affinity_lcp)``. ``pinned`` (a session's
        current owner) wins outright while routable and class-admitting
        — sticky sessions are the cheapest affinity there is.
        ``exclude`` removes replicas a retry already failed on.
        ``tier`` (disaggregated cells) restricts candidates to that tier
        plus "mixed" replicas; an empty tier falls back to ALL
        class-admitting candidates — disaggregation degrades to the
        colocated policy, it never sheds. Raises
        :class:`CellOverloaded` when the class sheds."""
        excluded = set(exclude or ())
        signals = [s for s in signals if s.replica_id not in excluded]
        if not any(s.routable() for s in signals):
            raise CellOverloaded("no routable replica in the cell")
        candidates = self._class_candidates(signals, slo_class)
        if not candidates:
            raise CellOverloaded(
                f"all routable replicas past the {slo_class!r}-class "
                f"admission threshold; shedding at the cell boundary"
            )
        if tier is not None:
            tiered = [s for s in candidates if s.tier in (tier, "mixed")]
            if tiered:
                candidates = tiered
        by_id = {s.replica_id: s for s in candidates}
        if pinned is not None and pinned in by_id:
            _, lcp = self.table.lookup(key, alive=[pinned])
            return pinned, lcp
        owner, lcp = self.table.lookup(key, alive=list(by_id))
        if owner is not None and owner in by_id and lcp > 0:
            # Affinity wins ties BEFORE the headroom/queue terms get a
            # vote: stealing a warm session over a fraction of a queue
            # slot re-prefills the whole prompt elsewhere, which costs
            # far more than the queue imbalance it "fixes". Only a real
            # load gap (owner past the least-loaded candidate by more
            # than the margin) overrides locality.
            floor = min(c.queue_frac for c in by_id.values())
            if by_id[owner].queue_frac <= floor + self.affinity_tie_margin:
                self._rr += 1
                return owner, lcp
        best_id, best_score = None, None
        order = sorted(by_id)
        for i, rid in enumerate(order):
            s = by_id[rid]
            aff = lcp if rid == owner else 0
            sc = self.score(s, slo_class, aff, len(key))
            # Deterministic rotation tiebreak: equal scores spread
            # round-robin instead of piling onto the lexicographically
            # first replica.
            sc += 1e-9 * ((i + self._rr) % max(len(order), 1))
            if best_score is None or sc > best_score:
                best_id, best_score = rid, sc
        self._rr += 1
        return best_id, (lcp if best_id == owner else 0)


__all__ = [
    "CellOverloaded",
    "ReplicaRouter",
    "ReplicaSignals",
    "RoutingTable",
    "route_key",
]
