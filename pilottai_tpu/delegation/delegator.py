"""TaskDelegator: manager-side delegation decisions.

Reference parity: ``pilott/delegation/task_delegator.py`` (359 LoC) —
``DelegationMetrics`` per agent (``:8-15``), ``evaluate_delegation``
(``:41``), ``_should_delegate`` gates: queue utilization > 0.8 OR
complexity > max_task_complexity OR missing capabilities (``:328-345``),
``_find_best_agent`` scoring 0.4·suitability + 0.3·(1−queue) +
0.2·success + 0.1·resources (``:92-111``), acceptance gate (``:316-326``),
similar-task history (``:159-181``), ``record_delegation`` (``:183-219``),
history retention cleanup (``:272-306``). One home for this logic — the
reference's vestigial second copy in ``core/router.py:148-193`` (§2.12-f)
has no counterpart here.
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.task import Task
from pilottai_tpu.obs.dag import global_dag
from pilottai_tpu.sched import global_scheduler
from pilottai_tpu.utils.logging import get_logger


@dataclass
class DelegationMetrics:
    """Per-agent delegation outcomes (reference ``:8-15``)."""

    delegations: int = 0
    successes: int = 0
    failures: int = 0
    total_exec_time: float = 0.0
    errors_by_type: Dict[str, int] = field(default_factory=dict)

    @property
    def success_rate(self) -> float:
        total = self.successes + self.failures
        return self.successes / total if total else 1.0

    @property
    def avg_exec_time(self) -> float:
        done = self.successes + self.failures
        return self.total_exec_time / done if done else 0.0


class TaskDelegator:
    """Decides whether and to whom a manager agent should delegate."""

    def __init__(
        self,
        agent: BaseAgent,
        history_retention: float = 86_400.0,   # 24h (reference ``:272-306``)
        history_cap: int = 1000,
        selection_timeout: float = 10.0,
        acceptance_threshold: float = 0.8,
    ) -> None:
        self.agent = agent
        self.history_retention = history_retention
        self.history_cap = history_cap
        self.selection_timeout = selection_timeout
        self.acceptance_threshold = acceptance_threshold
        self.metrics: Dict[str, DelegationMetrics] = {}
        self._history: Dict[str, List[Dict[str, Any]]] = {}  # agent -> records
        self._lock = asyncio.Lock()
        self._log = get_logger("delegation", agent_id=agent.id[:8])

    # ------------------------------------------------------------------ #
    # Decision (reference ``:41-111,316-345``)
    # ------------------------------------------------------------------ #

    def _should_delegate(self, task: Task) -> Tuple[bool, str]:
        cfg = self.agent.config
        if not cfg.delegation_enabled:
            return False, "delegation disabled"
        if not self.agent.child_agents:
            return False, "no child agents"
        if self.agent.queue_utilization > cfg.delegation_threshold:
            return True, "queue over threshold"
        if task.complexity > cfg.max_task_complexity:
            return True, "complexity over limit"
        needed = set(task.required_capabilities)
        own = set(cfg.required_capabilities) | set(self.agent.tools.names())
        if needed and not needed.issubset(own):
            return True, "missing capabilities"
        return False, "self-execution preferred"

    def _accepts(self, candidate: BaseAgent) -> bool:
        """Acceptance gate: candidate must not itself be overloaded
        (reference ``:316-326``)."""
        return (
            candidate.status.is_available
            and candidate.queue_utilization < self.acceptance_threshold
            and candidate.load < self.acceptance_threshold
        )

    def _historical_bonus(self, candidate: BaseAgent, task: Task) -> float:
        """Similar-task performance bonus (reference ``:159-181``)."""
        records = self._history.get(candidate.id, [])
        similar = [r for r in records if r.get("task_type") == task.type]
        if not similar:
            return 0.0
        rate = sum(1 for r in similar if r["success"]) / len(similar)
        return 0.1 * (rate - 0.5) * 2  # [-0.1, +0.1]

    def _score(self, candidate: BaseAgent, task: Task) -> float:
        metrics = self.metrics.get(candidate.id, DelegationMetrics())
        return (
            0.4 * candidate.evaluate_task_suitability(task)
            + 0.3 * (1.0 - candidate.queue_utilization)
            + 0.2 * metrics.success_rate
            + 0.1 * (1.0 - candidate.load)
            + self._historical_bonus(candidate, task)
        )

    async def evaluate_delegation(
        self, task: Task, candidates: Optional[List[BaseAgent]] = None
    ) -> Tuple[Optional[BaseAgent], str]:
        """Returns (target_agent_or_None, reason)."""
        t0 = time.perf_counter()
        target, reason = await self._evaluate_inner(task, candidates)
        # Delegation decision node in the task's DAG: the manager-side
        # choice (and its reason) becomes part of the orchestration
        # breakdown instead of invisible pre-routing latency.
        global_dag.record(
            task.id, "stage", "delegate",
            start=t0, end=time.perf_counter(),
            reason=reason, delegated=target is not None,
        )
        if target is not None:
            # Speculative stage pre-warm (pilottai_tpu/sched/): the
            # delegation target is decided — start restoring its first
            # stage's prompt preamble through the KV cache tier NOW, on
            # the engine's prep thread, so by the time the task reaches
            # the target's queue its first prefill finds resident KV.
            global_scheduler.prewarm_role(target.role)
        return target, reason

    async def _evaluate_inner(
        self, task: Task, candidates: Optional[List[BaseAgent]] = None
    ) -> Tuple[Optional[BaseAgent], str]:
        should, reason = self._should_delegate(task)
        if not should:
            return None, reason
        pool = [
            c for c in (candidates or list(self.agent.child_agents.values()))
            if self._accepts(c)
        ]
        if not pool:
            return None, "no accepting candidate"
        async def _select() -> BaseAgent:
            async with self._lock:
                return max(pool, key=lambda c: self._score(c, task))

        try:
            # wait_for, not asyncio.timeout: the latter is 3.11+ and this
            # package supports 3.10 (requires-python >= 3.10).
            best = await asyncio.wait_for(_select(), self.selection_timeout)
        except (TimeoutError, asyncio.TimeoutError):
            return None, "selection timed out"
        return best, reason

    # ------------------------------------------------------------------ #
    # Bookkeeping (reference ``:183-219,272-306``)
    # ------------------------------------------------------------------ #

    async def record_delegation(
        self,
        agent_id: str,
        task: Task,
        success: bool,
        execution_time: float = 0.0,
        error: Optional[str] = None,
    ) -> None:
        async with self._lock:
            metrics = self.metrics.setdefault(agent_id, DelegationMetrics())
            metrics.delegations += 1
            metrics.total_exec_time += execution_time
            if success:
                metrics.successes += 1
            else:
                metrics.failures += 1
                if error:
                    key = error.split(":")[0][:60]
                    metrics.errors_by_type[key] = metrics.errors_by_type.get(key, 0) + 1
            history = self._history.setdefault(agent_id, [])
            history.append(
                {
                    "task_id": task.id,
                    "task_type": task.type,
                    "success": success,
                    "execution_time": execution_time,
                    "ts": time.time(),
                }
            )
            if len(history) > self.history_cap:
                del history[: len(history) - self.history_cap]

    async def cleanup_history(self) -> int:
        """Drop records past retention (reference hourly janitor ``:272``)."""
        cutoff = time.time() - self.history_retention
        removed = 0
        async with self._lock:
            for agent_id in list(self._history):
                before = len(self._history[agent_id])
                self._history[agent_id] = [
                    r for r in self._history[agent_id] if r["ts"] >= cutoff
                ]
                removed += before - len(self._history[agent_id])
                if not self._history[agent_id]:
                    del self._history[agent_id]
        return removed

    def get_metrics(self) -> Dict[str, Any]:
        return {
            agent_id: {
                "delegations": m.delegations,
                "success_rate": m.success_rate,
                "avg_exec_time": m.avg_exec_time,
                "errors_by_type": dict(m.errors_by_type),
            }
            for agent_id, m in self.metrics.items()
        }
