from pilottai_tpu.delegation.delegator import DelegationMetrics, TaskDelegator

__all__ = ["TaskDelegator", "DelegationMetrics"]
