"""Durable task-queue journal: crash/preemption-safe task state.

The reference has NO runtime persistence — tasks, queues and memory are
process-local and lost on crash (SURVEY.md §5.4; its FaultTolerance only
migrates live Task objects in RAM, ``pilott/orchestration/scaling.py:354-378``).
On TPU-VMs, preemption is a first-class event, so the orchestrator journals
every task transition to an append-only JSONL file that a restarted process
replays to rebuild its queue.

Format — one JSON object per line:
  ``{"ev": "task",   "ts": ..., "data": {<full Task dump>}}``   (enqueue/update)
  ``{"ev": "status", "ts": ..., "id": ..., "status": ..., "result": {...}|null}``

Replay folds the log in order: the latest full dump wins for task fields,
later status records overwrite the status/result. Tasks whose final state is
non-terminal are the recovery set.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, TextIO

from pilottai_tpu.core.task import Task, TaskResult, TaskStatus
from pilottai_tpu.reliability import global_injector
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


class TaskJournal:
    """Append-only JSONL journal of task lifecycle events."""

    def __init__(self, path: str | Path, fsync: bool = False) -> None:
        self.path = Path(path)
        self.fsync = fsync
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh: Optional[TextIO] = open(self.path, "a", encoding="utf-8")
        self._log = get_logger("checkpoint.journal")
        self._writes = 0

    # ------------------------------------------------------------------ #
    # Writing
    # ------------------------------------------------------------------ #

    def _write(self, record: Dict[str, Any]) -> None:
        if self._fh is None:
            raise RuntimeError("journal is closed")
        try:
            line = json.dumps(record)
        except TypeError:
            # Non-JSON values (arrays, handles) stringify — the replayed
            # task would rerun with corrupted inputs, so say so loudly.
            self._log.warning(
                "journal record for task %s has non-JSON-serializable values; "
                "they are stored as strings and will NOT survive recovery "
                "intact — keep Task.payload/context JSON-safe",
                record.get("id") or record.get("data", {}).get("id"),
            )
            line = json.dumps(record, default=str)
        self._fh.write(line + "\n")
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())
        self._writes += 1

    def reopen(self) -> None:
        """Re-attach to the journal file after ``close()`` (e.g. a Serve
        stop/start cycle within one process)."""
        if self._fh is None:
            self._fh = open(self.path, "a", encoding="utf-8")

    def record_task(self, task: Task) -> None:
        """Full task dump — written on enqueue and requeue so replay can
        reconstruct the Task object exactly. Write failures (disk full,
        revoked mount) degrade to at-least-once-with-a-hole: the task
        still runs now, it just may rerun after a crash — a full journal
        disk must not take live serving down with it."""
        self._record({"ev": "task", "ts": time.time(),
                      "data": task.model_dump(mode="json")})

    def record_status(self, task: Task) -> None:
        """Slim status transition — written on start/terminal events.
        Same degraded semantics on write failure as ``record_task``."""
        self._record(
            {
                "ev": "status",
                "ts": time.time(),
                "id": task.id,
                "status": task.status.value,
                "result": (
                    task.result.model_dump(mode="json")
                    if task.result is not None
                    else None
                ),
            }
        )

    def _record(self, record: Dict[str, Any]) -> None:
        # Chaos point: a failing journal disk (arm with exc=OSError).
        try:
            global_injector.fire("checkpoint.write")
            self._write(record)
        except OSError as exc:
            global_metrics.inc("journal.write_failures")
            self._log.error(
                "journal write failed (%s); task %s will replay "
                "at-least-once after a crash",
                exc, record.get("id") or record.get("data", {}).get("id"),
            )

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    # ------------------------------------------------------------------ #
    # Replay
    # ------------------------------------------------------------------ #

    @staticmethod
    def replay(path: str | Path) -> Dict[str, Task]:
        """Fold the journal into {task_id: Task} with final statuses applied.

        Tolerates a torn final line (crash mid-write): bad lines are skipped
        with a warning rather than failing recovery.
        """
        log = get_logger("checkpoint.journal")
        path = Path(path)
        tasks: Dict[str, Task] = {}
        if not path.exists():
            return tasks
        with open(path, encoding="utf-8") as fh:
            for lineno, line in enumerate(fh, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    record = json.loads(line)
                    if record["ev"] == "task":
                        task = Task(**record["data"])
                        tasks[task.id] = task
                    elif record["ev"] == "status":
                        task = tasks.get(record["id"])
                        if task is None:
                            continue
                        task.status = TaskStatus(record["status"])
                        if record.get("result") is not None:
                            task.result = TaskResult(**record["result"])
                except Exception as exc:  # noqa: BLE001 - torn/corrupt line
                    log.warning(
                        "journal %s line %d unreadable (%s); skipping",
                        path, lineno, exc,
                    )
        return tasks

    @staticmethod
    def pending(tasks: Dict[str, Task]) -> List[Task]:
        """Tasks needing re-execution after a crash: anything non-terminal.
        In-flight work (IN_PROGRESS/RETRYING) is included — its result was
        never journaled, so it must rerun."""
        return [t for t in tasks.values() if not t.status.is_terminal]

    # ------------------------------------------------------------------ #
    # Compaction
    # ------------------------------------------------------------------ #

    def compact(self, retain_terminal: bool = False) -> int:
        """Rewrite the journal with one record per live task.

        Returns the number of tasks retained. Terminal tasks are dropped by
        default (their results live in the orchestrator's retention window,
        not the journal) — EXCEPT terminal children of a still-live parent,
        whose outputs the parent aggregation will need after the next
        recovery. Atomic via rename.
        """
        tasks = self.replay(self.path)
        live = {t.id for t in tasks.values() if not t.status.is_terminal}
        keep = [
            t for t in tasks.values()
            if retain_terminal
            or not t.status.is_terminal
            or (t.parent_task_id is not None and t.parent_task_id in live)
        ]
        tmp = self.path.with_suffix(self.path.suffix + ".compact")
        with open(tmp, "w", encoding="utf-8") as fh:
            for task in keep:
                fh.write(
                    json.dumps(
                        {
                            "ev": "task",
                            "ts": time.time(),
                            "data": task.model_dump(mode="json"),
                        },
                        default=str,
                    )
                    + "\n"
                )
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, self.path)
        self._fh = open(self.path, "a", encoding="utf-8")
        self._log.info("journal compacted: %d live tasks retained", len(keep))
        return len(keep)

    @property
    def writes(self) -> int:
        return self._writes


class BlackBoxJournal:
    """Append-only JSONL sink for observability black-box dumps.

    The flight recorder (``pilottai_tpu/obs/blackbox.py``) writes one
    record per triggering event — deadline expiry, breaker open, request
    error — containing the last engine steps and the request's span
    tree. Same posture as ``TaskJournal``: writes degrade instead of
    crash (a full disk must not take serving down with it) and pass the
    ``checkpoint.write`` chaos point so fault tests can script failures.
    """

    def __init__(self, path: str | Path, fsync: bool = False) -> None:
        self.path = Path(path)
        self.fsync = fsync
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._fh: Optional[TextIO] = open(self.path, "a", encoding="utf-8")
        self._log = get_logger("checkpoint.blackbox")
        self._lock = threading.Lock()
        self.writes = 0

    def write(self, record: Dict[str, Any]) -> bool:
        """Append one dump record; returns False on a degraded write —
        including writes racing a close/re-configure (a queued dump must
        degrade, never raise, on failure paths)."""
        try:
            global_injector.fire("checkpoint.write")
            line = json.dumps(record, default=str)
            with self._lock:
                if self._fh is None:
                    global_metrics.inc("blackbox.write_failures")
                    return False
                self._fh.write(line + "\n")
                self._fh.flush()
                if self.fsync:
                    os.fsync(self._fh.fileno())
                self.writes += 1
            return True
        except OSError as exc:
            global_metrics.inc("blackbox.write_failures")
            self._log.error(
                "black-box dump write failed (%s); dump for %s kept "
                "in-memory only", exc, record.get("trace_id"),
            )
            return False

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    @staticmethod
    def read(path: str | Path) -> List[Dict[str, Any]]:
        """Load every dump record, skipping torn lines (the writer may
        have died mid-dump — that's the scenario dumps exist for)."""
        path = Path(path)
        records: List[Dict[str, Any]] = []
        if not path.exists():
            return records
        with open(path, encoding="utf-8") as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    continue
        return records
