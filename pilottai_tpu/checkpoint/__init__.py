"""Checkpoint/resume: the durability layer the reference lacks.

SURVEY.md §5.4 — the reference loses tasks, queues and memory on any crash;
its only persistence is AgentConfig JSON round-trips. Here:

  * ``TaskJournal`` — append-only JSONL of task transitions; replayed on
    restart to rebuild the orchestrator queue (wired into ``Serve`` via
    ``ServeConfig.journal_path``).
  * ``save_memory`` / ``restore_memory`` — EnhancedMemory snapshots
    (JSON + embedding-buffer ``.npz``, no re-embedding on restore).
  * ``TrainCheckpointer`` — orbax params+opt_state+step checkpoints with
    retention; model-weight-only IO lives in ``models/loader.py``.
"""

from pilottai_tpu.checkpoint.journal import TaskJournal
from pilottai_tpu.checkpoint.memory_io import restore_memory, save_memory
from pilottai_tpu.checkpoint.train_io import TrainCheckpointer

__all__ = [
    "TaskJournal",
    "TrainCheckpointer",
    "restore_memory",
    "save_memory",
]
