"""Semantic-memory snapshot files.

``EnhancedMemory.export_state()`` is split into a JSON document (items,
histories, interactions, patterns) and an ``.npz`` of the embedding ring
buffer, so restore never re-embeds 10k items through the encoder
(SURVEY.md §5.4: the reference has no memory persistence at all).
"""

from __future__ import annotations

import json
import logging
from pathlib import Path
from typing import Any, Dict

import numpy as np

MEMORY_JSON = "memory.json"
VECTORS_NPZ = "vectors.npz"


async def save_memory(memory: Any, directory: str | Path) -> None:
    """Snapshot an ``EnhancedMemory`` into ``directory`` (atomic per file)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    state = await memory.export_state()
    arrays = state.pop("vector_arrays", None)

    tmp = directory / (MEMORY_JSON + ".tmp")
    try:
        doc = json.dumps(state)
    except TypeError:
        # Mirror TaskJournal._write: the lossy fallback must be loud —
        # stringified payloads come back as strings after restore.
        logging.getLogger("pilottai_tpu.checkpoint.memory_io").warning(
            "memory snapshot has non-JSON-serializable payloads; they are "
            "stored as strings and will NOT restore intact — keep "
            "MemoryItem.data/interactions JSON-safe"
        )
        doc = json.dumps(state, default=str)
    tmp.write_text(doc, encoding="utf-8")
    tmp.replace(directory / MEMORY_JSON)

    if arrays is not None:
        tmp_npz = directory / (VECTORS_NPZ + ".tmp")
        with open(tmp_npz, "wb") as fh:
            np.savez_compressed(fh, **arrays)
        tmp_npz.replace(directory / VECTORS_NPZ)
    else:
        # Drop any stale vector file from an earlier snapshot — restore
        # would otherwise pair old embeddings with the new items.
        (directory / VECTORS_NPZ).unlink(missing_ok=True)


async def restore_memory(memory: Any, directory: str | Path) -> bool:
    """Restore a snapshot into ``memory``; returns False if none exists."""
    directory = Path(directory)
    doc = directory / MEMORY_JSON
    if not doc.exists():
        return False
    state: Dict[str, Any] = json.loads(doc.read_text(encoding="utf-8"))
    npz = directory / VECTORS_NPZ
    if npz.exists():
        with np.load(npz) as data:
            state["vector_arrays"] = {k: data[k] for k in data.files}
    else:
        state["vector_arrays"] = None
    await memory.import_state(state)
    return True
