"""Train-state checkpointing: params + optimizer state + step, via orbax.

Directory layout (one orbax PyTree checkpoint per step)::

    <root>/step_00000100/   # orbax tree: {"params": ..., "opt_state": ...}
    <root>/step_00000200/
    <root>/LATEST           # text file: "200"

Restore requires a ``template`` state (from ``Trainer.init``) so optax
NamedTuple optimizer states come back with their original structure —
orbax restores raw containers otherwise. Sharded arrays restore onto the
template's shardings, so a checkpoint written on one mesh can resume on
another (orbax reshards on load).

Reference has no checkpointing of any kind (SURVEY.md §5.4).
"""

from __future__ import annotations

import shutil
from pathlib import Path
from typing import Any, List, Optional, Tuple

from pilottai_tpu.utils.logging import get_logger

_LATEST = "LATEST"


def _step_dir(root: Path, step: int) -> Path:
    return root / f"step_{step:08d}"


class TrainCheckpointer:
    """Save/restore (params, opt_state) with retention of the last N steps."""

    def __init__(self, root: str | Path, max_to_keep: int = 3) -> None:
        self.root = Path(root).absolute()
        self.root.mkdir(parents=True, exist_ok=True)
        self.max_to_keep = max_to_keep
        self._log = get_logger("checkpoint.train")

    # ------------------------------------------------------------------ #

    def save(self, step: int, state: Tuple[Any, Any]) -> Path:
        import orbax.checkpoint as ocp

        params, opt_state = state
        target = _step_dir(self.root, step)
        ocp.PyTreeCheckpointer().save(
            target, {"params": params, "opt_state": opt_state}, force=True
        )
        # LATEST write is atomic-ish (tiny file, rename) and last: a crash
        # mid-save leaves LATEST pointing at the previous good step.
        tmp = self.root / (_LATEST + ".tmp")
        tmp.write_text(str(step), encoding="utf-8")
        tmp.replace(self.root / _LATEST)
        self._gc(keep=step)
        self._log.info("saved train checkpoint step=%d at %s", step, target)
        return target

    def restore(
        self, template: Tuple[Any, Any], step: Optional[int] = None
    ) -> Tuple[Tuple[Any, Any], int]:
        """Returns ((params, opt_state), step). Raises if no checkpoint."""
        import orbax.checkpoint as ocp

        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint under {self.root}")
        params_t, opt_t = template
        item = {"params": params_t, "opt_state": opt_t}
        # Restore onto the *template's* shardings: without restore_args,
        # orbax populates sharding from the checkpoint file, which is
        # unsafe when resuming on a different mesh/topology.
        restore_args = ocp.checkpoint_utils.construct_restore_args(item)
        restored = ocp.PyTreeCheckpointer().restore(
            _step_dir(self.root, step), item=item, restore_args=restore_args,
        )
        return (restored["params"], restored["opt_state"]), step

    # ------------------------------------------------------------------ #

    def latest_step(self) -> Optional[int]:
        marker = self.root / _LATEST
        if marker.exists():
            try:
                return int(marker.read_text().strip())
            except ValueError:
                pass
        steps = self.all_steps()
        return steps[-1] if steps else None

    def all_steps(self) -> List[int]:
        return sorted(
            int(p.name.split("_")[1])
            for p in self.root.glob("step_*")
            if p.is_dir()
        )

    def _gc(self, keep: int) -> None:
        """Prune to the ``max_to_keep`` highest steps, always retaining
        ``keep`` — a rollback save(150) into [200,300,400] must never delete
        the step it just wrote (LATEST points at it)."""
        steps = self.all_steps()
        survivors = set(steps[-self.max_to_keep:]) | {keep}
        for old in steps:
            if old not in survivors:
                shutil.rmtree(_step_dir(self.root, old), ignore_errors=True)
