"""Semantic memory: an embedding-indexed store with on-device search.

Reference parity: ``pilott/memory/enhanced_memory.py`` — but
``semantic_search`` there is a naive case-insensitive substring match
(``:93-131``, SURVEY §2.8). Here search runs on an embedding matrix on
device: a jit-batched encoder (Gemma-2B when a checkpoint is available, a
byte-level encoder otherwise) embeds entries, and top-k cosine similarity
is one matmul on the accelerator (BASELINE.json config #2).
"""

from pilottai_tpu.memory.embedder import Embedder
from pilottai_tpu.memory.semantic import EnhancedMemory, MemoryItem

__all__ = ["Embedder", "EnhancedMemory", "MemoryItem"]
