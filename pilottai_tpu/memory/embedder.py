"""Jit-batched text embedding encoder.

The encoder is a transformer trunk from the model zoo run as a bidirectional
feature extractor: prefill the text, mean-pool valid hidden states, L2
normalize. With a Gemma-2B checkpoint this is the BASELINE config #2
"Gemma-2B encoder" path; without one, a small randomly-initialized trunk
over byte tokens still yields a usable locality-sensitive signature (random
features over overlapping byte n-grams), keeping tests and CPU CI hermetic.

Batched + jitted: one compile per length bucket; embeddings come back
L2-normalized so similarity is a single dot product on device.
"""

from __future__ import annotations

import threading
from functools import partial
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.engine.tokenizer import ByteTokenizer, Tokenizer, load_tokenizer
from pilottai_tpu.models.common import ModelConfig, init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.utils.metrics import global_metrics


@partial(jax.jit, static_argnames=("cfg",))
def _encode_batch(
    params, cfg: ModelConfig, tokens: jax.Array, valid: jax.Array
) -> jax.Array:
    """[B, T] tokens -> [B, E] L2-normalized mean-pooled features."""
    from pilottai_tpu.models.transformer import forward_prefill

    B, T = tokens.shape
    positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))
    # Feature source: the last layer's VALUE projections ([L,B,T,K,H] from
    # prefill) — contextualized token features one matmul short of the
    # logits, reused verbatim from the serving path so the encoder shares
    # its compile cache with the engine.
    _, _, vs = forward_prefill(params, cfg, tokens, positions, valid)
    feats = vs[-1].reshape(B, T, -1).astype(jnp.float32)
    mask = (jnp.arange(T)[None, :] < valid[:, None]).astype(jnp.float32)
    pooled = (feats * mask[:, :, None]).sum(axis=1) / jnp.maximum(
        mask.sum(axis=1, keepdims=True), 1.0
    )
    norm = jnp.linalg.norm(pooled, axis=-1, keepdims=True)
    return pooled / jnp.maximum(norm, 1e-6)


class Embedder:
    """Batched text → vector encoder with length-bucketed jit."""

    def __init__(
        self,
        model_name: str = "llama-tiny",
        checkpoint_path: Optional[str] = None,
        tokenizer: Optional[Tokenizer] = None,
        max_len: int = 256,
        seed: int = 0,
    ) -> None:
        self.tokenizer = tokenizer or load_tokenizer()
        cfg = get_model_config(model_name)
        if checkpoint_path is None and isinstance(self.tokenizer, ByteTokenizer):
            cfg = cfg.replace(
                vocab_size=self.tokenizer.vocab_size, tie_embeddings=True
            )
        self.cfg = cfg.replace(dtype=jnp.float32)
        self.max_len = min(max_len, self.cfg.max_seq_len)
        if checkpoint_path is not None:
            from pilottai_tpu.models.loader import load_hf_checkpoint

            self.params = load_hf_checkpoint(self.cfg, checkpoint_path, dtype=jnp.float32)
        else:
            self.params = init_params(self.cfg, jax.random.PRNGKey(seed), dtype=jnp.float32)
        self.dim = self.cfg.n_kv_heads * self.cfg.head_dim
        self._lock = threading.Lock()

    def _bucket(self, n: int) -> int:
        b = 32
        while b < n:
            b *= 2
        return min(b, self.max_len)

    def encode(self, texts: List[str]) -> np.ndarray:
        """Embed a batch of texts -> [N, dim] float32, L2-normalized."""
        if not texts:
            return np.zeros((0, self.dim), np.float32)
        with global_metrics.timer("embedder.encode_latency"):
            ids = [self.tokenizer.encode(t)[: self.max_len] for t in texts]
            T = self._bucket(max(len(i) for i in ids))
            batch = np.zeros((len(ids), T), np.int32)
            valid = np.zeros((len(ids),), np.int32)
            for row, seq in enumerate(ids):
                batch[row, : len(seq)] = seq
                valid[row] = len(seq)
            with self._lock:
                out = _encode_batch(
                    self.params, self.cfg, jnp.asarray(batch), jnp.asarray(valid)
                )
            result = np.asarray(out)
        global_metrics.inc("embedder.texts", len(texts))
        return result

    def encode_one(self, text: str) -> np.ndarray:
        return self.encode([text])[0]
