"""EnhancedMemory: the long-term semantic store.

Reference parity: ``pilott/memory/enhanced_memory.py`` (292 LoC) — four
stores under separate locks (semantic / task history / agent interactions /
patterns, ``:27-46``), ``MemoryItem`` with tags/priority/TTL (``:9-21``),
tag+priority-filtered search (``:110-131``), task-history versioning
(``:146-160``), interaction log (``:162-182``), TTL patterns
(``:184-218``), periodic cleanup (``:248-282``).

The headline change: ``semantic_search`` is embedding-based on device (one
jitted matmul over a vector ring buffer) instead of substring matching,
with stable-id indexes that survive eviction (the reference's positional
indexes drift, §2.12-h). Substring search remains available as
``keyword_search`` and as the fallback when no embedder is attached.
"""

from __future__ import annotations

import asyncio
import re
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from pilottai_tpu.obs.dag import global_dag
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


@dataclass
class MemoryItem:
    """One semantic entry (reference ``enhanced_memory.py:9-21``)."""

    text: str
    data: Any = None
    tags: Set[str] = field(default_factory=set)
    priority: int = 0
    ttl: Optional[float] = None  # seconds
    entry_id: int = 0
    created_at: float = field(default_factory=time.time)

    @property
    def expired(self) -> bool:
        return self.ttl is not None and time.time() - self.created_at > self.ttl

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.entry_id,
            "text": self.text,
            "data": self.data,
            "tags": sorted(self.tags),
            "priority": self.priority,
            "created_at": self.created_at,
        }


# Module-level so the jit cache is hit on every search (a per-call closure
# would re-trace and re-compile each query). jax import stays lazy.
_TOPK_FN = None


def _topk(vectors, q, k):
    global _TOPK_FN
    if _TOPK_FN is None:
        import jax

        @partial(jax.jit, static_argnames=("k",))
        def fn(vectors, q, k):
            return jax.lax.top_k(vectors @ q, k)

        _TOPK_FN = fn
    return _TOPK_FN(vectors, q, k=k)


class _VectorStore:
    """Fixed-capacity embedding ring buffer with device top-k search.

    Vectors live in one [capacity, dim] array; cosine top-k is a single
    matmul + top_k on the accelerator. Rows of evicted entries are zeroed
    (zero vectors can never win a cosine search over normalized queries).
    """

    def __init__(self, capacity: int, dim: int) -> None:
        import jax.numpy as jnp  # local: keep module import light

        self.capacity = capacity
        self.dim = dim
        self._vectors = jnp.zeros((capacity, dim), jnp.float32)
        self._row_ids = np.full((capacity,), -1, np.int64)  # entry_id per row
        self._id_to_row: Dict[int, int] = {}
        self._next_row = 0

    def add(self, entry_id: int, vector: np.ndarray) -> None:
        row = self._next_row % self.capacity
        old_id = self._row_ids[row]
        if old_id >= 0:
            self._id_to_row.pop(int(old_id), None)
        self._vectors = self._vectors.at[row].set(vector)
        self._row_ids[row] = entry_id
        self._id_to_row[entry_id] = row
        self._next_row += 1

    def remove(self, entry_id: int) -> None:
        row = self._id_to_row.pop(entry_id, None)
        if row is not None:
            import jax.numpy as jnp

            self._vectors = self._vectors.at[row].set(jnp.zeros((self.dim,)))
            self._row_ids[row] = -1

    def search(self, query: np.ndarray, k: int) -> List[Tuple[int, float]]:
        import jax.numpy as jnp

        k = min(k, self.capacity)
        scores, rows = _topk(self._vectors, jnp.asarray(query, jnp.float32), k)
        out: List[Tuple[int, float]] = []
        for score, row in zip(np.asarray(scores), np.asarray(rows)):
            entry_id = int(self._row_ids[int(row)])
            if entry_id >= 0 and score > 0.0:
                out.append((entry_id, float(score)))
        return out

    def export_arrays(self) -> Dict[str, np.ndarray]:
        """Host copies of the buffer for snapshotting (checkpoint/memory_io)."""
        return {
            "vectors": np.asarray(self._vectors),
            "row_ids": self._row_ids.copy(),
            "next_row": np.asarray([self._next_row]),
        }

    @classmethod
    def from_arrays(cls, arrays: Dict[str, np.ndarray]) -> "_VectorStore":
        """Build directly from a snapshot — skips the constructor's zeros
        allocation that import_arrays would immediately discard."""
        store = cls.__new__(cls)
        store.import_arrays(arrays)
        return store

    def import_arrays(self, arrays: Dict[str, np.ndarray]) -> None:
        import jax.numpy as jnp

        vectors = np.asarray(arrays["vectors"], np.float32)
        # The snapshot's geometry wins: keeping the constructor-time
        # capacity/dim would corrupt add()'s ring indexing (and search's k
        # bound) when restoring a snapshot saved under a different config.
        self.capacity, self.dim = vectors.shape
        self._vectors = jnp.asarray(vectors)
        self._row_ids = np.asarray(arrays["row_ids"], np.int64).copy()
        if len(self._row_ids) != self.capacity:
            raise ValueError(
                f"vector snapshot is inconsistent: {self.capacity} rows vs "
                f"{len(self._row_ids)} row ids"
            )
        self._next_row = int(arrays["next_row"][0])
        self._id_to_row = {
            int(eid): row for row, eid in enumerate(self._row_ids) if eid >= 0
        }


class EnhancedMemory:
    """Semantic + episodic memory for agents."""

    def __init__(
        self,
        embedder: Optional[Any] = None,   # memory.embedder.Embedder
        capacity: int = 10_000,           # reference deque maxlen=10000
        task_history_size: int = 1000,
        cleanup_interval: float = 3600.0,
    ) -> None:
        self.embedder = embedder
        self.capacity = capacity
        self.cleanup_interval = cleanup_interval
        self._items: Dict[int, MemoryItem] = {}
        self._order: List[int] = []  # insertion order for FIFO eviction
        self._tag_index: Dict[str, Set[int]] = {}
        self._next_id = 0
        self._vectors: Optional[_VectorStore] = None
        self._semantic_lock = asyncio.Lock()

        self._task_history: Dict[str, List[Dict[str, Any]]] = {}
        self._task_history_size = task_history_size
        self._task_lock = asyncio.Lock()

        self._interactions: List[Dict[str, Any]] = []
        self._interaction_lock = asyncio.Lock()

        self._patterns: Dict[str, MemoryItem] = {}
        self._pattern_lock = asyncio.Lock()

        self._cleanup_task: Optional[asyncio.Task] = None
        self._log = get_logger("memory.semantic")

    # ------------------------------------------------------------------ #
    # Lifecycle (background janitor, reference ``:248-282``)
    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._cleanup_task is None:
            self._cleanup_task = asyncio.create_task(self._periodic_cleanup())

    async def stop(self) -> None:
        if self._cleanup_task is not None:
            self._cleanup_task.cancel()
            try:
                await self._cleanup_task
            except asyncio.CancelledError:
                pass
            self._cleanup_task = None

    async def _periodic_cleanup(self) -> None:
        while True:
            await asyncio.sleep(self.cleanup_interval)
            await self.cleanup()

    # ------------------------------------------------------------------ #
    # Semantic store (reference ``:60-144``)
    # ------------------------------------------------------------------ #

    async def store_semantic(
        self,
        text: str,
        data: Any = None,
        tags: Optional[Set[str]] = None,
        priority: int = 0,
        ttl: Optional[float] = None,
    ) -> int:
        async with self._semantic_lock:
            item = MemoryItem(
                text=text, data=data, tags=set(tags or ()), priority=priority,
                ttl=ttl, entry_id=self._next_id,
            )
            self._next_id += 1
            self._items[item.entry_id] = item
            self._order.append(item.entry_id)
            for tag in item.tags:
                self._tag_index.setdefault(tag, set()).add(item.entry_id)
            if self.embedder is not None:
                if self._vectors is None:
                    self._vectors = _VectorStore(self.capacity, self.embedder.dim)
                vec = await asyncio.to_thread(self.embedder.encode_one, text)
                self._vectors.add(item.entry_id, vec)
            while len(self._items) > self.capacity:
                self._evict(self._order.pop(0))
            global_metrics.inc("memory.semantic_stored")
            return item.entry_id

    def _evict(self, entry_id: int) -> None:
        item = self._items.pop(entry_id, None)
        if item is None:
            return
        for tag in item.tags:
            ids = self._tag_index.get(tag)
            if ids:
                ids.discard(entry_id)
                if not ids:
                    del self._tag_index[tag]
        if self._vectors is not None:
            self._vectors.remove(entry_id)

    def _filter(
        self,
        ids: List[int],
        tags: Optional[Set[str]],
        min_priority: Optional[int],
    ) -> List[MemoryItem]:
        out = []
        for entry_id in ids:
            item = self._items.get(entry_id)
            if item is None or item.expired:
                continue
            if tags and not tags.issubset(item.tags):
                continue
            if min_priority is not None and item.priority < min_priority:
                continue
            out.append(item)
        return out

    async def semantic_search(
        self,
        query: str,
        limit: int = 5,
        tags: Optional[Set[str]] = None,
        min_priority: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """Embedding top-k on device; keyword fallback without an embedder.

        Replaces the reference's substring scan (``enhanced_memory.py:110``).
        Returns items with similarity scores, most similar first.
        """
        # Memory lookup node in the ambient task's DAG (no-op outside
        # one): retrieval latency becomes task.memory_s.
        with global_dag.recorded("memory", "semantic_search"):
            return await self._semantic_search_inner(
                query, limit, tags, min_priority
            )

    async def _semantic_search_inner(
        self,
        query: str,
        limit: int = 5,
        tags: Optional[Set[str]] = None,
        min_priority: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        async with self._semantic_lock:
            if self.embedder is None or self._vectors is None:
                return await self._keyword_search_locked(
                    query, limit, tags, min_priority
                )
            qvec = await asyncio.to_thread(self.embedder.encode_one, query)
            # Over-fetch so tag/priority filters still leave `limit` results.
            hits = self._vectors.search(qvec, k=min(limit * 4, self.capacity))
            items = self._filter([eid for eid, _ in hits], tags, min_priority)
            scores = dict(hits)
            global_metrics.inc("memory.semantic_searches")
            return [
                {**item.to_dict(), "score": scores.get(item.entry_id, 0.0)}
                for item in items[:limit]
            ]

    async def keyword_search(
        self, query: str, limit: int = 5, tags: Optional[Set[str]] = None,
        min_priority: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        async with self._semantic_lock:
            return await self._keyword_search_locked(query, limit, tags, min_priority)

    async def _keyword_search_locked(
        self, query: str, limit: int, tags: Optional[Set[str]],
        min_priority: Optional[int],
    ) -> List[Dict[str, Any]]:
        needle = query.lower()
        candidates = self._filter(list(self._items), tags, min_priority)
        hits = [i for i in candidates if needle in i.text.lower()]
        if not hits and len(needle.split()) > 1:
            # A whole natural-language question never matches an item
            # verbatim: degrade to per-word OR matching, ranked by how
            # many query words each item contains (the no-embedder path
            # must still ground multi-word questions).
            words = [w for w in re.findall(r"[a-z0-9]{4,}", needle)]
            if words:
                scored = [
                    (sum(1 for w in words if w in i.text.lower()), i)
                    for i in candidates
                ]
                scored = [(n, i) for n, i in scored if n > 0]
                scored.sort(
                    key=lambda p: (p[0], p[1].priority, p[1].created_at),
                    reverse=True,
                )
                return [
                    {**i.to_dict(), "score": n / len(words)}
                    for n, i in scored[:limit]
                ]
        hits.sort(key=lambda i: (i.priority, i.created_at), reverse=True)
        return [{**item.to_dict(), "score": 1.0} for item in hits[:limit]]

    # ------------------------------------------------------------------ #
    # Task history (reference ``:146-160,220-246``)
    # ------------------------------------------------------------------ #

    async def store_task(self, task_id: str, record: Dict[str, Any]) -> None:
        async with self._task_lock:
            history = self._task_history.setdefault(task_id, [])
            history.append({**record, "version": len(history), "ts": time.time()})
            if len(history) > self._task_history_size:
                del history[: len(history) - self._task_history_size]

    async def get_task_history(self, task_id: str) -> List[Dict[str, Any]]:
        async with self._task_lock:
            return list(self._task_history.get(task_id, []))

    async def get_recent_tasks(self, limit: int = 10) -> List[Dict[str, Any]]:
        async with self._task_lock:
            latest = [h[-1] for h in self._task_history.values() if h]
            latest.sort(key=lambda r: r["ts"], reverse=True)
            return latest[:limit]

    # ------------------------------------------------------------------ #
    # Agent interactions (reference ``:162-182``)
    # ------------------------------------------------------------------ #

    async def log_interaction(
        self, source_agent: str, target_agent: str, payload: Any
    ) -> None:
        async with self._interaction_lock:
            self._interactions.append(
                {
                    "source": source_agent,
                    "target": target_agent,
                    "payload": payload,
                    "ts": time.time(),
                }
            )
            if len(self._interactions) > 10_000:
                del self._interactions[:5000]

    async def get_interactions(
        self, agent_id: Optional[str] = None, limit: int = 100
    ) -> List[Dict[str, Any]]:
        async with self._interaction_lock:
            rows = self._interactions
            if agent_id is not None:
                rows = [
                    r for r in rows
                    if r["source"] == agent_id or r["target"] == agent_id
                ]
            return rows[-limit:]

    # ------------------------------------------------------------------ #
    # Patterns with TTL (reference ``:184-218``)
    # ------------------------------------------------------------------ #

    async def store_pattern(
        self, key: str, value: Any, ttl: Optional[float] = None
    ) -> None:
        async with self._pattern_lock:
            self._patterns[key] = MemoryItem(text=key, data=value, ttl=ttl)

    async def get_pattern(self, key: str) -> Optional[Any]:
        async with self._pattern_lock:
            item = self._patterns.get(key)
            if item is None or item.expired:
                self._patterns.pop(key, None)
                return None
            return item.data

    # ------------------------------------------------------------------ #

    async def cleanup(self) -> int:
        """Drop expired items across stores; returns count removed."""
        removed = 0
        async with self._semantic_lock:
            for entry_id in [i for i, item in self._items.items() if item.expired]:
                self._evict(entry_id)
                if entry_id in self._order:
                    self._order.remove(entry_id)
                removed += 1
        async with self._pattern_lock:
            for key in [k for k, v in self._patterns.items() if v.expired]:
                del self._patterns[key]
                removed += 1
        return removed

    async def clear(self) -> None:
        async with self._semantic_lock:
            self._items.clear()
            self._order.clear()
            self._tag_index.clear()
            if self._vectors is not None and self.embedder is not None:
                self._vectors = _VectorStore(self.capacity, self.embedder.dim)

    # ------------------------------------------------------------------ #
    # Snapshot / restore (checkpoint/memory_io.py does the file IO; the
    # reference loses all memory on crash, SURVEY.md §5.4)
    # ------------------------------------------------------------------ #

    async def export_state(self) -> Dict[str, Any]:
        """Host-side snapshot of every store (plus vector arrays if any)."""
        async with self._semantic_lock, self._task_lock, \
                self._interaction_lock, self._pattern_lock:
            state: Dict[str, Any] = {
                "items": [
                    {
                        "text": i.text, "data": i.data, "tags": sorted(i.tags),
                        "priority": i.priority, "ttl": i.ttl,
                        "entry_id": i.entry_id, "created_at": i.created_at,
                    }
                    for i in self._items.values()
                ],
                "order": list(self._order),
                "next_id": self._next_id,
                "task_history": {k: list(v) for k, v in self._task_history.items()},
                "interactions": list(self._interactions),
                "patterns": [
                    {
                        "key": k, "data": v.data, "ttl": v.ttl,
                        "created_at": v.created_at,
                    }
                    for k, v in self._patterns.items()
                ],
                "vector_arrays": (
                    self._vectors.export_arrays() if self._vectors is not None else None
                ),
            }
            return state

    async def import_state(self, state: Dict[str, Any]) -> None:
        """Restore a snapshot. Vectors are restored verbatim (no re-embed)."""
        async with self._semantic_lock, self._task_lock, \
                self._interaction_lock, self._pattern_lock:
            self._items = {}
            self._tag_index = {}
            for row in state["items"]:
                item = MemoryItem(
                    text=row["text"], data=row["data"], tags=set(row["tags"]),
                    priority=row["priority"], ttl=row["ttl"],
                    entry_id=row["entry_id"], created_at=row["created_at"],
                )
                self._items[item.entry_id] = item
                for tag in item.tags:
                    self._tag_index.setdefault(tag, set()).add(item.entry_id)
            self._order = [i for i in state["order"] if i in self._items]
            self._next_id = state["next_id"]
            self._task_history = {
                k: list(v) for k, v in state["task_history"].items()
            }
            self._interactions = list(state["interactions"])
            self._patterns = {
                row["key"]: MemoryItem(
                    text=row["key"], data=row["data"], ttl=row["ttl"],
                    created_at=row["created_at"],
                )
                for row in state["patterns"]
            }
            arrays = state.get("vector_arrays")
            if arrays is not None and self.embedder is not None:
                dim = int(np.asarray(arrays["vectors"]).shape[1])
                if dim != self.embedder.dim:
                    # Silently scoring queries against foreign embeddings
                    # would make every search wrong; fail loudly instead.
                    raise ValueError(
                        f"memory snapshot embedding dim {dim} != attached "
                        f"embedder dim {self.embedder.dim}; restore with a "
                        "matching embedder or drop the vector snapshot"
                    )
                self._vectors = _VectorStore.from_arrays(arrays)
            else:
                # Never keep a pre-import buffer: its rows map old embeddings
                # onto the restored entry ids.
                self._vectors = None

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "semantic_items": len(self._items),
            "tags": len(self._tag_index),
            "task_histories": len(self._task_history),
            "interactions": len(self._interactions),
            "patterns": len(self._patterns),
            "embedder": self.embedder is not None,
        }
