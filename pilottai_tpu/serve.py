"""Serve: the top-level orchestrator.

Reference parity: ``pilott/pilott.py`` (697 LoC) — task intake with
LLM analysis (``:184-221,569-601``), LLM decomposition into dependent
subtasks (``:203,427-458``), bounded-concurrency processor loop
(``:272-303``), agent selection → execution → LLM evaluation → retry
(``:305-331,488-551``), queue overflow eviction (``:249-270``), cleanup/
retention loop (``:358-367``), metrics (``:397-407``), callbacks (``:668``).

Fixes over the reference (SURVEY §2.12-a): ONE coherent API supporting both
constructor-injected agents and dynamic ``add_agent`` + ``execute_task``;
priorities compare numerically; subtask dependency scheduling is real
(BLOCKED tasks wait for their deps, failed deps cascade); side services
(balancer/scaler/fault-tolerance) attach to the same lifecycle instead of
floating unwired (§3.1).
"""

from __future__ import annotations

import asyncio
import heapq
import itertools
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
from pilottai_tpu.core.memory import Memory
from pilottai_tpu.core.router import TaskRouter
from pilottai_tpu.core.task import Task, TaskPriority, TaskResult, TaskStatus
from pilottai_tpu.obs.dag import global_dag
from pilottai_tpu.prompts.manager import PromptManager
from pilottai_tpu.prompts.schemas import schema_for
from pilottai_tpu.utils.json_utils import coerce_bool, extract_json
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics
from pilottai_tpu.utils.tracing import global_tracer


def _engine_health_snapshot() -> Dict[str, Any]:
    """Engine fault-domain summary for Serve.get_metrics: watchdog
    verdict + capability-ladder rung, from the process-global registries
    (no engine reference needed — the orchestrator may be remote from
    the device)."""
    from pilottai_tpu.reliability import global_engine_health

    snap = global_engine_health.snapshot()
    return {
        "stalled": snap["stalled"],
        **({"reason": snap["reason"]} if snap["stalled"] else {}),
        "degrade_level": global_metrics.get("engine.degrade_level"),
        "rebuilds": global_metrics.get("engine.rebuilds"),
    }

TaskCallback = Callable[[Task, TaskResult], Any]


class PriorityTaskQueue:
    """Bounded max-priority queue with lowest-priority eviction.

    The reference peeked ``asyncio.Queue``'s private ``_queue`` and compared
    string priorities lexicographically to evict (``pilott.py:249-270``,
    §2.12-h); this is the intended behavior done properly.
    """

    def __init__(self, maxsize: int) -> None:
        self.maxsize = maxsize
        self._heap: List[tuple] = []  # (-priority, seq, task)
        self._ids: Dict[str, Task] = {}
        self._seq = itertools.count()
        self._not_empty = asyncio.Condition()

    def __len__(self) -> int:
        return len(self._ids)

    async def put(self, task: Task) -> Optional[Task]:
        """Insert; returns an evicted lower-priority task when full, or
        raises if ``task`` itself is the lowest priority."""
        evicted: Optional[Task] = None
        async with self._not_empty:
            if len(self._ids) >= self.maxsize:
                worst = min(
                    (t for t in self._ids.values()), key=lambda t: t.priority
                )
                if worst.priority >= task.priority:
                    raise asyncio.QueueFull(
                        f"queue full and task priority {task.priority.name} "
                        "does not outrank queued work"
                    )
                self._ids.pop(worst.id)
                worst.mark_cancelled()
                evicted = worst
            self._ids[task.id] = task
            heapq.heappush(self._heap, (-int(task.priority), next(self._seq), task))
            task.mark_queued()
            # Queue residency opens here and closes at get(): the DAG
            # ledger turns the pair into a "queue" node and the
            # queue-wait-by-priority histograms.
            global_dag.queue_enter(task.id, task.priority.name)
            self._not_empty.notify()
        return evicted

    async def get(self, timeout: Optional[float] = None) -> Optional[Task]:
        async with self._not_empty:
            if not self._ids:
                try:
                    await asyncio.wait_for(self._not_empty.wait(), timeout=timeout)
                except asyncio.TimeoutError:
                    return None
            while self._heap:
                _, _, task = heapq.heappop(self._heap)
                if task.id in self._ids:  # skip tombstones (evicted/removed)
                    self._ids.pop(task.id)
                    global_dag.queue_exit(task.id)
                    return task
            return None

    def remove(self, task_id: str) -> Optional[Task]:
        return self._ids.pop(task_id, None)

    def snapshot(self) -> List[Task]:
        return list(self._ids.values())


class Serve:
    """Hierarchical multi-agent orchestrator (the package's front door)."""

    def __init__(
        self,
        name: str = "pilott-tpu",
        agents: Optional[List[BaseAgent]] = None,
        config: Optional[ServeConfig | Dict[str, Any]] = None,
        manager_llm: Optional[Any] = None,       # LLMHandler for manager path
        llm_config: Optional[LLMConfig] = None,  # or build one from config
        manager_agent: Optional[BaseAgent] = None,
        task_callback: Optional[TaskCallback] = None,
    ) -> None:
        if isinstance(config, dict):
            config = ServeConfig(**config)
        self.config = config or ServeConfig(name=name)
        self.name = name or self.config.name
        self.agents: Dict[str, BaseAgent] = {}
        for agent in agents or []:
            # _wire_agent only binds methods; nothing it touches is
            # evaluated until the callbacks actually fire.
            self._wire_agent(agent)
            self.agents[agent.id] = agent
        self.manager_agent = manager_agent
        if manager_llm is None and llm_config is not None:
            from pilottai_tpu.engine.handler import LLMHandler

            manager_llm = LLMHandler(llm_config)
        self.manager_llm = manager_llm
        self.task_callback = task_callback

        self.router = TaskRouter()
        self.memory = Memory()
        self.prompts = PromptManager("orchestrator")

        self.task_queue = PriorityTaskQueue(self.config.max_queue_size)
        self.all_tasks: Dict[str, Task] = {}
        self.running_tasks: Dict[str, Task] = {}
        self.completed_tasks: Dict[str, Task] = {}
        self.failed_tasks: Dict[str, Task] = {}
        self._blocked: Dict[str, Task] = {}
        self._waiters: Dict[str, asyncio.Future] = {}
        self._parent_children: Dict[str, List[str]] = {}
        # Live task-event feeds (subscribe_events): task_id → queues.
        # Subtask events roll up to the parent's subscribers too.
        self._event_subs: Dict[str, List[asyncio.Queue]] = {}

        self.metrics: Dict[str, float] = {
            "tasks_received": 0, "tasks_completed": 0, "tasks_failed": 0,
            "tasks_retried": 0, "tasks_evicted": 0, "subtasks_created": 0,
        }
        self._running = False
        self._bg_tasks: List[asyncio.Task] = []
        # Strong refs for fire-and-forget tasks: the loop only keeps weak
        # refs, so un-referenced tasks can be garbage-collected mid-run.
        self._inflight: set = set()
        self._exec_semaphore = asyncio.Semaphore(self.config.max_concurrent_tasks)
        self._log = get_logger("serve", serve_name=self.name)

        # Integrated side services (attached in start() when enabled).
        self.load_balancer = None
        self.dynamic_scaling = None
        self.fault_tolerance = None
        self.delegator = None

        # Durable task journal (crash/preemption recovery, SURVEY §5.4).
        self.journal = None
        if self.config.journal_path:
            from pilottai_tpu.checkpoint.journal import TaskJournal

            self.journal = TaskJournal(
                self.config.journal_path, fsync=self.config.journal_fsync
            )

    # ------------------------------------------------------------------ #
    # Agent management (both API styles, fixing §2.12-a)
    # ------------------------------------------------------------------ #

    def add_agent(self, agent: BaseAgent) -> None:
        if agent.id in self.agents:
            raise ValueError(f"agent {agent.id} already added")
        self._wire_agent(agent)
        self.agents[agent.id] = agent
        self.router.invalidate()

    def _wire_agent(self, agent: BaseAgent) -> None:
        """Attach orchestrator plumbing an agent needs: dependency
        lookups and (unless the user installed their own) a step
        callback feeding the task event bus. ``getattr`` with a
        non-None sentinel: proxy agents (``distributed/control_plane.py``
        RemoteAgent) don't carry these hooks at all — leave them alone
        (their steps happen on the worker host)."""
        if getattr(agent, "dependency_resolver", True) is None:
            agent.dependency_resolver = self.get_task
        if getattr(agent, "step_callback", True) is None:
            agent.step_callback = self._agent_step_event

    def _agent_step_event(self, task_id: str, info: Dict[str, Any]) -> None:
        task = self.all_tasks.get(task_id)
        self._emit_event(task if task is not None else task_id, "step", **info)

    async def remove_agent(self, agent_id: str) -> Optional[BaseAgent]:
        agent = self.agents.pop(agent_id, None)
        if agent is not None:
            await agent.stop()
            self.router.invalidate(agent_id)
        return agent

    async def create_agent(
        self, agent_type: str = "worker", config: Optional[AgentConfig] = None,
        **kwargs: Any,
    ) -> BaseAgent:
        """Factory hook used by DynamicScaling (reference ``scaling`` calls
        ``orchestrator.create_agent``, §2.12-b)."""
        from pilottai_tpu.core.factory import AgentFactory

        if "llm" not in kwargs and self.manager_llm is not None:
            kwargs["llm"] = self.manager_llm
        kwargs.setdefault("dependency_resolver", self.get_task)
        agent = await AgentFactory.create_agent(agent_type, config, **kwargs)
        self.add_agent(agent)
        return agent

    def agent_list(self) -> List[BaseAgent]:
        return list(self.agents.values())

    # ------------------------------------------------------------------ #
    # Lifecycle (reference ``pilott.py:122-182``)
    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        if self._running:
            return
        self._running = True
        if self.manager_llm is not None:
            await self.manager_llm.start()
        for agent in self.agents.values():
            agent.dependency_resolver = agent.dependency_resolver or self.get_task
            await agent.start()
        if self.journal is not None:
            self.journal.reopen()  # no-op unless a prior stop() closed it
            if self.config.journal_recover:
                await self.recover()
        self._bg_tasks = [
            asyncio.create_task(self._process_tasks(), name="serve-processor"),
            asyncio.create_task(self._cleanup_loop(), name="serve-cleanup"),
        ]
        await self._start_services()
        self._log.info("serve started with %d agents", len(self.agents))

    async def recover(self) -> int:
        """Replay the journal and requeue unfinished work.

        Recovery semantics are at-least-once: tasks that were queued or in
        flight when the process died rerun from scratch (their results were
        never journaled). Decomposed parents are NOT re-queued — their
        parent/child links are restored and they complete when their
        surviving children do. Returns the number of tasks requeued.
        """
        from pilottai_tpu.checkpoint.journal import TaskJournal

        tasks = TaskJournal.replay(self.journal.path)
        requeued = 0
        for task in tasks.values():
            known = task.id in self.all_tasks
            self.all_tasks.setdefault(task.id, task)
            if known:
                continue
            if task.status == TaskStatus.COMPLETED:
                self.completed_tasks[task.id] = task
            elif task.status.is_terminal:
                self.failed_tasks[task.id] = task
            elif task.subtasks and all(c in tasks for c in task.subtasks):
                self._parent_children[task.id] = list(task.subtasks)
                task.status = TaskStatus.BLOCKED  # waits on recovered children
            elif task.subtasks:
                # Some children never reached the journal (crash mid-
                # decomposition) or were compacted away — aggregating now
                # would silently lose their outputs. Re-run the parent from
                # scratch instead (at-least-once).
                task.subtasks = []
                task.status = TaskStatus.PENDING
                task.agent_id = None
                await self._queue_task(task)
                requeued += 1
            else:
                task.status = TaskStatus.PENDING
                task.agent_id = None
                await self._queue_task(task)
                requeued += 1
        # A recovered parent whose children all finished pre-crash would
        # otherwise wait forever — re-run the aggregation check now.
        for task in tasks.values():
            if task.subtasks and not task.status.is_terminal:
                await self._check_parent(task.id)
        if requeued:
            self._log.info(
                "journal recovery: %d tasks requeued (%d total in journal)",
                requeued, len(tasks),
            )
        # Compact so the next boot replays only live work.
        self.journal.compact()
        return requeued

    async def _start_services(self) -> None:
        if self.config.load_balancing_enabled:
            from pilottai_tpu.orchestration.load_balancer import LoadBalancer

            self.load_balancer = LoadBalancer(self)
            await self.load_balancer.start()
        if self.config.dynamic_scaling_enabled:
            from pilottai_tpu.orchestration.scaling import DynamicScaling

            self.dynamic_scaling = DynamicScaling(self)
            await self.dynamic_scaling.start()
        if self.config.fault_tolerance_enabled:
            from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance

            self.fault_tolerance = FaultTolerance(self)
            await self.fault_tolerance.start()
        if self.config.delegation_enabled and self.manager_agent is not None:
            from pilottai_tpu.delegation.delegator import TaskDelegator

            # Serve-level enablement implies the manager's own gate: the
            # delegator checks agent.config.delegation_enabled
            # (_should_delegate), and one switch must mean one behavior.
            self.manager_agent.config.delegation_enabled = True
            self.delegator = TaskDelegator(self.manager_agent)
            self._log.info(
                "delegation attached (manager=%s, children=%d)",
                self.manager_agent.id[:8], len(self.manager_agent.child_agents),
            )

    async def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        for service in (self.load_balancer, self.dynamic_scaling, self.fault_tolerance):
            if service is not None:
                await service.stop()
        for bg in self._bg_tasks:
            bg.cancel()
        await asyncio.gather(*self._bg_tasks, return_exceptions=True)
        self._bg_tasks = []
        # Settle in-flight executions before the journal closes: a task
        # finishing after close would hit record_status on a closed journal
        # inside _finalize and strand its waiter.
        inflight = list(self._inflight)
        for t in inflight:
            t.cancel()
        await asyncio.gather(*inflight, return_exceptions=True)
        # Cancellation skips _finalize (CancelledError is a BaseException),
        # so journal the interruption and resolve outstanding waiters —
        # a wait_for with no timeout must not hang across stop().
        for task in list(self.running_tasks.values()):
            if not task.status.is_terminal:
                task.status = TaskStatus.CANCELLED
                if self.journal is not None:
                    self.journal.record_status(task)
        stopped = TaskResult(success=False, error="serve stopped")
        for waiter in self._waiters.values():
            if not waiter.done():
                waiter.set_result(stopped)
        # Settle DAG records of work that will never reach _finalize —
        # an active ledger entry for a dead task would pin task.active
        # and leak until process exit.
        for task in (
            list(self.running_tasks.values())
            + self.task_queue.snapshot()
            + list(self._blocked.values())
        ):
            global_dag.finish(task.id, "cancelled")
        for agent in self.agents.values():
            await agent.stop()
        if self.manager_llm is not None:
            await self.manager_llm.stop()
        if self.journal is not None:
            self.journal.close()
        self._log.info("serve stopped")

    # ------------------------------------------------------------------ #
    # Task intake (reference ``pilott.py:184-270``; stack §3.2)
    # ------------------------------------------------------------------ #

    def _coerce_task(self, task: Task | Dict[str, Any] | str) -> Task:
        if isinstance(task, Task):
            return task
        if isinstance(task, str):
            return Task(description=task)
        data = dict(task)
        if "description" not in data:
            data["description"] = data.pop("task", None) or str(data)
        known = set(Task.model_fields)
        payload = {k: v for k, v in data.items() if k not in known}
        kwargs = {k: v for k, v in data.items() if k in known}
        if payload:
            kwargs.setdefault("payload", {}).update(payload)
        return Task(**kwargs)

    def prepare_task(self, task: Task | Dict[str, Any] | str) -> Task:
        """Coerce to a ``Task`` WITHOUT submitting — lets a caller
        ``subscribe_events(task.id)`` before ``add_task`` so no lifecycle
        event is missed (the API server's SSE task stream does this)."""
        return self._coerce_task(task)

    # ------------------------------------------------------------------ #
    # Task event feed (observability, SURVEY §5.5): every lifecycle
    # transition — received/analyzed/decomposed/queued/assigned/step/
    # retry/completed — is emitted to subscribers of the task AND of its
    # parent (so one subscription watches a whole decomposition).
    # ------------------------------------------------------------------ #

    def subscribe_events(
        self, task_id: str, max_buffer: int = 256
    ) -> asyncio.Queue:
        """Live event feed for ``task_id`` (and its subtasks). Slow
        consumers lose OLDEST events (drop-oldest ring), never block the
        orchestrator."""
        q: asyncio.Queue = asyncio.Queue(maxsize=max_buffer)
        self._event_subs.setdefault(task_id, []).append(q)
        return q

    def unsubscribe_events(self, task_id: str, q: asyncio.Queue) -> None:
        subs = self._event_subs.get(task_id)
        if subs and q in subs:
            subs.remove(q)
            if not subs:
                self._event_subs.pop(task_id, None)

    def _emit_event(self, task: Task | str, event: str, **data: Any) -> None:
        tid = task if isinstance(task, str) else task.id
        ts = time.time()
        # One clock for both surfaces: the DAG ledger's lifecycle marks
        # carry the same timestamp the event payload does, so the event
        # stream and the ledger stay order-consistent by construction
        # (first stamp wins on repeated events like step/retry).
        global_dag.mark(tid, event, at=ts)
        if not self._event_subs:
            return
        parent = None if isinstance(task, str) else task.parent_task_id
        payload = {"event": event, "task_id": tid, "ts": ts, **data}
        for key in {tid, parent} - {None}:
            for q in self._event_subs.get(key, ()):
                try:
                    q.put_nowait(payload)
                except asyncio.QueueFull:
                    try:
                        q.get_nowait()
                    except asyncio.QueueEmpty:
                        pass
                    try:
                        q.put_nowait(payload)
                    except asyncio.QueueFull:
                        pass

    def _task_trace(self, task: Task) -> str:
        """The task's trace id, stamped once in ``metadata`` at intake:
        adopted from the ambient span when one is live (the HTTP edge's
        ``server.request``) and minted otherwise. Every span the task's
        execution opens — across the processor's separate asyncio tasks,
        retries and requeues — seeds from THIS id, so one task is one
        Perfetto tree instead of a fresh trace per scheduling hop."""
        trace = task.metadata.get("trace_id")
        if not trace:
            ambient = global_tracer.current()
            trace = (
                ambient.trace_id if ambient is not None
                else uuid.uuid4().hex[:16]
            )
            task.metadata["trace_id"] = trace
        return trace

    async def add_task(self, task: Task | Dict[str, Any] | str) -> Task:
        """Analyze, maybe decompose, and queue. Returns the (parent) Task."""
        task = self._coerce_task(task)
        self.all_tasks[task.id] = task
        self.metrics["tasks_received"] += 1
        self._waiters.setdefault(task.id, asyncio.get_running_loop().create_future())
        global_dag.start(
            task.id, trace_id=self._task_trace(task),
            parent_task_id=task.parent_task_id,
            type=task.type, priority=task.priority.name,
        )
        self._emit_event(task, "received", description=task.description[:200])

        with global_dag.span(task.id, "stage", "analyze"):
            analysis = await self._analyze_task(task)
        self._emit_event(
            task, "analyzed",
            complexity=task.complexity,
            requires_decomposition=coerce_bool(
                analysis.get("requires_decomposition", False)
            ),
        )
        if (
            self.config.decomposition_enabled
            and coerce_bool(analysis.get("requires_decomposition", False))
        ):
            await self._handle_complex_task(task, analysis)
        else:
            await self._queue_task(task)
        return task

    async def _queue_task(self, task: Task) -> None:
        if self.journal is not None:
            self.journal.record_task(task)
        self._emit_event(task, "queued", priority=str(task.priority))
        try:
            evicted = await self.task_queue.put(task)
        except asyncio.QueueFull:
            task.mark_failed("queue full")
            self._finalize(task, TaskResult(success=False, error="queue full"))
            return
        if evicted is not None:
            self.metrics["tasks_evicted"] += 1
            self._finalize(
                evicted,
                TaskResult(success=False, error="evicted by higher-priority task"),
            )

    def _spawn(self, coro) -> asyncio.Task:
        """create_task with a strong reference until completion."""
        t = asyncio.ensure_future(coro)
        self._inflight.add(t)
        t.add_done_callback(self._inflight.discard)
        return t

    async def _analyze_task(self, task: Task) -> Dict[str, Any]:
        """Manager-LLM analysis (reference ``:569-601``); graceful default
        when no manager LLM is configured. Skipped entirely when
        decomposition is disabled — the analysis' only consumer is the
        decomposition gate, so the LLM round-trip would be wasted."""
        if self.manager_llm is None or not self.config.decomposition_enabled:
            return {"requires_decomposition": False, "complexity": task.complexity}
        prompt = self.prompts.format_prompt("task_analysis", task=task.to_prompt())
        try:
            content = await self.manager_llm.apredict(
                prompt, json_mode=True,
                json_schema=schema_for("orchestrator", "task_analysis"),
            )
            data = extract_json(content) or {}
        except Exception as exc:  # noqa: BLE001 - analysis is advisory
            self._log.warning("task analysis failed: %s", exc)
            return {"requires_decomposition": False, "complexity": task.complexity}
        complexity = data.get("complexity", task.complexity)
        if isinstance(complexity, (int, float)) and 1 <= complexity <= 10:
            task.complexity = int(complexity)
        return data

    async def _handle_complex_task(self, task: Task, analysis: Dict[str, Any]) -> None:
        """LLM decomposition into dependent subtasks (reference ``:427-458``)."""
        prompt = self.prompts.format_prompt("task_decomposition", task=task.to_prompt())
        try:
            with global_dag.span(task.id, "stage", "decompose"):
                content = await self.manager_llm.apredict(
                    prompt, json_mode=True,
                    json_schema=schema_for("orchestrator", "task_decomposition"),
                )
            data = extract_json(content) or {}
            raw_subtasks = data.get("subtasks") or []
        except Exception as exc:  # noqa: BLE001 - fall back to simple path
            self._log.warning("decomposition failed (%s); queueing as simple", exc)
            raw_subtasks = []
        if not raw_subtasks:
            await self._queue_task(task)
            return

        subtasks: List[Task] = []
        for spec in raw_subtasks:
            sub = Task(
                description=spec.get("description", task.description),
                type=spec.get("type", task.type),
                priority=TaskPriority.coerce(spec.get("priority", task.priority)),
                parent_task_id=task.id,
                payload=task.payload,
                # Inherit the parent's budget only when one was explicitly
                # set — passing the 300s default through would mark every
                # subtask as explicitly-budgeted and re-cap deployments
                # that raised config.task_timeout.
                **(
                    {"timeout": task.timeout}
                    if "timeout" in task.model_fields_set else {}
                ),
            )
            deps = spec.get("depends_on", []) or []
            sub.dependencies = [
                subtasks[i].id for i in deps if isinstance(i, int) and i < len(subtasks)
            ]
            subtasks.append(sub)
        # Gang-tag the independent siblings (pilottai_tpu/sched/): the
        # fan-out branches with no intra-decomposition dependencies all
        # become runnable at once, and their first-stage LLM calls
        # should admit to the engine as a group — the batcher holds a
        # bounded wait for the whole gang so one branch's analysis
        # doesn't straggle behind unrelated backlog while its siblings
        # finish (the join waits for the slowest branch either way).
        independent = [s for s in subtasks if not s.dependencies]
        if len(independent) >= 2:
            gang_id = f"gang-{task.id[:8]}"
            for s in independent:
                s.metadata["gang_id"] = gang_id
                s.metadata["gang_size"] = len(independent)
        task.subtasks = [s.id for s in subtasks]
        self._parent_children[task.id] = [s.id for s in subtasks]
        self._emit_event(task, "decomposed", subtasks=[s.id for s in subtasks])
        task.status = TaskStatus.BLOCKED
        if self.journal is not None:  # parents never pass through _queue_task
            self.journal.record_task(task)
        self.metrics["subtasks_created"] += len(subtasks)
        trace = self._task_trace(task)
        for sub in subtasks:
            self.all_tasks[sub.id] = sub
            # One task tree = one trace: delegated subtasks inherit the
            # parent's trace id, and each gets its own DAG record whose
            # finish rolls up into the parent's (with the dependency
            # edges the scheduler runs on).
            sub.metadata["trace_id"] = trace
            global_dag.start(
                sub.id, trace_id=trace, parent_task_id=task.id,
                type=sub.type, priority=sub.priority.name,
                dependencies=list(sub.dependencies),
            )
            self._waiters.setdefault(
                sub.id, asyncio.get_running_loop().create_future()
            )
            await self._queue_task(sub)

    # ------------------------------------------------------------------ #
    # Execution API (reference §2.12-a: exposed by README/tests but absent
    # on the real class; first-class here)
    # ------------------------------------------------------------------ #

    async def execute_task(
        self, task: Task | Dict[str, Any] | str, timeout: Optional[float] = None
    ) -> TaskResult:
        """Submit and wait for the final result. An explicit ``timeout``
        is the caller's end-to-end budget: it bounds the wait AND is
        threaded into ``task.timeout`` so the execution side (processor
        ``wait_for``, decomposed subtasks, agents' stuck-task checks)
        honors the same deadline instead of running to the config default
        long after the caller gave up."""
        task = self._coerce_task(task)
        if timeout is not None:
            task.timeout = min(task.timeout, timeout)
        task = await self.add_task(task)
        return await self.wait_for(task.id, timeout=timeout)

    async def execute(
        self, tasks: List[Task | Dict[str, Any] | str]
    ) -> List[TaskResult]:
        submitted = [await self.add_task(t) for t in tasks]
        return list(
            await asyncio.gather(*[self.wait_for(t.id) for t in submitted])
        )

    async def wait_for(self, task_id: str, timeout: Optional[float] = None) -> TaskResult:
        # Already-terminal tasks (e.g. recovered from the journal in a
        # finished state) resolve immediately — no _finalize will ever fire
        # for them in this process. CANCELLED/evicted tasks are journaled
        # with result=null: synthesize a result rather than hanging on a
        # waiter that can never fire.
        task = self.all_tasks.get(task_id)
        if task is not None and task.status.is_terminal:
            if task.result is not None:
                return task.result
            return TaskResult(
                success=False,
                error=f"task {task_id} recovered in terminal state "
                      f"{task.status.value} with no recorded result",
            )
        future = self._waiters.setdefault(
            task_id, asyncio.get_running_loop().create_future()
        )
        return await asyncio.wait_for(
            asyncio.shield(future), timeout=timeout or self.config.task_timeout * 4
        )

    async def requeue_task(
        self, task: Task, reason: str = "requeue", **dag_attrs: Any
    ) -> None:
        """Put a detached task back through orchestrator routing (used by
        the load balancer's last-resort rollback and fault-tolerance
        recovery). The task keeps its stamped trace id and its DAG
        record — the requeue lands as a ``retry`` node (with the
        caller's attribution, e.g. heartbeat stall seconds) instead of
        restarting the trace."""
        task.status = TaskStatus.PENDING
        task.agent_id = None
        self.all_tasks.setdefault(task.id, task)
        global_dag.start(
            task.id, trace_id=self._task_trace(task),
            parent_task_id=task.parent_task_id,
            type=task.type, priority=task.priority.name,
        )
        now = time.perf_counter()
        global_dag.record(
            task.id, "retry", reason, start=now, end=now, **dag_attrs
        )
        await self._queue_task(task)

    def get_task(self, task_id: str) -> Optional[Task]:
        return self.all_tasks.get(task_id)

    def get_result(self, task_id: str) -> Optional[TaskResult]:
        task = self.all_tasks.get(task_id)
        return task.result if task else None

    # ------------------------------------------------------------------ #
    # Processor loop (reference ``:272-356``; stack §3.3)
    # ------------------------------------------------------------------ #

    async def _process_tasks(self) -> None:
        while self._running:
            try:
                task = await self.task_queue.get(timeout=0.2)
                if task is None:
                    continue
                if task.status == TaskStatus.CANCELLED:
                    continue
                ready, failed_dep = self._deps_state(task)
                if failed_dep is not None:
                    self._finalize(
                        task,
                        TaskResult(
                            success=False,
                            error=f"dependency {failed_dep} failed",
                        ),
                    )
                    continue
                if not ready:
                    task.status = TaskStatus.BLOCKED
                    self._blocked[task.id] = task
                    continue
                self._spawn(self._execute_with_limit(task))
            except asyncio.CancelledError:
                raise
            except Exception as exc:  # noqa: BLE001 - keep the loop alive
                self._log.error("processor loop error: %s", exc, exc_info=True)
                await asyncio.sleep(0.1)

    def _deps_state(self, task: Task) -> tuple:
        """(all_completed, first_failed_dep_or_None)."""
        for dep_id in task.dependencies:
            dep = self.all_tasks.get(dep_id)
            if dep is None:
                continue
            if dep.status in (TaskStatus.FAILED, TaskStatus.CANCELLED):
                return False, dep_id
            if dep.status != TaskStatus.COMPLETED:
                return False, None
        return True, None

    async def _execute_with_limit(self, task: Task) -> None:
        # An EXPLICIT per-task timeout (execute_task's caller budget, or
        # set on the Task at construction) tightens the orchestrator
        # default, never loosens it. Explicitness matters: Task.timeout
        # has a non-None default (300s) that would otherwise silently cap
        # a deployment's raised config.task_timeout.
        budget = self.config.task_timeout
        if "timeout" in task.model_fields_set:
            budget = min(budget, task.timeout)
        async with self._exec_semaphore:
            try:
                await asyncio.wait_for(
                    self._execute_task(task), timeout=budget
                )
            except asyncio.TimeoutError:
                self._finalize(
                    task,
                    TaskResult(
                        success=False,
                        error=f"orchestrator timeout after {budget}s",
                    ),
                )
            except Exception as exc:  # noqa: BLE001 - task boundary
                self._log.error("execution error for %s: %s", task.id[:8], exc)
                self._finalize(task, TaskResult(success=False, error=str(exc)))

    async def _execute_task(self, task: Task) -> None:
        # trace_id from the task's stamped trace: execution runs in a
        # processor-spawned asyncio task with NO ambient span, so without
        # it every scheduling hop would mint a fresh trace and the
        # server → orchestrator → agent → engine tree would split here.
        with global_tracer.span(
            "serve.execute_task", task_id=task.id,
            trace_id=self._task_trace(task),
        ), global_dag.span(task.id, "stage", "execute", trace=False):
            with global_dag.span(task.id, "stage", "route"):
                agent = await self._select_agent(task)
            if agent is None:
                self._finalize(
                    task, TaskResult(success=False, error="no available agent")
                )
                return
            self.running_tasks[task.id] = task
            self._emit_event(
                task, "assigned",
                agent_id=agent.id, agent_role=agent.config.role,
            )
            try:
                result = await agent.execute_task(task)
                result = await self._maybe_retry(task, result)
                if (
                    self.delegator is not None
                    and task.metadata.get("delegation") is not None
                ):
                    # Outcome feedback closes the loop: future scoring
                    # prefers children that actually deliver. Recorded
                    # AFTER retries settle — a child that recovers via
                    # the framework's own retry path must not be scored
                    # as a failure (the retry may land on another agent;
                    # task.agent_id tracks the final executor).
                    await self.delegator.record_delegation(
                        task.agent_id or agent.id, task, result.success,
                        execution_time=result.execution_time,
                        error=result.error,
                    )
            finally:
                self.running_tasks.pop(task.id, None)
            self._finalize(task, result)

    async def _select_agent(self, task: Task) -> Optional[BaseAgent]:
        """Delegation gate first (when attached), then the manager hook,
        then the router (reference ``:488-504`` +
        ``delegation/task_delegator.py:41-111`` semantics)."""
        candidates = self.agent_list()
        if self.delegator is not None:
            target, reason = await self.delegator.evaluate_delegation(task)
            if target is not None:
                task.metadata["delegation"] = {
                    "by": self.manager_agent.id, "reason": reason,
                }
                self._emit_event(
                    task, "delegated", agent_id=target.id, reason=reason
                )
                return target
        if self.manager_agent is not None:
            chosen = await self.manager_agent.select_agent(task, candidates)
            if chosen is not None:
                return chosen
        return await self.router.route_task(task, candidates)

    async def _maybe_retry(self, task: Task, result: TaskResult) -> TaskResult:
        """LLM evaluation + bounded retry (reference ``:506-551``)."""
        needs_retry = not result.success
        if (
            result.success
            and self.config.evaluation_enabled
            and self.manager_llm is not None
        ):
            try:
                prompt = self.prompts.format_prompt(
                    "result_evaluation",
                    task=task.to_prompt(),
                    agent_id=task.agent_id or "unknown",
                    result=str(result.output)[:2000],
                )
                with global_dag.span(task.id, "stage", "evaluate"):
                    evaluation = extract_json(
                        await self.manager_llm.apredict(
                            prompt, json_mode=True,
                            json_schema=schema_for(
                                "orchestrator", "result_evaluation"
                            ),
                        )
                    ) or {}
                needs_retry = coerce_bool(evaluation.get("requires_retry", False))
                result.metadata["orchestrator_evaluation"] = evaluation
            except Exception as exc:  # noqa: BLE001 - evaluation is advisory
                self._log.warning("result evaluation failed: %s", exc)
        retries = 0
        while needs_retry and retries < self.config.max_retry_attempts:
            if not task.prepare_retry():
                break
            retries += 1
            self.metrics["tasks_retried"] += 1
            agent = await self._select_agent(task)
            if agent is None:
                break
            self._emit_event(task, "retry", attempt=retries, agent_id=agent.id)
            task.mark_started(agent_id=agent.id)
            # Retry attempts are CHILD spans of the task's single trace
            # (attempt index as attribute) — one task, one Perfetto
            # tree, retries included; restarting the ambient trace here
            # used to orphan every post-retry span.
            with global_dag.span(
                task.id, "retry", f"attempt-{retries}",
                attempt=retries, agent_id=agent.id[:8],
            ):
                result = await agent.execute_task(task)
            needs_retry = not result.success
        return result

    # ------------------------------------------------------------------ #
    # Completion plumbing
    # ------------------------------------------------------------------ #

    def _finalize(self, task: Task, result: TaskResult) -> None:
        if result.success:
            if task.status != TaskStatus.COMPLETED:
                task.mark_completed(result)
            self.completed_tasks[task.id] = task
            self.metrics["tasks_completed"] += 1
        else:
            if task.status not in (TaskStatus.FAILED, TaskStatus.CANCELLED):
                task.mark_failed(result.error or "failed", result)
            self.failed_tasks[task.id] = task
            self.metrics["tasks_failed"] += 1

        if self.journal is not None:
            self.journal.record_status(task)

        self._emit_event(
            task, "completed" if result.success else "failed",
            success=result.success, error=result.error,
            execution_time=result.execution_time,
        )

        # Close the task's DAG: critical path + breakdown computed here,
        # task.* histograms observed, subtask records rolled up into the
        # parent's dag (when one is still active).
        global_dag.finish(
            task.id,
            "ok" if result.success else (
                "cancelled" if task.status == TaskStatus.CANCELLED
                else "failed"
            ),
        )

        waiter = self._waiters.get(task.id)
        if waiter is not None and not waiter.done():
            waiter.set_result(result)

        self._spawn(self._post_completion(task, result))

    async def _post_completion(self, task: Task, result: TaskResult) -> None:
        # Memory record (reference ``:653-666``).
        try:
            await self.memory.store(
                {
                    "task_id": task.id,
                    "type": task.type,
                    "success": result.success,
                    "agent_id": task.agent_id,
                    "execution_time": result.execution_time,
                },
                tags={"task_execution", task.type},
            )
        except Exception:  # noqa: BLE001 - memory is best-effort
            pass
        # Callback (reference ``:668-676``).
        if self.task_callback is not None:
            try:
                maybe = self.task_callback(task, result)
                if asyncio.iscoroutine(maybe):
                    await maybe
            except Exception as exc:  # noqa: BLE001
                self._log.warning("task callback failed: %s", exc)
        # Unblock dependents.
        self._requeue_unblocked()
        # Parent aggregation.
        if task.parent_task_id:
            await self._check_parent(task.parent_task_id)

    def _requeue_unblocked(self) -> None:
        for tid in list(self._blocked):
            task = self._blocked[tid]
            ready, failed_dep = self._deps_state(task)
            if failed_dep is not None:
                del self._blocked[tid]
                self._finalize(
                    task,
                    TaskResult(success=False, error=f"dependency {failed_dep} failed"),
                )
            elif ready:
                del self._blocked[tid]
                task.status = TaskStatus.PENDING
                self._spawn(self._queue_task(task))

    async def _check_parent(self, parent_id: str) -> None:
        children_ids = self._parent_children.get(parent_id)
        parent = self.all_tasks.get(parent_id)
        if not children_ids or parent is None or parent.status.is_terminal:
            return
        children = [self.all_tasks[c] for c in children_ids if c in self.all_tasks]
        if any(t.status in (TaskStatus.FAILED, TaskStatus.CANCELLED) for t in children):
            failed = [t.id for t in children if t.status == TaskStatus.FAILED]
            self._finalize(
                parent,
                TaskResult(success=False, error=f"subtasks failed: {failed}"),
            )
            return
        if all(t.status == TaskStatus.COMPLETED for t in children):
            outputs = [
                t.result.output if t.result else None for t in children
            ]
            self._finalize(
                parent,
                TaskResult(
                    success=True,
                    output=outputs,
                    metadata={"subtask_ids": children_ids},
                ),
            )

    # ------------------------------------------------------------------ #
    # Cleanup / retention (reference ``:358-367``)
    # ------------------------------------------------------------------ #

    async def _cleanup_loop(self) -> None:
        while self._running:
            await asyncio.sleep(self.config.cleanup_interval)
            self.cleanup_once()

    def cleanup_once(self) -> int:
        cutoff = time.time() - self.config.task_retention
        dropped = 0
        for store in (self.completed_tasks, self.failed_tasks):
            for tid in list(store):
                task = store[tid]
                if task.completed_at is not None and task.completed_at < cutoff:
                    del store[tid]
                    self.all_tasks.pop(tid, None)
                    self._waiters.pop(tid, None)
                    self._parent_children.pop(tid, None)
                    dropped += 1
        return dropped

    # ------------------------------------------------------------------ #

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "running": self._running,
            "agents": len(self.agents),
            "queued": len(self.task_queue),
            "blocked": len(self._blocked),
            "running_tasks": len(self.running_tasks),
            **{k: v for k, v in self.metrics.items()},
            "agent_metrics": {
                aid[:8]: a.get_metrics() for aid, a in self.agents.items()
            },
            "engine": (
                self.manager_llm.get_metrics() if self.manager_llm is not None else None
            ),
            # Engine fault-domain surface (reliability/watchdog.py +
            # degrade.py): operators polling the orchestrator see a
            # stalled/degraded engine here without a separate scrape.
            "engine_health": _engine_health_snapshot(),
            # Trailing-60s window, stated explicitly: this is CURRENT
            # throughput (0 after a minute idle), not the run's all-time
            # average — pass window=None for that.
            "steps_per_sec": global_metrics.rate("agent.steps", window=60.0),
        }

    def __repr__(self) -> str:
        return f"<Serve {self.name} agents={len(self.agents)} running={self._running}>"
