"""DagScheduler: priority boosts, gang tags and stage pre-warm.

One process-global instance (``global_scheduler``) sits between the
orchestrator (agents, serve, delegator) and the engine:

* agents ask :meth:`request_hints` before every LLM call — it returns
  the engine-facing ``priority`` (the task's static priority, boosted
  when the task's live remaining critical path dominates the active
  set), the ``gang_id``/``gang_size`` tag for sibling fan-out calls,
  and — as a side effect — records the stage's prompt prefix and fires
  a pre-warm for the PREDICTED next stage;
* engines attach a pre-warm callback (:meth:`attach_prewarm`) at
  start; ``prewarm`` broadcasts a predicted prompt prefix to every
  attached engine, which stages the KV cache tier's restore on its
  prep thread (``ContinuousBatcher.prewarm``). Without an attached
  engine (mock backends, control-plane processes) every pre-warm is a
  cheap no-op.

The scheduler is ADVISORY by design: every method is best-effort and
never raises into the serving path, the engine enforces its own aging
floor against starvation, and ``policy="off"`` reduces every hint to
the task's static priority with no gangs and no pre-warm.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from pilottai_tpu.obs.dag import global_dag
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics

#: Priority lattice bounds (core.task.TaskPriority: LOW=0 … CRITICAL=3).
MIN_PRIORITY, MAX_PRIORITY = 0, 3

#: A criticality estimate below this (seconds) never earns a boost —
#: sub-50 ms remainders are noise against the estimator's EMA clock.
_BOOST_FLOOR_S = 0.05

#: Boost when a task's remaining critical path exceeds this multiple of
#: the median across active tasks: the task IS the path everyone else's
#: join is waiting on.
_BOOST_RATIO = 1.5


class DagScheduler:
    """Advisory DAG-aware scheduler (see module docstring)."""

    def __init__(self, policy: str = "dag") -> None:
        self.policy = policy
        self._lock = threading.Lock()
        self._log = get_logger("sched")
        # Engine pre-warm callbacks, keyed by attach key (the engine's
        # id): fn(prompt_text, session_id) -> None.
        self._prewarm_fns: Dict[Any, Callable[[str, Optional[str]], Any]] = {}
        # Learned stage model per agent role: observed successor stage
        # (the pipeline order analyze → tools → step → evaluate emerges
        # from traffic, never hardcoded) and the latest prompt prefix
        # per (role, stage) — what a pre-warm of that stage restores. A
        # prefix is either plain text or the structured
        # ``{"system": ..., "user": ...}`` form agents pass, which the
        # engine re-renders through the SAME chat framing as a real
        # request so the pre-warmed token prefix byte-matches the
        # admission that follows.
        self._next_stage: Dict[Tuple[str, str], str] = {}
        self._first_stage: Dict[str, str] = {}
        self._stage_prefix: Dict[Tuple[str, str], Any] = {}
        # Observations per (role, stage): the stored prefix CONVERGES to
        # the cross-task common head (template preamble) by repeated
        # merging, and pre-warm only fires once a stage has stabilized
        # (≥2 observations) — pre-warming one task's FULL prompt would
        # whole-restore (and consume) a host entry no other task can
        # prefix-match, hurting instead of helping.
        self._stage_obs: Dict[Tuple[str, str], int] = {}
        # Last stage seen per (task, role) — bounded LRU so abandoned
        # tasks can't grow it without bound.
        self._task_stage: "OrderedDict[Tuple[str, str], str]" = OrderedDict()
        self._task_stage_cap = 512
        #: Characters of prompt head kept per stage (the engine clamps
        #: again to its own token-level ``engine_prewarm_depth``).
        self.prefix_chars = 4096
        # Criticality snapshot cache: priority_for runs on EVERY agent
        # LLM call, and the estimates move on stage timescales
        # (hundreds of ms) — re-walking the ledger per call would put
        # the observability lock on the agent hot path. One snapshot
        # per TTL window serves all calls inside it.
        self._crit_ttl_s = 0.1
        self._crit_at = 0.0
        self._crit_snapshot: Dict[str, float] = {}

    # ------------------------------------------------------------------ #
    # Configuration / engine attachment
    # ------------------------------------------------------------------ #

    def configure(self, policy: Optional[str] = None) -> None:
        if policy is not None:
            if policy not in ("off", "dag"):
                raise ValueError(
                    f"unknown sched policy {policy!r}; supported: "
                    f"'off', 'dag'"
                )
            self.policy = policy

    def attach_prewarm(
        self, key: Any, fn: Callable[[str, Optional[str]], Any]
    ) -> None:
        """Register an engine's pre-warm entry point (NativeEngine does
        this at start and detaches at stop). Bound methods are held
        WEAKLY — the process-global scheduler must never keep a whole
        engine (weights, device cache) alive after its owner dropped it
        without calling stop() (same discipline as the engine-health
        registry's breaker subscriptions)."""
        try:
            ref: Any = weakref.WeakMethod(fn)
        except TypeError:  # plain function / lambda (tests)
            ref = lambda fn=fn: fn  # noqa: E731 — constant deref shim
        with self._lock:
            self._prewarm_fns[key] = ref

    def detach_prewarm(self, key: Any) -> None:
        with self._lock:
            self._prewarm_fns.pop(key, None)

    @property
    def wants_prefix(self) -> bool:
        """Should call sites bother building the pre-warm prefix?
        Only under policy "dag" AND with at least one engine attached —
        mock/external backends and prewarm_depth=0 deployments never
        attach, and rendering tool preambles + merging 4 KB prefixes
        per LLM call with zero consumers is hot-path waste."""
        return self.policy == "dag" and bool(self._prewarm_fns)

    # ------------------------------------------------------------------ #
    # Priority (critical-path boost)
    # ------------------------------------------------------------------ #

    def priority_for(self, task: Any) -> int:
        """The engine-facing priority for ``task``'s LLM calls: its
        static ``Task.priority`` (clamped to the lattice), plus one rung
        when the task's live remaining critical path dominates the
        active set — the slowest branch of a fan-out (or the task a
        deep pipeline is blocked on) preempts backlog ahead of its
        siblings, which is exactly what shrinks the straggler gap."""
        try:
            base = int(getattr(task, "priority", 1))
        except (TypeError, ValueError):
            base = 1
        base = max(MIN_PRIORITY, min(base, MAX_PRIORITY))
        if self.policy != "dag" or base >= MAX_PRIORITY:
            return base
        try:
            task_id = getattr(task, "id", None)
            if task_id is None:
                return base
            now = time.monotonic()
            with self._lock:
                if now - self._crit_at > self._crit_ttl_s:
                    self._crit_snapshot = global_dag.criticalities()
                    self._crit_at = now
                crits = self._crit_snapshot
            crit = crits.get(task_id, 0.0)
            if crit <= _BOOST_FLOOR_S or len(crits) < 2:
                return base
            others = sorted(v for k, v in crits.items() if k != task_id)
            median = others[len(others) // 2]
            if crit >= max(median * _BOOST_RATIO, _BOOST_FLOOR_S):
                global_metrics.inc("sched.priority_boosts")
                return min(base + 1, MAX_PRIORITY)
        except Exception:  # noqa: BLE001 — advisory, never block a call
            pass
        return base

    # ------------------------------------------------------------------ #
    # Request hints (the one call sites make)
    # ------------------------------------------------------------------ #

    def request_hints(
        self,
        task: Any,
        stage: Optional[str] = None,
        *,
        role: Optional[str] = None,
        prompt: Optional[Any] = None,
        session_id: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Engine-facing hints for one LLM call: ``priority`` always
        (the full lattice threads even with the policy off — mapping
        priority only onto slo_class was the lossy path this fixes),
        ``gang_id``/``gang_size`` for the first stage of a tagged
        fan-out sibling, plus the stage-transition side effects (prefix
        learning, next-stage pre-warm) under policy "dag"."""
        hints: Dict[str, Any] = {"priority": self.priority_for(task)}
        if task is None:
            return hints
        meta = getattr(task, "metadata", None) or {}
        gang_id = meta.get("gang_id")
        if (
            self.policy == "dag"
            and gang_id
            and stage is not None
            and role is not None
            and stage == self._first_stage.get(role, stage)
        ):
            # Only the first stage's calls gang: siblings drift apart
            # after it, and ganging desynchronized calls would just
            # burn the gang wait bound on every admission.
            hints["gang_id"] = str(gang_id)
            hints["gang_size"] = int(meta.get("gang_size") or 0)
        if stage is not None and role is not None:
            self.note_stage(
                getattr(task, "id", None), role, stage,
                prompt=prompt, session_id=session_id,
            )
        return hints

    # ------------------------------------------------------------------ #
    # Stage model + speculative pre-warm
    # ------------------------------------------------------------------ #

    def _clamp_prefix(self, prompt: Any) -> Any:
        if isinstance(prompt, dict):
            return {
                k: str(v)[: self.prefix_chars] for k, v in prompt.items()
            }
        return str(prompt)[: self.prefix_chars]

    @staticmethod
    def _common_head(a: str, b: str) -> str:
        n = min(len(a), len(b))
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return a[:i]

    def _merge_prefix(self, old: Any, new: Any) -> Any:
        """Shrink the stored stage prefix to what is COMMON across
        tasks: after two observations it holds exactly the shared
        template preamble — the part a pre-warm can restore that the
        next task's prompt will actually prefix-match."""
        if isinstance(old, dict) and isinstance(new, dict):
            return {
                k: self._common_head(str(old.get(k, "")), str(v))
                for k, v in new.items() if k in old
            }
        return self._common_head(str(old), str(new))

    def note_stage(
        self,
        task_id: Optional[str],
        role: str,
        stage: str,
        prompt: Optional[Any] = None,
        session_id: Optional[str] = None,
    ) -> None:
        """Record a stage entry: learn the role's stage order and the
        stage's prompt prefix, then pre-warm the PREDICTED next stage's
        prefix so its prefill finds restored KV. Never raises. A no-op
        with the policy off — learning would cost the hot path lock
        traffic and prefix merges with no consumer."""
        if self.policy != "dag":
            return
        try:
            with self._lock:
                self._first_stage.setdefault(role, stage)
                if prompt:
                    skey = (role, stage)
                    clamped = self._clamp_prefix(prompt)
                    prev_prefix = self._stage_prefix.get(skey)
                    if prev_prefix is None:
                        self._stage_prefix[skey] = clamped
                        self._stage_obs[skey] = 1
                    else:
                        self._stage_prefix[skey] = self._merge_prefix(
                            prev_prefix, clamped
                        )
                        self._stage_obs[skey] = (
                            self._stage_obs.get(skey, 1) + 1
                        )
                predicted = None
                if task_id is not None:
                    key = (str(task_id), role)
                    prev = self._task_stage.get(key)
                    if prev is not None and prev != stage:
                        self._next_stage[(role, prev)] = stage
                    self._task_stage[key] = stage
                    self._task_stage.move_to_end(key)
                    while len(self._task_stage) > self._task_stage_cap:
                        self._task_stage.popitem(last=False)
                nxt = self._next_stage.get((role, stage))
                if nxt is not None and self._stage_obs.get(
                    (role, nxt), 0
                ) >= 2:
                    predicted = self._stage_prefix.get((role, nxt))
            if self.policy == "dag" and predicted:
                self.prewarm(predicted, session_id=session_id)
        except Exception:  # noqa: BLE001 — advisory
            pass

    def prewarm_role(self, role: str, session_id: Optional[str] = None) -> None:
        """Pre-warm a role's FIRST stage prefix — the delegator's hook:
        the moment a delegation target is chosen, its first prompt's
        preamble starts restoring before the task even reaches its
        queue."""
        if self.policy != "dag":
            return
        with self._lock:
            first = self._first_stage.get(role)
            prefix = (
                self._stage_prefix.get((role, first))
                if first is not None
                and self._stage_obs.get((role, first), 0) >= 2
                else None
            )
        if prefix:
            self.prewarm(prefix, session_id=session_id)

    def prewarm(self, prompt: Any, session_id: Optional[str] = None) -> int:
        """Broadcast a predicted prompt prefix (text, or the structured
        ``{"system", "user"}`` form) to every attached engine. Returns
        how many engines accepted the pre-warm (0 without an engine —
        mock backends and control planes pay nothing)."""
        if self.policy != "dag" or not prompt:
            return 0
        with self._lock:
            refs = list(self._prewarm_fns.items())
        accepted = 0
        dead = []
        for key, ref in refs:
            fn = ref()
            if fn is None:  # engine collected without stop()
                dead.append(key)
                continue
            try:
                if fn(prompt, session_id) is not False:
                    accepted += 1
            except Exception as exc:  # noqa: BLE001 — advisory
                self._log.warning("prewarm callback failed: %s", exc)
        if dead:
            with self._lock:
                for key in dead:
                    self._prewarm_fns.pop(key, None)
        return accepted

    # ------------------------------------------------------------------ #

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "policy": self.policy,
                "engines_attached": len(self._prewarm_fns),
                "stages_learned": len(self._stage_prefix),
                "transitions_learned": len(self._next_stage),
            }

    def reset(self) -> None:
        """Drop learned stage state (tests / bench mode isolation);
        attached engines stay attached."""
        with self._lock:
            self._next_stage.clear()
            self._first_stage.clear()
            self._stage_prefix.clear()
            self._stage_obs.clear()
            self._task_stage.clear()


global_scheduler = DagScheduler()
