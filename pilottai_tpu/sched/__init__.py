"""DAG-aware scheduling: the control loop over PR 7's tracing.

``obs/dag.py`` *measures* critical paths, stragglers and orchestration
overhead; this package *acts* on them (ROADMAP item 4, grounded in
"Towards Efficient Agents: A Co-Design of Inference Architecture and
System" — orchestration-level DAG knowledge driving engine-level
admission). Three rungs:

* **critical-path priority admission** — ``DagScheduler.priority_for``
  turns ``global_dag.criticality()`` (a live blame-walk estimate of a
  task's remaining critical path) into a priority boost; the full
  lattice threads ``Task.priority`` → ``GenerationParams.priority`` →
  ``GenRequest.priority`` into the batcher's priority-ordered backlog
  (``engine_sched_policy="dag"``), with an aging floor so low-priority
  work cannot starve.
* **gang admission** — sibling fan-out branches from one decompose
  stage carry a shared ``gang_id``; the batcher admits the gang as a
  group when slots+pages suffice for all of it (bounded wait, then
  partial-admit fallback), so a task's slowest branch stops straggling
  behind unrelated traffic.
* **speculative stage pre-warm** — on entering stage N, the scheduler
  predicts stage N+1's prompt prefix (learned per role/stage) and asks
  the engine to pre-warm it: the KV cache tier's session restore
  (PR 9) staged on the prep thread (PR 5), so the next hop's prefill
  is nearly free.

Greedy outputs are byte-identical with the scheduler on or off
(tests/test_sched.py) — the scheduler reorders and pre-warms, it never
changes what any single request computes.

Import cost: stdlib + obs + utils only — no jax (control-plane safe,
same constraint as ``obs``/``reliability``).
"""

from pilottai_tpu.sched.scheduler import DagScheduler, global_scheduler

__all__ = ["DagScheduler", "global_scheduler"]
