"""One structured logging bus for the whole framework.

Reference parity: ``pilott/utils/logger.py`` (JsonFormatter, rotating gzip
handler, split error file, audit logger, LogContext) — which the reference's
mainline code ignores, each class wiring its own StreamHandler instead
(SURVEY.md §5.5). Here every component logs through ``get_logger`` so
configuration is applied exactly once.
"""

from __future__ import annotations

import gzip
import json
import logging
import logging.handlers
import os
import shutil
import time
from pathlib import Path
from typing import Any, Dict, Optional

from pilottai_tpu.core.config import LogConfig

_ROOT_NAME = "pilottai_tpu"
_configured = False


class JsonFormatter(logging.Formatter):
    """Structured JSON log lines with component/agent/task context fields.

    Reference: ``pilott/utils/logger.py:34-64``.
    """

    def format(self, record: logging.LogRecord) -> str:
        payload: Dict[str, Any] = {
            "ts": round(record.created, 6),
            "level": record.levelname,
            "logger": record.name,
            "msg": record.getMessage(),
        }
        for key in ("agent_id", "task_id", "span_id", "trace_id", "component"):
            value = getattr(record, key, None)
            if value is not None:
                payload[key] = value
        if "trace_id" not in payload:
            # Correlate with the request's span tree: any log line emitted
            # inside an active span (server request handling, handler
            # retries, agent steps) carries that span's trace id, so one
            # grep over trace_id follows a request across components.
            # Lazy import: utils.logging loads before tracing in some
            # control-plane paths and must never create a cycle.
            try:
                from pilottai_tpu.utils.tracing import global_tracer

                span = global_tracer.current()
                if span is not None:
                    payload["trace_id"] = span.trace_id
            except Exception:  # pragma: no cover — logging must not raise
                pass
        if record.exc_info:
            payload["exc"] = self.formatException(record.exc_info)
        return json.dumps(payload, default=str)


class GzipRotatingFileHandler(logging.handlers.RotatingFileHandler):
    """Size-rotating file handler that gzips rotated logs.

    Reference: ``CustomRotatingFileHandler`` (``pilott/utils/logger.py:14-31``,
    midnight rotation + gzip); size-based rotation is friendlier for
    long-running TPU-VM jobs.
    """

    def rotation_filename(self, default_name: str) -> str:
        return default_name + ".gz"

    def rotate(self, source: str, dest: str) -> None:
        with open(source, "rb") as sf, gzip.open(dest, "wb") as df:
            shutil.copyfileobj(sf, df)
        os.remove(source)


def setup_logging(config: Optional[LogConfig] = None) -> None:
    """Configure the framework root logger.

    Calling with an explicit config always (re)builds handlers, even if a
    ``get_logger`` call auto-configured defaults earlier — otherwise
    ``log_to_file`` would be silently ignored after any import-time logging.
    Calling with no config is idempotent.
    """
    global _configured
    root = logging.getLogger(_ROOT_NAME)
    if _configured and config is None:
        return
    config = config or LogConfig()
    for handler in list(root.handlers):
        root.removeHandler(handler)
        handler.close()
    root.setLevel(config.level)
    root.propagate = False

    console = logging.StreamHandler()
    console.setFormatter(
        JsonFormatter()
        if config.json_format
        else logging.Formatter("%(asctime)s %(levelname)s %(name)s %(message)s")
    )
    root.addHandler(console)

    if config.log_to_file:
        log_dir = Path(config.log_dir)
        log_dir.mkdir(parents=True, exist_ok=True)
        main = GzipRotatingFileHandler(
            log_dir / "pilottai_tpu.log",
            maxBytes=config.rotate_max_bytes,
            backupCount=config.rotate_backups,
        )
        main.setFormatter(JsonFormatter())
        root.addHandler(main)
        # Split error file (reference ``utils/logger.py:119-129``).
        errors = GzipRotatingFileHandler(
            log_dir / "pilottai_tpu.error.log",
            maxBytes=config.rotate_max_bytes,
            backupCount=config.rotate_backups,
        )
        errors.setLevel(logging.ERROR)
        errors.setFormatter(JsonFormatter())
        root.addHandler(errors)
    _configured = True


# Logger.makeRecord rejects ANY extra key already present on LogRecord, so
# derive the reserved set from a real record rather than hand-listing.
_RESERVED_KEYS = set(logging.makeLogRecord({}).__dict__) | {"message", "asctime"}


def get_logger(component: str, **context: Any) -> logging.LoggerAdapter:
    """Component logger carrying structured context (agent_id, task_id...).

    Context keys colliding with LogRecord internals are prefixed rather
    than raising KeyError at log time.
    """
    if not _configured:
        setup_logging()
    logger = logging.getLogger(f"{_ROOT_NAME}.{component}")
    safe = {
        (f"ctx_{k}" if k in _RESERVED_KEYS else k): v for k, v in context.items()
    }
    return logging.LoggerAdapter(logger, {"component": component, **safe})


class LogContext:
    """Temporarily switch the framework log level (reference
    ``utils/logger.py:164-177``)."""

    def __init__(self, level: str) -> None:
        self._level = level.upper()
        self._prev: Optional[int] = None

    def __enter__(self) -> "LogContext":
        root = logging.getLogger(_ROOT_NAME)
        self._prev = root.level
        root.setLevel(self._level)
        return self

    def __exit__(self, *exc: Any) -> None:
        if self._prev is not None:
            logging.getLogger(_ROOT_NAME).setLevel(self._prev)


def create_audit_logger(path: str | Path) -> logging.Logger:
    """Append-only audit trail logger (reference ``utils/logger.py:192-207``)."""
    logger = logging.getLogger(f"{_ROOT_NAME}.audit.{path}")
    if not logger.handlers:
        handler = logging.FileHandler(path)
        handler.setFormatter(JsonFormatter())
        logger.addHandler(handler)
        logger.propagate = False
        logger.setLevel(logging.INFO)
    return logger
