"""In-process metrics bus: counters, gauges and latency histograms.

The reference exposes pull-only ``get_metrics()`` dicts per component with
no aggregation (SURVEY.md §5.5). Here one registry aggregates everything and
is the source of the headline numbers (agent-steps/sec/chip, p50 step
latency — BASELINE.json metric).
"""

from __future__ import annotations

import bisect
import threading
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional


class _Histogram:
    """Bounded reservoir of observations with percentile queries."""

    __slots__ = ("values", "count", "total", "max_samples")

    def __init__(self, max_samples: int = 4096) -> None:
        self.values: List[float] = []
        self.count = 0
        self.total = 0.0
        self.max_samples = max_samples

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        if len(self.values) >= self.max_samples:
            # Reservoir-style eviction keeping the list sorted: drop an
            # element at a deterministic rotating index.
            del self.values[self.count % self.max_samples]
        bisect.insort(self.values, value)

    def percentile(self, q: float) -> Optional[float]:
        if not self.values:
            return None
        idx = min(len(self.values) - 1, int(q / 100.0 * len(self.values)))
        return self.values[idx]

    def summary(self) -> Dict[str, Any]:
        return {
            "count": self.count,
            "mean": self.total / self.count if self.count else None,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms, labelled by name."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, _Histogram] = {}
        self._started = time.time()

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def remove_gauge(self, name: str) -> None:
        """Drop a gauge entirely (e.g. a reaped agent's health gauge —
        a stale last value would read as a live report forever)."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = _Histogram()
            self._histograms[name].observe(value)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def rate(self, name: str) -> float:
        """Counter value per second since registry start."""
        with self._lock:
            elapsed = max(time.time() - self._started, 1e-9)
            return self._counters.get(name, 0.0) / elapsed

    def get(self, name: str) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0.0)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "uptime_s": time.time() - self._started,
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._histograms.items()},
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._started = time.time()


class _Timer:
    def __init__(self, registry: MetricsRegistry, name: str) -> None:
        self._registry = registry
        self._name = name
        self._start = 0.0

    def __enter__(self) -> "_Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._registry.observe(self._name, time.perf_counter() - self._start)


global_metrics = MetricsRegistry()
