"""In-process metrics bus: counters, gauges and latency histograms.

The reference exposes pull-only ``get_metrics()`` dicts per component with
no aggregation (SURVEY.md §5.5). Here one registry aggregates everything and
is the source of the headline numbers (agent-steps/sec/chip, p50 step
latency — BASELINE.json metric) plus the request-phase histograms the
observability layer (``pilottai_tpu/obs``) exports as Prometheus summaries.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict, deque
from typing import Any, Deque, Dict, Optional, Tuple

# Longest sliding window rate() supports; counter event history is pruned
# past it so hot counters stay O(events-in-window), not O(process-lifetime).
_RATE_WINDOW_MAX = 300.0


class _Histogram:
    """Bounded window of the most recent observations with percentile
    queries, plus all-time count/total.

    Percentiles are WINDOW-AWARE: ``values`` holds the last
    ``max_samples`` observations in arrival order, so quantiles describe
    recent behavior. (The previous design kept a sorted list and evicted
    at a rotating *value-rank* index, which dropped arbitrary-aged
    samples — percentiles silently mixed all-time and recent data.)
    ``count``/``total`` (and therefore ``mean``) remain all-time.
    """

    __slots__ = ("values", "count", "total", "max_samples")

    def __init__(self, max_samples: int = 4096) -> None:
        self.values: Deque[float] = deque(maxlen=max_samples)
        self.count = 0
        self.total = 0.0
        self.max_samples = max_samples

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.values.append(value)

    def percentile(self, q: float) -> Optional[float]:
        if not self.values:
            return None
        ordered = sorted(self.values)
        idx = min(len(ordered) - 1, int(q / 100.0 * len(ordered)))
        return ordered[idx]

    def summary(self) -> Dict[str, Any]:
        ordered = sorted(self.values)

        def pct(q: float) -> Optional[float]:
            if not ordered:
                return None
            return ordered[min(len(ordered) - 1, int(q / 100.0 * len(ordered)))]

        return {
            "count": self.count,
            "mean": self.total / self.count if self.count else None,
            "p50": pct(50),
            "p90": pct(90),
            "p99": pct(99),
            # Samples the percentiles above were computed over (≤
            # max_samples; < count once eviction starts).
            "window": len(ordered),
        }


class MetricsRegistry:
    """Thread-safe counters / gauges / histograms, labelled by name."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)
        # Per-counter (timestamp, cumulative-after-inc) events for sliding
        # window rates; pruned to _RATE_WINDOW_MAX keeping one event at or
        # before the boundary as the window base.
        self._events: Dict[str, Deque[Tuple[float, float]]] = {}
        self._gauges: Dict[str, float] = {}
        self._histograms: Dict[str, _Histogram] = {}
        # Declared series: name -> kind ("counter" | "gauge" | "histogram").
        # A declaration is a CONTRACT: the series appears in snapshot()
        # (zero-valued until first observation) and therefore in every
        # exporter built on it. obs.export_completeness walks this table
        # so a subsystem can't register a series and ship it half-wired
        # (present in code, absent from /metrics).
        self._declared: Dict[str, str] = {}
        self._started = time.time()

    def inc(self, name: str, value: float = 1.0) -> None:
        now = time.time()
        with self._lock:
            self._counters[name] += value
            ev = self._events.get(name)
            if ev is None:
                ev = self._events[name] = deque()
            # Coalesce into per-second buckets: a hot counter (per-token
            # incs at production rates) must stay O(window seconds), not
            # O(increments) — both for memory and for rate()'s base scan.
            if ev and int(ev[-1][0]) == int(now):
                ev[-1] = (ev[-1][0], self._counters[name])
            else:
                ev.append((now, self._counters[name]))
            cutoff = now - _RATE_WINDOW_MAX
            while len(ev) >= 2 and ev[1][0] <= cutoff:
                ev.popleft()

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def remove_gauge(self, name: str) -> None:
        """Drop a gauge entirely (e.g. a reaped agent's health gauge —
        a stale last value would read as a live report forever)."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            if name not in self._histograms:
                self._histograms[name] = _Histogram()
            self._histograms[name].observe(value)

    def timer(self, name: str) -> "_Timer":
        return _Timer(self, name)

    def rate(self, name: str, window: Optional[float] = 60.0) -> float:
        """Counter value per second over the trailing ``window`` seconds
        (capped at 300 s). The previous counter ÷ uptime-since-start
        definition underreported current throughput after any idle
        period; pass ``window=None`` for that all-time average.
        """
        with self._lock:
            now = time.time()
            if window is None:
                elapsed = max(now - self._started, 1e-9)
                return self._counters.get(name, 0.0) / elapsed
            window = min(window, _RATE_WINDOW_MAX)
            cur = self._counters.get(name, 0.0)
            ev = self._events.get(name)
            if not ev:
                return 0.0
            cutoff = now - window
            base = 0.0
            for ts, cum in ev:
                if ts > cutoff:
                    break
                base = cum
            # A registry younger than the window divides by its actual
            # age — otherwise a fresh process underreports for a minute.
            elapsed = max(min(window, now - self._started), 1e-9)
            return max(cur - base, 0.0) / elapsed

    def get(self, name: str) -> float:
        with self._lock:
            if name in self._counters:
                return self._counters[name]
            return self._gauges.get(name, 0.0)

    def declare(self, name: str, kind: str = "gauge") -> None:
        """Declare a series the deployment is expected to export.
        ``kind`` is "counter", "gauge" or "histogram". Declared-but-not-
        yet-observed series surface in ``snapshot()`` with a zero value
        (empty summary for histograms) so scrapers see the full surface
        from boot and the export-completeness check can verify every
        registration reaches the exposition."""
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"unknown series kind {kind!r}")
        with self._lock:
            self._declared[name] = kind

    def declared(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._declared)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            hists = {k: h.summary() for k, h in self._histograms.items()}
            for name, kind in self._declared.items():
                if kind == "counter":
                    counters.setdefault(name, 0.0)
                elif kind == "gauge":
                    gauges.setdefault(name, 0.0)
                elif name not in hists:
                    hists[name] = _Histogram().summary()
            return {
                "uptime_s": time.time() - self._started,
                "counters": counters,
                "gauges": gauges,
                "histograms": hists,
            }

    def reset_histograms(self, prefix: str = "") -> None:
        """Drop histograms whose name starts with ``prefix`` (all when
        empty). Section-scoped measurement (bench) resets the request-
        phase histograms between sections so each section's percentiles
        describe ONLY its own traffic — the window alone still mixes a
        small section with its large predecessor's samples."""
        with self._lock:
            for name in [
                n for n in self._histograms if n.startswith(prefix)
            ]:
                del self._histograms[name]

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._events.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._started = time.time()


class _Timer:
    def __init__(self, registry: MetricsRegistry, name: str) -> None:
        self._registry = registry
        self._name = name
        self._start = 0.0

    def __enter__(self) -> "_Timer":
        self._start = time.perf_counter()
        return self

    def __exit__(self, *exc: Any) -> None:
        self._registry.observe(self._name, time.perf_counter() - self._start)


global_metrics = MetricsRegistry()
