from pilottai_tpu.utils.logging import LogContext, get_logger, setup_logging
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics
from pilottai_tpu.utils.tracing import Span, Tracer, global_tracer

__all__ = [
    "get_logger",
    "setup_logging",
    "LogContext",
    "MetricsRegistry",
    "global_metrics",
    "Span",
    "Tracer",
    "global_tracer",
]
