"""Persistent XLA compilation cache for warm engine restarts.

Every engine boot compiles the same programs: the prefill bucket ladder,
the fused decode chunk, the admission variants. On the TPU that cost
~2.4 minutes of dead time per process start (round-3 bench tail:
"engine up in 141.7s") — paid again on every FaultTolerance respawn and
every worker redeploy, because nothing persisted the executables.

This module points JAX's persistent compilation cache at a durable
directory and exposes a hit counter so restart paths can *assert* they
reused it instead of hoping. Serving engines call
:func:`enable_compilation_cache` before their first dispatch
(``engine/native.py``); anything else (bench, trainers, workers) can
too — the cache is process-global and idempotent.

Resolution order for the directory: explicit argument, then the
``PILOTTAI_COMPILE_CACHE`` env var, then ``~/.cache/pilottai_tpu/xla``.
Entries are keyed by program + topology + compiler version, so a stale
cache is never wrong, only useless.

No reference counterpart (the reference compiles nothing); this is
TPU-operational surface. VERDICT r3 next-step 4.
"""

from __future__ import annotations

import os
import threading
from pathlib import Path
from typing import Optional

from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics

_lock = threading.Lock()
_enabled_dir: Optional[str] = None
_listener_installed = False

HIT_METRIC = "engine.compile_cache_hits"


def default_cache_dir() -> str:
    return os.environ.get(
        "PILOTTAI_COMPILE_CACHE",
        os.path.join(
            os.path.expanduser("~"), ".cache", "pilottai_tpu", "xla"
        ),
    )


def _install_hit_listener() -> None:
    """Count persistent-cache hits into the global metrics registry via
    jax's monitoring events (the only stable signal the cache exposes)."""
    global _listener_installed
    if _listener_installed:
        return
    try:
        import jax._src.monitoring as mon

        def _on_event(name: str, **kwargs) -> None:
            if "compilation_cache" in name and "hit" in name:
                global_metrics.inc(HIT_METRIC)

        mon.register_event_listener(_on_event)
        _listener_installed = True
    except Exception:  # noqa: BLE001 — metrics are best-effort
        pass


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Enable JAX's persistent compilation cache (idempotent; returns the
    active directory, or None when disabling failed/was requested).

    ``cache_dir`` of ``"off"`` disables nothing retroactively — callers
    that do not want the cache simply never call this."""
    global _enabled_dir
    if cache_dir == "off":
        return None
    with _lock:
        path = str(Path(cache_dir or default_cache_dir()).expanduser())
        if _enabled_dir == path:
            _install_hit_listener()
            return path
        try:
            import jax

            Path(path).mkdir(parents=True, exist_ok=True)
            jax.config.update("jax_compilation_cache_dir", path)
            # Cache everything: through a remote tunnel even sub-second
            # compiles beat a round trip, and entry-size floors would
            # silently skip the small admission variants.
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        except Exception as exc:  # noqa: BLE001 — cache is an optimization
            get_logger("utils.compile_cache").warning(
                "persistent compilation cache unavailable: %s", exc
            )
            return None
        _enabled_dir = path
        _install_hit_listener()
        get_logger("utils.compile_cache").info(
            "persistent compilation cache at %s", path
        )
        return path


def cache_hits() -> int:
    return int(global_metrics.get(HIT_METRIC) or 0)


# --------------------------------------------------------------------- #
# Autotune results live NEXT TO the compiled executables: both are
# warm-restart state keyed by program shape, and a FaultTolerance respawn
# that reloads executables from here should reload the strip choice the
# executables were compiled WITH (re-timing would risk picking a different
# strip and recompiling the whole decode ladder it just restored).
#
# The same directory also carries the per-DEPLOYMENT workload profile
# store (``profiles.json``): fingerprints from obs/profile.py and the
# knob recommendations scripts/recommend.py derives from them. Both
# files share one merge-under-race discipline below — two replicas in a
# ServingCell point at one cache dir, and a plain read→merge→rename
# loses whichever writer renamed first.
# --------------------------------------------------------------------- #

_AUTOTUNE_FILE = "autotune.json"
_PROFILE_FILE = "profiles.json"
_STORE_RETRIES = 4
# Same-process writers (batcher tuner thread + profiler persist on the
# event loop) serialize here; the verify-own-key retry below only has to
# cover OTHER processes sharing the cache dir.
_STORE_LOCK = threading.Lock()


def _autotune_path() -> Path:
    return Path(_enabled_dir or default_cache_dir()) / _AUTOTUNE_FILE


def _profile_path() -> Path:
    return Path(_enabled_dir or default_cache_dir()) / _PROFILE_FILE


def _read_json_store(path: Path) -> dict:
    import json

    try:
        data = json.loads(path.read_text())
        return data if isinstance(data, dict) else {}
    except Exception:  # noqa: BLE001 — absence/corruption starts fresh
        return {}


def _store_json_key(path: Path, key: str, value) -> None:
    """Merge ``{key: value}`` into the JSON dict at ``path`` atomically.

    Write-temp + rename keeps readers torn-write-safe, but rename alone
    does not make read-modify-write safe: two replicas sharing the cache
    dir can both read, both merge their own key, and the second rename
    erases the first one's entry. So after renaming we re-read and
    verify OUR key landed; a concurrent winner that dropped it triggers
    a re-merge on top of the winner's file (bounded retries — this is a
    cache, livelock protection beats completeness)."""
    import json

    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(f".tmp{os.getpid()}-{threading.get_ident()}")
    with _STORE_LOCK:
        for _ in range(_STORE_RETRIES):
            data = _read_json_store(path)
            data[key] = value
            tmp.write_text(json.dumps(data, indent=2, sort_keys=True))
            tmp.replace(path)
            check = _read_json_store(path)
            if check.get(key) == value:
                return
    raise OSError(f"lost store race {_STORE_RETRIES}x on {path.name}:{key}")


def load_autotune(key: str) -> Optional[int]:
    """Best-effort read of a previously tuned integer for ``key``."""
    try:
        val = _read_json_store(_autotune_path()).get(key)
        return int(val) if val is not None else None
    except Exception:  # noqa: BLE001 — a missing/corrupt cache just re-tunes
        return None


def store_autotune(key: str, value: int) -> None:
    """Best-effort persist of a tuned integer under ``key``."""
    try:
        _store_json_key(_autotune_path(), key, int(value))
    except Exception as exc:  # noqa: BLE001 — tuning cache is an optimization
        get_logger("utils.compile_cache").warning(
            "autotune cache write failed: %s", exc
        )


def load_profile(key: str) -> Optional[dict]:
    """Best-effort read of the stored profile/recommendation blob for a
    deployment ``key`` (a dict as stored; None when absent/corrupt)."""
    try:
        val = _read_json_store(_profile_path()).get(key)
        return dict(val) if isinstance(val, dict) else None
    except Exception:  # noqa: BLE001 — profile store is advisory
        return None


def store_profile(key: str, value: dict) -> None:
    """Best-effort persist of a deployment profile blob under ``key``
    (same atomic merge-under-race discipline as the autotune store)."""
    try:
        _store_json_key(_profile_path(), key, dict(value))
    except Exception as exc:  # noqa: BLE001 — profile store is advisory
        get_logger("utils.compile_cache").warning(
            "profile store write failed: %s", exc
        )


__all__ = ["enable_compilation_cache", "cache_hits", "default_cache_dir",
           "load_autotune", "store_autotune", "load_profile",
           "store_profile", "HIT_METRIC"]
