"""Live metrics endpoint: the real replacement for the reference's
static marketing SPA (``/root/reference/interface/src`` shows hardcoded
stats like "10x Faster Development", ``Performance.js:8-20``; SURVEY.md
§2.19 notes a real metrics dashboard would supersede it).

Stdlib-only (http.server on a daemon thread), three routes:

* ``/metrics.json`` (alias ``/metrics``) — the unified snapshot
  (``obs.metrics_snapshot``: counters, gauges, histogram summaries,
  component ``get_metrics()``) — the SAME shape ``APIServer``'s
  ``/metrics`` serves; add ``?format=prometheus`` for text exposition.
* ``/trace.json`` — Chrome/Perfetto ``trace_event`` JSON of finished
  span trees plus engine step-ring counters (``?trace_id=`` narrows to
  one request); load it at https://ui.perfetto.dev.
* ``/slo.json`` — per-class SLO attainment/burn-rate snapshot
  (``obs.global_slo``), same shape as the API server's route.
* ``/dag.json`` — task-DAG attribution snapshot (``obs.global_dag``):
  active tasks + recent finished breakdowns/critical paths;
  ``?task_id=`` for one task's full node ledger (API server parity).
* ``/profile.json`` — the rolling workload fingerprint
  (``obs.global_profile``): length/arrival/class-mix shape plus the
  seasonal forecast state (API server parity).
* ``/`` — a self-refreshing HTML table over the same JSON.

Read-only and unauthenticated by design: bind to localhost (the default)
and port-forward, the same operational posture as a debug/metrics port.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional
from urllib.parse import parse_qs

from pilottai_tpu.obs import (
    global_dag,
    global_profile,
    global_slo,
    global_steps,
    metrics_snapshot,
    perfetto_trace,
    prometheus_text,
)
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.tracing import global_tracer

_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>pilottai-tpu metrics</title>
<style>
 body { font-family: ui-monospace, monospace; margin: 2rem; }
 table { border-collapse: collapse; margin-bottom: 1.5rem; }
 td, th { border: 1px solid #999; padding: 0.25rem 0.6rem; text-align: left; }
 caption { font-weight: bold; text-align: left; padding: 0.3rem 0; }
</style></head>
<body>
<h1>pilottai-tpu metrics</h1>
<p id="ts"></p>
<div id="root">loading…</div>
<script>
function table(title, obj) {
  if (!obj || !Object.keys(obj).length) return null;
  // DOM construction with textContent — metric names and component
  // values are data, never markup (task/agent names are user-controlled).
  const t = document.createElement("table");
  const cap = document.createElement("caption");
  cap.textContent = title;
  t.appendChild(cap);
  for (const [k, v] of Object.entries(obj)) {
    const tr = document.createElement("tr");
    const td1 = document.createElement("td");
    const td2 = document.createElement("td");
    td1.textContent = k;
    td2.textContent = typeof v === "object" ? JSON.stringify(v) : String(v);
    tr.appendChild(td1); tr.appendChild(td2);
    t.appendChild(tr);
  }
  return t;
}
async function refresh() {
  const r = await fetch("metrics.json");
  const m = await r.json();
  document.getElementById("ts").textContent =
    "uptime " + (m.uptime_s || 0).toFixed(1) + " s — refreshes every 2 s";
  const root = document.getElementById("root");
  root.replaceChildren();
  for (const t of [table("component", m.component),
                   table("counters", m.counters),
                   table("gauges", m.gauges),
                   table("histograms", m.histograms)]) {
    if (t) root.appendChild(t);
  }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


class MetricsDashboard:
    """Serve live metrics over HTTP. ``source`` is any object exposing
    ``get_metrics() -> dict`` (Serve, LLMHandler, ContinuousBatcher...);
    ``port=0`` picks a free port (read it back from ``.port``)."""

    def __init__(
        self,
        source: Optional[Any] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ) -> None:
        self.source = source
        self._log = get_logger("utils.dashboard")
        dashboard = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # route to our logger
                dashboard._log.debug(fmt % args)

            def do_GET(self):  # noqa: N802 — http.server API
                path, _, query = self.path.partition("?")
                params = parse_qs(query)
                if path in ("/metrics.json", "/metrics"):
                    if params.get("format") == ["prometheus"]:
                        body = prometheus_text(dashboard.snapshot()).encode()
                        ctype = "text/plain; version=0.0.4; charset=utf-8"
                    else:
                        body = json.dumps(
                            dashboard.snapshot(), default=str
                        ).encode()
                        ctype = "application/json"
                elif path == "/healthz":
                    # APIServer parity: a watchdog-declared engine stall
                    # is a 503 with retry_after, not a quiet 200.
                    from pilottai_tpu.reliability import (
                        global_engine_health,
                    )

                    snap = global_engine_health.snapshot()
                    stalled = snap.get("stalled")
                    body = json.dumps(
                        {"status": "stalled", "reason": snap.get("reason"),
                         "retry_after": snap.get("retry_after")}
                        if stalled else {"status": "ok"}
                    ).encode()
                    self.send_response(503 if stalled else 200)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                elif path == "/slo.json":
                    body = json.dumps(
                        global_slo.snapshot(), default=str
                    ).encode()
                    ctype = "application/json"
                elif path == "/dag.json":
                    task_id = (params.get("task_id") or [None])[0]
                    payload = (
                        global_dag.describe(task_id)
                        if task_id else global_dag.snapshot()
                    )
                    if payload is None:  # APIServer parity: unknown=404
                        self.send_error(404)
                        return
                    body = json.dumps(payload, default=str).encode()
                    ctype = "application/json"
                elif path == "/profile.json":
                    body = json.dumps(
                        global_profile.fingerprint(), default=str
                    ).encode()
                    ctype = "application/json"
                elif path == "/trace.json":
                    trace_id = (params.get("trace_id") or [None])[0]
                    spans = (
                        global_tracer.for_trace(trace_id)
                        if trace_id else global_tracer.finished()
                    )
                    # default=str: span attributes are caller-supplied
                    # (Tracer.span(**attrs) is public API) and one
                    # non-serializable value must not 500 the trace view.
                    body = json.dumps(perfetto_trace(
                        spans, steps=global_steps.snapshot()
                    ), default=str).encode()
                    ctype = "application/json"
                elif path == "/":
                    body = _PAGE.encode()
                    ctype = "text/html; charset=utf-8"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.host = host
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def snapshot(self) -> dict:
        # The ONE snapshot shape (shared with APIServer's /metrics).
        return metrics_snapshot(component=self.source)

    def start(self) -> "MetricsDashboard":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._server.serve_forever,
                name="pilottai-dashboard",
                daemon=True,
            )
            self._thread.start()
            self._log.info(
                "metrics dashboard at http://%s:%d/", self.host, self.port
            )
        return self

    def stop(self) -> None:
        if self._thread is not None:
            self._server.shutdown()
            self._thread.join(timeout=10)
            self._thread = None
        self._server.server_close()


__all__ = ["MetricsDashboard"]
