"""Device-side timing from JAX profiler traces — transport-independent
performance truth.

The benchmark chip sits behind a shared tunnel whose latency oscillates
between ~100 ms and multi-second stalls; end-to-end wall-clock therefore
conflates engine regressions with tunnel weather (VERDICT r4 weak #2: the
round-over-round headline moved 23% with no way to tell which). The fix is
to measure the DEVICE's own busy time: run a window under
``jax.profiler.trace`` and sum the execution lanes of the device process
from the perfetto JSON the profiler writes (the same method
docs/PERF_NOTES.md used by hand, automated).

No tensorboard/profile-plugin dependency: the ``*.trace.json.gz`` file is
plain perfetto JSON.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import tempfile
import time
from typing import Any, Callable, Dict, Optional

from pilottai_tpu.utils.logging import get_logger

_log = get_logger("device_profile")


def parse_trace_dir(trace_dir: str) -> Dict[str, Any]:
    """Parse the newest ``*.trace.json.gz`` under ``trace_dir``.

    Returns ``{device_busy_s, wall_s, busy_frac, lane, n_events}`` where
    ``device_busy_s`` is the largest per-thread interval UNION over the
    device process's lanes. Union, not sum: profiler lanes carry nested
    events ("XLA Ops" rows overlap hierarchically — a raw sum
    over-counted a measured 1B wave by ~1.8×), and merging intervals
    yields the time the device actually spent executing regardless of
    nesting. Falls back to host execution lanes when no ``/device:``
    process exists (CPU backend), and to zeros when no trace was
    written.
    """
    files = sorted(
        glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                  recursive=True),
        key=os.path.getmtime,
    )
    empty = {"device_busy_s": 0.0, "wall_s": 0.0, "busy_frac": 0.0,
             "lane": None, "n_events": 0}
    if not files:
        return empty
    with gzip.open(files[-1], "rt") as f:
        events = json.load(f).get("traceEvents", [])

    proc_names: Dict[int, str] = {}
    thread_names: Dict[tuple, str] = {}
    for e in events:
        if e.get("ph") != "M":
            continue
        if e.get("name") == "process_name":
            proc_names[e["pid"]] = str(e.get("args", {}).get("name", ""))
        elif e.get("name") == "thread_name":
            thread_names[(e["pid"], e.get("tid"))] = str(
                e.get("args", {}).get("name", "")
            )

    device_pids = {
        pid for pid, name in proc_names.items() if "/device:" in name
    }
    if not device_pids:
        # CPU backend: XLA's client threads are the closest analog; the
        # "python" lane is host bookkeeping, not execution.
        device_pids = set(proc_names)

        def lane_ok(pid: int, tid) -> bool:
            return "python" not in thread_names.get((pid, tid), "")
    else:
        def lane_ok(pid: int, tid) -> bool:
            return True

    intervals: Dict[tuple, list] = {}
    t_min, t_max = float("inf"), float("-inf")
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        if not lane_ok(e["pid"], e.get("tid")):
            continue
        key = (e["pid"], e.get("tid"))
        dur = float(e.get("dur", 0.0))
        ts = float(e.get("ts", 0.0))
        intervals.setdefault(key, []).append((ts, ts + dur))
        t_min = min(t_min, ts)
        t_max = max(t_max, ts + dur)
    if not intervals:
        return empty

    def union_us(spans: list) -> float:
        spans.sort()
        total = 0.0
        cur_start, cur_end = spans[0]
        for s, t in spans[1:]:
            if s > cur_end:
                total += cur_end - cur_start
                cur_start, cur_end = s, t
            else:
                cur_end = max(cur_end, t)
        return total + (cur_end - cur_start)

    unions = {k: union_us(v) for k, v in intervals.items()}
    lane_key = max(unions, key=lambda k: unions[k])
    busy_s = unions[lane_key] / 1e6
    wall_s = max(t_max - t_min, 0.0) / 1e6
    return {
        "device_busy_s": busy_s,
        "wall_s": wall_s,
        "busy_frac": busy_s / wall_s if wall_s > 0 else 0.0,
        "lane": thread_names.get(lane_key)
        or proc_names.get(lane_key[0], "?"),
        "n_events": len(intervals[lane_key]),
    }


class DeviceWindow:
    """``start()``/``stop()`` profiling window for async code paths (the
    bench can't wrap an ``await`` in a context manager argument)."""

    def __init__(self, trace_dir: Optional[str] = None) -> None:
        self._own_dir = trace_dir is None
        self.trace_dir = trace_dir or tempfile.mkdtemp(prefix="pilottai-prof-")
        self._t0 = 0.0
        self.wall_s = 0.0

    def start(self) -> "DeviceWindow":
        import jax

        jax.profiler.start_trace(self.trace_dir)
        self._t0 = time.perf_counter()
        return self

    def stop(self) -> Dict[str, Any]:
        import jax

        self.wall_s = time.perf_counter() - self._t0
        jax.profiler.stop_trace()
        out = parse_trace_dir(self.trace_dir)
        if self._own_dir:
            # Self-created temp dir: traces of multi-request waves run
            # tens of MB; leaking one per profiled section fills tmpfs
            # on long-lived hosts.
            import shutil

            shutil.rmtree(self.trace_dir, ignore_errors=True)
        out["window_wall_s"] = self.wall_s
        if self.wall_s > 0:
            # Busy fraction against the measured host window (the trace's
            # own extent understates idle time at the edges).
            out["busy_frac"] = min(out["device_busy_s"] / self.wall_s, 1.0)
        return out


def profile_device_window(
    fn: Callable[[], Any], trace_dir: Optional[str] = None
) -> Dict[str, Any]:
    """Run ``fn()`` under a profiler trace; return device-side timing."""
    win = DeviceWindow(trace_dir)
    win.start()
    try:
        fn()
    finally:
        out = win.stop()
    return out
