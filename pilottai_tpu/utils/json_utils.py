"""Tolerant JSON extraction from LLM output.

The structured-JSON prompt protocol (prompts/rules.yaml) makes JSON the
wire format between model and runtime. Models wrap JSON in prose and
``` fences; the reference's orchestrator had a tolerant parser
(``pilott/pilott.py:603-639``) while its agent used strict ``json.loads``
(``core/agent.py:397-402``) and a broken recursive regex (``(?R)``,
SURVEY.md §2.12-h). Here one tolerant parser serves every call site, with a
real brace-scanner instead of regex recursion.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional

_FENCE_RE = re.compile(r"```(?:json)?\s*(.*?)```", re.DOTALL)


def _balanced_spans(text: str) -> List[str]:
    """All top-level {...} spans, found by brace scanning (string-aware)."""
    spans: List[str] = []
    depth = 0
    start = -1
    in_string = False
    escape = False
    for i, ch in enumerate(text):
        if in_string:
            if escape:
                escape = False
            elif ch == "\\":
                escape = True
            elif ch == '"':
                in_string = False
            continue
        if ch == '"':
            if depth > 0:
                in_string = True
            continue
        if ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}":
            if depth > 0:
                depth -= 1
                if depth == 0 and start >= 0:
                    spans.append(text[start : i + 1])
                    start = -1
    return spans


def extract_json(text: str) -> Optional[Dict[str, Any]]:
    """Best-effort: parse ``text`` as a JSON object.

    Order: whole text → fenced blocks → balanced brace spans (longest
    first). Returns None when nothing parses.
    """
    if not text:
        return None
    candidates: List[str] = [text.strip()]
    candidates += [m.strip() for m in _FENCE_RE.findall(text)]
    candidates += sorted(_balanced_spans(text), key=len, reverse=True)
    for candidate in candidates:
        try:
            obj = json.loads(candidate)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict):
            return obj
    return None


def require_fields(
    obj: Optional[Dict[str, Any]],
    fields: Dict[str, type | tuple],
    context: str = "LLM response",
) -> Dict[str, Any]:
    """Validate presence and types of protocol fields (reference validates
    orchestrator analysis fields at ``pilott/pilott.py:584-597``)."""
    if obj is None:
        raise ValueError(f"{context}: no JSON object found")
    missing = [f for f in fields if f not in obj]
    if missing:
        raise ValueError(f"{context}: missing fields {missing}")
    for name, expected in fields.items():
        if not isinstance(obj[name], expected):
            raise ValueError(
                f"{context}: field {name!r} has type "
                f"{type(obj[name]).__name__}, expected {expected}"
            )
    return obj


def coerce_bool(value: Any) -> bool:
    if isinstance(value, bool):
        return value
    if isinstance(value, str):
        return value.strip().lower() in ("true", "yes", "1")
    return bool(value)
