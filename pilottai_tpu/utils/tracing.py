"""Span tracing threaded through task → agent → engine, with jax.profiler
integration on device-side spans.

The reference has no tracing at all (SURVEY.md §5.1 — only ad-hoc
``execution_time`` stamps). Here every task execution opens a span tree;
device spans additionally emit ``jax.profiler.TraceAnnotation`` markers so
steps line up with XLA traces in TensorBoard.
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional


@dataclass
class Span:
    name: str
    span_id: str
    parent_id: Optional[str]
    trace_id: str
    start: float = field(default_factory=time.perf_counter)
    end: Optional[float] = None
    attributes: Dict[str, Any] = field(default_factory=dict)

    @property
    def duration(self) -> Optional[float]:
        return None if self.end is None else self.end - self.start

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "trace_id": self.trace_id,
            # perf_counter timestamps: one process-wide monotonic clock,
            # shared with the engine step ring — the Perfetto exporter
            # relies on the two aligning.
            "start": self.start,
            "end": self.end,
            "duration": self.duration,
            "attributes": self.attributes,
        }


class Tracer:
    """Minimal in-process tracer.

    Span stacks live in a ``contextvars.ContextVar`` (not threading.local):
    interleaved asyncio tasks on one event loop each see their own stack, so
    concurrent task executions (``ServeConfig.max_concurrent_tasks`` > 1)
    get correct span parentage.
    """

    def __init__(self, max_finished: int = 10000) -> None:
        self._stack_var: contextvars.ContextVar[tuple] = contextvars.ContextVar(
            f"pilottai_span_stack_{id(self)}", default=()
        )
        self._finished: List[Span] = []
        self._lock = threading.Lock()
        self._max_finished = max_finished

    def current(self) -> Optional[Span]:
        stack = self._stack_var.get()
        return stack[-1] if stack else None

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        device: bool = False,
        trace_id: Optional[str] = None,
        **attributes: Any,
    ) -> Iterator[Span]:
        """Open a span. ``trace_id`` seeds a ROOT span's trace (the HTTP
        edge passes the request's ``x-request-id`` here); a span with a
        live parent always inherits the parent's trace instead — one
        request, one trace, no matter what a nested caller passes."""
        parent = self.current()
        span = Span(
            name=name,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent.span_id if parent else None,
            trace_id=(
                parent.trace_id if parent
                else (trace_id or uuid.uuid4().hex[:16])
            ),
            attributes=attributes,
        )
        token = self._stack_var.set(self._stack_var.get() + (span,))
        annotation = contextlib.nullcontext()
        if device:
            try:
                import jax.profiler

                annotation = jax.profiler.TraceAnnotation(name)
            except Exception:  # pragma: no cover - profiler optional
                pass
        try:
            with annotation:
                yield span
        finally:
            span.end = time.perf_counter()
            self._stack_var.reset(token)
            with self._lock:
                self._finished.append(span)
                if len(self._finished) > self._max_finished:
                    del self._finished[: len(self._finished) // 2]

    def emit(
        self,
        name: str,
        *,
        trace_id: str,
        start: float,
        end: float,
        parent_id: Optional[str] = None,
        **attributes: Any,
    ) -> Span:
        """Record an already-finished span directly. For code that runs
        outside any task context (the batcher's device/reader threads,
        where the contextvar stack doesn't propagate): the engine emits
        its per-request span at completion time with the parent span id
        the request carried in, so the request's tree still nests
        server → handler → batcher."""
        span = Span(
            name=name,
            span_id=uuid.uuid4().hex[:16],
            parent_id=parent_id,
            trace_id=trace_id,
            start=start,
            end=end,
            attributes=attributes,
        )
        with self._lock:
            self._finished.append(span)
            if len(self._finished) > self._max_finished:
                del self._finished[: len(self._finished) // 2]
        return span

    def finished(self, name: Optional[str] = None) -> List[Span]:
        with self._lock:
            spans = list(self._finished)
        if name is not None:
            spans = [s for s in spans if s.name == name]
        return spans

    def for_trace(self, trace_id: str) -> List[Span]:
        """Every finished span of one trace, in finish order (a flight
        recorder dump wants exactly this tree)."""
        with self._lock:
            return [s for s in self._finished if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._finished.clear()


global_tracer = Tracer()
