"""Parallelism: device meshes, sharding rules, collectives, ring attention.

ABSENT in the reference (SURVEY.md §2.13-2.14 — its only concurrency is
asyncio). This package is new TPU-native surface: SPMD over
``jax.sharding.Mesh`` with XLA collectives riding ICI, scaling the in-tree
engine the way the reference's remote-API path never could. As of
ISSUE 13 it also owns the serving KV-cache shardings
(``kv_shard_axes``/``place_kv_cache``) and the per-axis collective-time
attribution model (``collectives.CollectiveModel``) behind
``engine.collective_frac[.axis]``. ISSUE 16 adds the degraded-mesh
fault domain (``meshplan``): an ordered ladder of viable mesh plans the
engine re-plans onto when a shard is lost mid-serving.
"""

from pilottai_tpu.parallel.collectives import CollectiveModel, collective_ops
from pilottai_tpu.parallel.mesh import MeshConfig, best_mesh_config, create_mesh
from pilottai_tpu.parallel.meshplan import (
    MeshLadderExhausted,
    MeshPlanLadder,
    ShardLossError,
    classify_device_error,
    default_ladder,
    plan_label,
)
from pilottai_tpu.parallel.ring_attention import ring_attention
from pilottai_tpu.parallel.sharding import (
    kv_shard_axes,
    logical_to_spec,
    place_kv_cache,
    shard_params,
    validate_serving_mesh,
    with_logical_constraint,
)

__all__ = [
    "CollectiveModel",
    "MeshConfig",
    "MeshLadderExhausted",
    "MeshPlanLadder",
    "ShardLossError",
    "best_mesh_config",
    "classify_device_error",
    "collective_ops",
    "create_mesh",
    "default_ladder",
    "kv_shard_axes",
    "plan_label",
    "logical_to_spec",
    "place_kv_cache",
    "ring_attention",
    "shard_params",
    "validate_serving_mesh",
    "with_logical_constraint",
]
