"""Parallelism: device meshes, sharding rules, collectives, ring attention.

ABSENT in the reference (SURVEY.md §2.13-2.14 — its only concurrency is
asyncio). This package is new TPU-native surface: SPMD over
``jax.sharding.Mesh`` with XLA collectives riding ICI, scaling the in-tree
engine the way the reference's remote-API path never could.
"""

from pilottai_tpu.parallel.mesh import MeshConfig, best_mesh_config, create_mesh
from pilottai_tpu.parallel.ring_attention import ring_attention
from pilottai_tpu.parallel.sharding import (
    logical_to_spec,
    shard_params,
    with_logical_constraint,
)

__all__ = [
    "MeshConfig",
    "create_mesh",
    "best_mesh_config",
    "logical_to_spec",
    "ring_attention",
    "shard_params",
    "with_logical_constraint",
]
