"""Pipeline parallelism: GPipe-style microbatch pipeline over a 'stage'
mesh axis.

Layers are split into contiguous stage groups; each device in the
``stage`` axis holds one group's parameters and activations flow
stage-to-stage over ICI via ``ppermute``. Microbatches fill the pipeline
(n_micro + n_stages - 1 ticks); the bubble fraction is
(n_stages - 1) / (n_micro + n_stages - 1), so callers pick
n_micro >= n_stages for decent utilization. Differentiable end to end
(ppermute transposes to the reverse rotation), so the same primitive
serves training.

This is the standalone pp building block; the transformer trainer
composes it with the other axes (dp/fsdp/tp/sp/ep) by splitting the
layer stack into stage groups.

No reference counterpart (SURVEY.md §2.13).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pilottai_tpu.parallel.mesh import compat_shard_map


def pipeline_apply(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,        # pytree; leaves have leading [n_stages] axis
    x: jax.Array,             # [n_micro, mb, ...] microbatched input
    mesh: Mesh,
    axis: str = "stage",
    batch_axes: tuple = ("data", "fsdp"),
) -> jax.Array:
    """Run ``block_fn`` over ``n_stages`` pipeline stages.

    ``block_fn(params_for_stage, activation) -> activation`` must preserve
    the activation shape (classic transformer trunk). Microbatch i's
    output appears in slot i of the returned [n_micro, mb, ...] array.
    """
    n_stages = mesh.shape[axis]
    n_micro = x.shape[0]
    total_ticks = n_micro + n_stages - 1

    param_specs = jax.tree.map(lambda _: P(axis), stage_params)
    x_spec = P(None, batch_axes)  # microbatch axis replicated across stages

    def per_stage(params, x):
        # params: this stage's group (leading axis stripped by shard_map
        # to size 1) — squeeze it.
        params = jax.tree.map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        mb_shape = x.shape[1:]

        fwd_perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]
        carry = jnp.zeros(mb_shape, x.dtype)      # current inbound activation
        out = jnp.zeros_like(x)                    # last stage accumulates

        for tick in range(total_ticks):
            # Stage 0 ingests microbatch `tick` (when one remains).
            mb_idx = min(tick, n_micro - 1)
            inbound = jnp.where(stage == 0, x[mb_idx], carry)
            y = block_fn(params, inbound)
            # Which microbatch is this stage holding at this tick?
            held = tick - stage                    # traced via `stage`
            live = (held >= 0) & (held < n_micro)
            y = jnp.where(live, y, jnp.zeros_like(y))
            # Last stage deposits its finished microbatch.
            is_last = stage == n_stages - 1
            slot = jnp.clip(held, 0, n_micro - 1)
            deposit = jnp.where(live & is_last, y, jnp.zeros_like(y))
            out = out.at[slot].add(deposit)
            # Rotate activations forward (last→0 wraps but stage 0 ignores
            # its inbound, so the wrap is harmless).
            carry = jax.lax.ppermute(y, axis, fwd_perm)

        # Only the last stage holds real outputs; share them along the ring.
        out = jax.lax.psum(
            jnp.where(stage == n_stages - 1, out, jnp.zeros_like(out)),
            axis,
        )
        return out

    return compat_shard_map(
        per_stage,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        check_vma=False,
    )(stage_params, x)


def split_layers_to_stages(stacked_params: Any, n_stages: int) -> Any:
    """Reshape stacked-layer params [L, ...] -> [n_stages, L/n_stages, ...]."""

    def split(leaf):
        L = leaf.shape[0]
        assert L % n_stages == 0, f"{L} layers not divisible by {n_stages} stages"
        return leaf.reshape(n_stages, L // n_stages, *leaf.shape[1:])

    return jax.tree.map(split, stacked_params)
