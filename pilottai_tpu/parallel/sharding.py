"""Logical-axis sharding rules: annotate once, let XLA insert collectives.

Arrays carry *logical* axis names; one rules table maps logical axes to
mesh axes. This is the scaling-book recipe (pick a mesh, annotate
shardings, let XLA do the rest) — no reference counterpart (SURVEY §2.13).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated)
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("data", "fsdp"),
    "seq": "seq",           # activation sequence axis (context parallel)
    "embed": "fsdp",        # weight embed axis sharded over fsdp
    "heads": "model",       # attention heads: tensor parallel
    "kv_heads": "model",
    "head_dim": None,
    "mlp": "model",         # ffn hidden: tensor parallel
    "vocab": "model",       # embedding/logits vocab axis
    "layers": None,         # stacked-layer leading axis: never sharded
    "expert": "model",      # MoE experts (expert parallel rides the model axis
                            # by default; override with a dedicated axis)
    "mlp_expert": None,     # per-expert ffn hidden: already sharded by expert
}


def logical_to_spec(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Any]] = None,
) -> P:
    """Map ('batch', 'seq', 'embed') -> PartitionSpec via the rules table."""
    rules = {**DEFAULT_RULES, **(rules or {})}
    entries = []
    for axis in logical_axes:
        if axis is None:
            entries.append(None)
        else:
            entries.append(rules.get(axis))
    return P(*entries)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Any]] = None,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules))


def shard_params(
    params: Any,
    logical_tree: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, Any]] = None,
) -> Any:
    """Device-put a param pytree according to a parallel pytree of logical
    axis tuples (``None`` leaf = replicated)."""

    def place(axes, leaf):
        sharding = (
            NamedSharding(mesh, P())
            if axes is None
            else named_sharding(mesh, axes, rules)
        )
        return jax.device_put(leaf, sharding)

    # Map over logical_tree FIRST so bare-None leaves ("replicated") are
    # honored — with params first, a None in the second tree would be
    # treated as an empty subtree and raise a structure mismatch.
    return jax.tree.map(
        place,
        logical_tree,
        params,
        is_leaf=lambda x: x is None
        or (isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x)),
    )


def with_logical_constraint(
    x: jax.Array,
    logical_axes: Sequence[Optional[str]],
    mesh: Optional[Mesh] = None,
    rules: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """``lax.with_sharding_constraint`` by logical axes; no-op outside jit
    mesh contexts so model code runs unchanged on one device."""
    try:
        mesh = mesh or _current_mesh()
        if mesh is None or mesh.empty:
            return x
        return jax.lax.with_sharding_constraint(
            x, named_sharding(mesh, logical_axes, rules)
        )
    except (ValueError, RuntimeError):
        return x


def _current_mesh() -> Optional[Mesh]:
    try:
        from jax._src.mesh import thread_resources

        mesh = thread_resources.env.physical_mesh
        return None if mesh.empty else mesh
    except Exception:
        return None


def spec_tree_for(logical_tree: Any, rules: Optional[Dict[str, Any]] = None) -> Any:
    """Parallel pytree of PartitionSpecs (for pjit in/out shardings)."""
    return jax.tree.map(
        lambda axes: P() if axes is None else logical_to_spec(axes, rules),
        logical_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        )),
    )
