"""Logical-axis sharding rules: annotate once, let XLA insert collectives.

Arrays carry *logical* axis names; one rules table maps logical axes to
mesh axes. This is the scaling-book recipe (pick a mesh, annotate
shardings, let XLA do the rest) — no reference counterpart (SURVEY §2.13).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated)
DEFAULT_RULES: Dict[str, Any] = {
    "batch": ("data", "fsdp"),
    "seq": "seq",           # activation sequence axis (context parallel)
    "embed": "fsdp",        # weight embed axis sharded over fsdp
    "heads": "model",       # attention heads: tensor parallel
    "kv_heads": "model",
    "head_dim": None,
    "mlp": "model",         # ffn hidden: tensor parallel
    "vocab": "model",       # embedding/logits vocab axis
    "layers": None,         # stacked-layer leading axis: never sharded
    "expert": "model",      # MoE experts (expert parallel rides the model axis
                            # by default; override with a dedicated axis)
    "mlp_expert": None,     # per-expert ffn hidden: already sharded by expert
}


def logical_to_spec(
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Any]] = None,
) -> P:
    """Map ('batch', 'seq', 'embed') -> PartitionSpec via the rules table."""
    rules = {**DEFAULT_RULES, **(rules or {})}
    entries = []
    for axis in logical_axes:
        if axis is None:
            entries.append(None)
        else:
            entries.append(rules.get(axis))
    return P(*entries)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: Optional[Dict[str, Any]] = None,
) -> NamedSharding:
    return NamedSharding(mesh, logical_to_spec(logical_axes, rules))


def shard_params(
    params: Any,
    logical_tree: Any,
    mesh: Mesh,
    rules: Optional[Dict[str, Any]] = None,
) -> Any:
    """Device-put a param pytree according to a parallel pytree of logical
    axis tuples (``None`` leaf = replicated)."""

    def place(axes, leaf):
        sharding = (
            NamedSharding(mesh, P())
            if axes is None
            else named_sharding(mesh, axes, rules)
        )
        return jax.device_put(leaf, sharding)

    # Map over logical_tree FIRST so bare-None leaves ("replicated") are
    # honored — with params first, a None in the second tree would be
    # treated as an empty subtree and raise a structure mismatch.
    return jax.tree.map(
        place,
        logical_tree,
        params,
        is_leaf=lambda x: x is None
        or (isinstance(x, tuple) and all(a is None or isinstance(a, str) for a in x)),
    )


def with_logical_constraint(
    x: jax.Array,
    logical_axes: Sequence[Optional[str]],
    mesh: Optional[Mesh] = None,
    rules: Optional[Dict[str, Any]] = None,
) -> jax.Array:
    """``lax.with_sharding_constraint`` by logical axes; no-op outside jit
    mesh contexts so model code runs unchanged on one device."""
    try:
        mesh = mesh or _current_mesh()
        if mesh is None or mesh.empty:
            return x
        return jax.lax.with_sharding_constraint(
            x, named_sharding(mesh, logical_axes, rules)
        )
    except (ValueError, RuntimeError):
        return x


def _current_mesh() -> Optional[Mesh]:
    try:
        from jax._src.mesh import thread_resources

        mesh = thread_resources.env.physical_mesh
        return None if mesh.empty else mesh
    except Exception:
        return None


# --------------------------------------------------------------------- #
# Serving KV-cache shardings (ISSUE 13: tensor-parallel serving)
# --------------------------------------------------------------------- #
#
# The engine's KV state is built OUTSIDE jit (ops/kvcache.py /
# ops/paged.py ``create``) and then donated through every dispatch, so
# its initial placement decides where the pool lives for the whole
# serving lifetime. Until ISSUE 13 the pool was created on the default
# device and XLA resharded it into whatever propagation chose on the
# first dispatch; these helpers give it an explicit layout instead:
#
# * dense cache panels [B, K, S, H]: slots shard over ``data``/``fsdp``
#   (each data group owns its slots' context — the dense capacity win),
#   kv-heads over ``model`` (each TP shard streams only its heads);
# * paged pool panels [K, pages, P, H]: kv-heads over ``model``. Pages
#   are a GLOBAL resource (any slot may hold any page), so the page dim
#   replicates over ``data`` — cross-replica data-parallel KV capacity
#   is the serving cell's job (distributed/cell.py), while the in-mesh
#   ``data`` axis parallelizes compute over slots;
# * per-slot control vectors ([B] lengths, decode/sampling state) stay
#   replicated: they are bytes, and sharding them buys collectives, not
#   capacity.
#
# Non-shardable shapes degrade per-axis (documented in
# docs/SERVING.md): a kv-head count that doesn't divide the ``model``
# extent replicates the head dim (weights still shard — GSPMD pads),
# and a slot count that doesn't divide the data extent replicates the
# slot dim.


def _divides(n: int, by: int) -> bool:
    return by > 1 and n % by == 0


def kv_shard_axes(
    mesh: Optional[Mesh],
    *,
    n_kv_heads: int,
    n_slots: int,
) -> Dict[str, Any]:
    """Which KV-cache dims can shard on ``mesh``: ``{"heads": mesh-axis
    or None, "slots": axis-tuple or None, "data_groups": int}``.
    ``data_groups`` is the number of independent admission groups the
    batcher runs over the batch axes (1 = no batch parallelism)."""
    out: Dict[str, Any] = {"heads": None, "slots": None, "data_groups": 1}
    if mesh is None or mesh.devices.size <= 1:
        return out
    shape = dict(mesh.shape)
    model = int(shape.get("model", 1))
    batch_axes = tuple(
        a for a in ("data", "fsdp") if int(shape.get(a, 1)) > 1
    )
    db = 1
    for a in batch_axes:
        db *= int(shape[a])
    if _divides(n_kv_heads, model):
        out["heads"] = "model"
    if batch_axes and _divides(n_slots, db):
        out["slots"] = batch_axes
        out["data_groups"] = db
    return out


def kv_cache_shardings(
    mesh: Optional[Mesh],
    cache: Any,
    *,
    n_kv_heads: int,
    n_slots: int,
) -> Optional[Any]:
    """A sharding pytree matching ``cache`` (``ops/kvcache.KVCache`` or
    ``ops/paged.PagedKVCache``): panel/scale leaves shard per
    :func:`kv_shard_axes`; ``lengths`` and any other per-slot vector
    replicate. None when the mesh gives nothing to shard."""
    axes = kv_shard_axes(mesh, n_kv_heads=n_kv_heads, n_slots=n_slots)
    if mesh is None or (axes["heads"] is None and axes["slots"] is None):
        return None
    paged = hasattr(cache, "num_pages")  # PagedKVCache vs KVCache
    head, slots = axes["heads"], axes["slots"]
    if paged:
        panel = P(head, None, None, None)       # [K, pages, P, H]
        scale = P(head, None, None)             # [K, pages, P]
    else:
        panel = P(slots, head, None, None)      # [B, K, S, H]
        scale = P(slots, head, None)            # [B, K, S]
    repl = NamedSharding(mesh, P())

    def _leaf(spec: P) -> NamedSharding:
        return NamedSharding(mesh, spec)

    layers = tuple(
        (_leaf(panel), _leaf(panel)) for _ in cache.layers
    )
    scales = (
        tuple((_leaf(scale), _leaf(scale)) for _ in cache.scales)
        if cache.scales is not None else None
    )
    return cache._replace(layers=layers, lengths=repl, scales=scales)


def place_kv_cache(
    cache: Any,
    mesh: Optional[Mesh],
    *,
    n_kv_heads: int,
    n_slots: int,
) -> Any:
    """Device-put a freshly created KV cache onto its serving layout
    (identity off-mesh). Donation-friendly: every later jitted dispatch
    sees inputs already in the layout propagation would choose, so the
    donated buffers alias instead of resharding."""
    shardings = kv_cache_shardings(
        mesh, cache, n_kv_heads=n_kv_heads, n_slots=n_slots
    )
    if shardings is None:
        return cache
    return jax.device_put(cache, shardings)


def validate_serving_mesh(
    mesh: Optional[Mesh],
    cfg: Any,
    n_slots: int,
) -> Dict[str, Any]:
    """Shardability report for an engine boot: which KV dims shard,
    which degrade to replication, and why — so a mis-shaped mesh logs
    one line at start instead of silently serving replicated KV.
    Returns ``{"kv_heads_sharded", "slots_sharded", "data_groups",
    "warnings": [...]}``."""
    report: Dict[str, Any] = {
        "kv_heads_sharded": False, "slots_sharded": False,
        "data_groups": 1, "warnings": [],
    }
    if mesh is None or mesh.devices.size <= 1:
        return report
    shape = dict(mesh.shape)
    model = int(shape.get("model", 1))
    axes = kv_shard_axes(
        mesh, n_kv_heads=cfg.n_kv_heads, n_slots=n_slots
    )
    report["kv_heads_sharded"] = axes["heads"] is not None
    report["slots_sharded"] = axes["slots"] is not None
    report["data_groups"] = axes["data_groups"]
    if model > 1 and axes["heads"] is None:
        report["warnings"].append(
            f"n_kv_heads={cfg.n_kv_heads} does not divide mesh "
            f"model={model}; KV panels replicate over the model axis "
            f"(weights still shard)"
        )
    if model > 1 and cfg.n_heads % model:
        report["warnings"].append(
            f"n_heads={cfg.n_heads} does not divide mesh model={model}; "
            f"attention-head sharding pads"
        )
    db = 1
    for a in ("data", "fsdp"):
        db *= int(shape.get(a, 1))
    if db > 1 and axes["slots"] is None:
        report["warnings"].append(
            f"n_slots={n_slots} does not divide the batch axes "
            f"(data*fsdp={db}); slot dim replicates and admission runs "
            f"a single group"
        )
    return report


def spec_tree_for(logical_tree: Any, rules: Optional[Dict[str, Any]] = None) -> Any:
    """Parallel pytree of PartitionSpecs (for pjit in/out shardings)."""
    return jax.tree.map(
        lambda axes: P() if axes is None else logical_to_spec(axes, rules),
        logical_tree,
        is_leaf=lambda x: x is None or (isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        )),
    )
