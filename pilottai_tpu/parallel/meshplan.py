"""Degraded-mesh planning: survive shard loss by re-planning the mesh.

PR 8 contained device *failures* (rebuild + snapshot/re-admit recovery)
and PR 12 made the serving mesh first-class — but both assume the device
set that booted is the device set that serves. A chip or ICI failure
inside a ``{'model':M,'data':D}`` mesh previously had no recovery path
short of killing the replica. This module makes the **mesh plan** the
unit of survival instead of the process:

* ``MeshPlanLadder`` owns an ordered ladder of viable mesh plans for
  the boot device set (e.g. ``{'model':4,'data':2}`` →
  ``{'model':4,'data':1}`` → ``{'model':2,'data':1}`` → single-chip),
  the set of devices marked lost, and a per-shard heartbeat table that
  rides the PR 8 watchdog (a shard whose heartbeat freezes while its
  siblings keep beating is a *loss*, not a stall).
* ``classify_device_error`` maps a device-loop exception to the boot
  index of the shard it names (None → not a shard loss; the generic
  PR 8 rebuild path handles it).
* ``replan()`` walks the ladder from the active rung down and builds a
  ``jax.sharding.Mesh`` over the surviving devices for the first rung
  that fits — or raises ``MeshLadderExhausted``, at which point the
  PR 8 contract ends and in-flight requests fail with the original
  exception.

The ladder sheds replica-style axes first (``seq``, ``data``, ``fsdp``
— capacity, not layout) and the ``model`` axis last, because dropping a
``model`` rung changes every weight shard's layout while dropping a
``data`` rung only shrinks the admission groups.

Degradation is NOT data recovery: the KV pool resident on a lost shard
is gone, and recovery re-prefills it from the snapshotted tokens (the
host tier's spilled entries survive in host RAM and restore onto the
new layout). Weight re-placement after a loss assumes the surviving
devices can reconstruct every shard — true under simulated loss (all
physical devices still answer) and under replicated axes; a production
deployment that loses the only holder of a ``model`` shard must reload
those weights from the host checkpoint first (see SERVING.md).
"""

from __future__ import annotations

import re
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from pilottai_tpu.parallel.mesh import AXIS_NAMES, MeshConfig, create_mesh
from pilottai_tpu.utils.logging import get_logger


class MeshLadderExhausted(RuntimeError):
    """No rung of the mesh-plan ladder fits the surviving device set."""


class ShardLossError(RuntimeError):
    """A device of the serving mesh failed (chip or ICI link).

    Raised by the ``mesh.shard_loss`` chaos point and recognized by
    ``classify_device_error`` — the canonical in-tree shape of a
    per-device failure. Real backends surface device loss as free-form
    runtime errors; the classifier's patterns cover the common ones.
    """

    def __init__(self, device_index: int, detail: str = "") -> None:
        self.device_index = int(device_index)
        msg = f"lost shard: device {self.device_index} failed"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


# Deliberately narrow: a stray "device 0" inside an ordinary XLA error
# must NOT degrade the mesh — misclassifying a transient dispatch error
# as a shard loss permanently sheds capacity. Only phrasings that name
# a device AND assert its failure match.
_DEVICE_PATTERNS = (
    re.compile(r"lost shard: device (\d+)"),
    re.compile(r"device (\d+) (?:failed|lost|unavailable|unreachable|"
               r"is unhealthy|not responding)", re.I),
    re.compile(r"lost device (\d+)", re.I),
    re.compile(r"DATA_LOSS[^0-9]*device[^0-9]*(\d+)"),
)


def classify_device_error(exc: BaseException) -> Optional[int]:
    """Boot-order device index an exception names as failed, or None.

    None means "not a shard loss" — the caller falls back to the
    generic PR 8 device-loop recovery (same-mesh rebuild).
    """
    if isinstance(exc, ShardLossError):
        return exc.device_index
    text = str(exc)
    for pat in _DEVICE_PATTERNS:
        m = pat.search(text)
        if m:
            return int(m.group(1))
    return None


def default_ladder(plan: Dict[str, int]) -> List[Dict[str, int]]:
    """Halving ladder from a boot plan down to single-chip.

    Replica-style axes shed first (``seq`` → ``data`` → ``fsdp``: each
    rung halves capacity but keeps every weight shard's layout), the
    ``model`` axis last (halving it re-lays-out every parameter).
    ``{'model':4,'data':2}`` → ``[{'model':4,'data':2},
    {'model':4,'data':1}, {'model':2,'data':1}, {'model':1,'data':1}]``.
    """
    cur = {a: max(1, int(plan.get(a, 1))) for a in AXIS_NAMES}
    rungs = [dict(cur)]
    for axis in ("seq", "data", "fsdp"):
        while cur[axis] > 1:
            cur[axis] //= 2
            rungs.append(dict(cur))
    while cur["model"] > 1:
        cur["model"] //= 2
        rungs.append(dict(cur))
    return rungs


def _plan_devices(plan: Dict[str, int]) -> int:
    n = 1
    for a in AXIS_NAMES:
        n *= max(1, int(plan.get(a, 1)))
    return n


def plan_label(plan: Dict[str, int]) -> str:
    """Human shape: axes of extent 1 dropped (``model=2,data=1`` →
    ``"model2"``; the all-ones rung is ``"single"``)."""
    parts = [
        f"{a}{int(plan[a])}" for a in AXIS_NAMES
        if int(plan.get(a, 1)) > 1
    ]
    return "x".join(parts) if parts else "single"


class MeshPlanLadder:
    """Ordered mesh plans for one boot device set + loss bookkeeping.

    Thread model: ``mark_lost``/``replan`` run on the batcher's device
    thread (inside the failure arms); ``beat_all`` runs on the fold
    path (reader thread, lock-free plain stores — same contract as the
    watchdog's ``beat()``); ``stale``/``rung``/``plan`` are read from
    the watchdog and metrics threads.
    """

    def __init__(
        self,
        mesh: Any,
        rungs: Optional[Sequence[Dict[str, int]]] = None,
        name: str = "engine",
    ) -> None:
        self._devices: List[Any] = list(mesh.devices.flat)
        boot = {str(a): int(s) for a, s in mesh.shape.items()}
        plans = (
            [dict(r) for r in rungs] if rungs else default_ladder(boot)
        )
        # The boot plan is always rung 0 — an explicit ladder that
        # omits it would otherwise report a degraded rung at boot.
        if not plans or _plan_devices(plans[0]) != _plan_devices(boot) or {
            a: int(plans[0].get(a, 1)) for a in AXIS_NAMES
        } != {a: int(boot.get(a, 1)) for a in AXIS_NAMES}:
            plans.insert(0, boot)
        for p in plans:
            if _plan_devices(p) > len(self._devices):
                raise ValueError(
                    f"mesh ladder rung {p} needs {_plan_devices(p)} "
                    f"devices; boot set has {len(self._devices)}"
                )
        self._plans = plans
        self._rung = 0
        self._lost: set = set()
        self._frozen: set = set()
        self._exhausted = False
        self._lock = threading.Lock()
        self._mesh = mesh
        self._beats: List[float] = [time.monotonic()] * len(self._devices)
        self._log = get_logger("parallel.meshplan")
        self.name = name

    # ------------------------------------------------------------------ #
    # Introspection
    # ------------------------------------------------------------------ #

    @property
    def rung(self) -> int:
        """Active ladder rung (0 = boot plan; the gauge value of
        ``engine.mesh_plan``)."""
        return self._rung

    @property
    def exhausted(self) -> bool:
        return self._exhausted

    @property
    def mesh(self) -> Any:
        """The active ``jax.sharding.Mesh`` (boot mesh until the first
        successful ``replan``)."""
        return self._mesh

    def plan(self) -> Dict[str, int]:
        return dict(self._plans[self._rung])

    def plans(self) -> List[Dict[str, int]]:
        return [dict(p) for p in self._plans]

    def lost(self) -> List[int]:
        with self._lock:
            return sorted(self._lost)

    def surviving(self) -> List[Any]:
        with self._lock:
            return [
                d for i, d in enumerate(self._devices) if i not in self._lost
            ]

    def viable(self) -> bool:
        """Would a ``replan()`` right now find a rung? (No mutation —
        the failure arm asks this before deciding whether recovery or
        fail-with-original-exception applies.)"""
        n = len(self.surviving())
        return n > 0 and any(
            _plan_devices(p) <= n for p in self._plans[self._rung:]
        )

    # ------------------------------------------------------------------ #
    # Loss bookkeeping + re-planning (device thread)
    # ------------------------------------------------------------------ #

    def mark_lost(self, device_index: int) -> None:
        idx = int(device_index)
        with self._lock:
            if 0 <= idx < len(self._devices):
                self._lost.add(idx)
                self._frozen.discard(idx)

    def replan(self) -> Any:
        """Build a mesh over the surviving devices for the first rung
        (from the active one down) that fits. Raises
        ``MeshLadderExhausted`` when none does — the caller's recovery
        contract ends and in-flight requests fail with the original
        exception (PR 8 semantics)."""
        surv = self.surviving()
        with self._lock:
            start = self._rung
        for i in range(start, len(self._plans)):
            p = self._plans[i]
            need = _plan_devices(p)
            if need > len(surv):
                continue
            cfg = MeshConfig.from_dict(
                {a: int(p.get(a, 1)) for a in AXIS_NAMES}
            )
            # create_mesh reshapes exactly n_devices — hand it the
            # first ``need`` survivors in boot order (deterministic,
            # so two replicas degrading identically build identical
            # meshes).
            mesh = create_mesh(cfg, surv[:need])
            with self._lock:
                self._rung = i
                self._mesh = mesh
            if i != start or self._lost:
                self._log.warning(
                    "mesh re-planned to rung %d (%s) over %d surviving "
                    "device(s); lost=%s", i, plan_label(p), len(surv),
                    self.lost(),
                )
            return mesh
        self._exhausted = True
        raise MeshLadderExhausted(
            f"no mesh rung fits {len(surv)} surviving device(s); "
            f"ladder={[plan_label(p) for p in self._plans]}, "
            f"lost={self.lost()}"
        )

    # ------------------------------------------------------------------ #
    # Per-shard heartbeats (riding the PR 8 watchdog)
    # ------------------------------------------------------------------ #

    def beat_all(self) -> None:
        """Fold-path heartbeat for every live, unfrozen shard (lock-free
        plain stores — the watchdog contract). A fold completing proves
        the whole active mesh answered; a *frozen* shard (the
        ``mesh.shard_loss`` hang variant, or a real per-device probe in
        a production backend) goes stale while its siblings keep
        beating — the watchdog's stall hook reads ``stale()`` to tell a
        shard loss from a whole-engine hang."""
        now = time.monotonic()
        frozen = self._frozen
        lost = self._lost
        beats = self._beats
        for i in range(len(beats)):
            if i not in frozen and i not in lost:
                beats[i] = now

    def freeze(self, device_index: int) -> None:
        """Stop ``beat_all`` from refreshing one shard (chaos: a shard
        that hangs instead of raising)."""
        with self._lock:
            idx = int(device_index)
            if 0 <= idx < len(self._devices):
                self._frozen.add(idx)

    def stale(self, stall_s: float, now: Optional[float] = None) -> List[int]:
        """Live shards whose heartbeat is older than ``stall_s``."""
        now = time.monotonic() if now is None else now
        with self._lock:
            return [
                i for i, t in enumerate(self._beats)
                if i not in self._lost and now - t >= stall_s
            ]

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "rung": self._rung,
                "plan": plan_label(self._plans[self._rung]),
                "plans": [plan_label(p) for p in self._plans],
                "lost": sorted(self._lost),
                "devices": len(self._devices),
                "exhausted": self._exhausted,
            }
