"""Ring attention: exact attention over a sequence-sharded context.

Long-context plan (SURVEY.md §5.7): activations are sharded over the
``seq`` mesh axis; instead of all-gathering K/V (XLA's default when it
meets a sequence-sharded attention), each device keeps running online-
softmax statistics for its local queries while K/V chunks rotate around
the ring via ``ppermute`` — every step overlaps the neighbor transfer
(ICI) with the local block's matmuls, and no device ever holds more than
one K/V chunk beyond its own.

Built on ``shard_map`` so it composes with the 4-axis mesh: batch stays
sharded over data/fsdp, heads over model, sequence over seq. The whole
thing is differentiable (ppermute transposes to the reverse rotation),
so the training path can use it directly.

No reference counterpart (SURVEY.md §2.13 — the reference has no model
execution at all).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pilottai_tpu.ops.attention import NEG_INF, flash_enabled, flash_shapes_ok
from pilottai_tpu.parallel.mesh import compat_shard_map
from pilottai_tpu.parallel.sharding import _current_mesh

# Logical shardings of the operands (mesh axes, not logical names, because
# shard_map wants PartitionSpecs over the mesh directly).
_Q_SPEC = P(("data", "fsdp"), "seq", "model", None)
_KV_SPEC = P(("data", "fsdp"), "seq", "model", None)
_POS_SPEC = P(("data", "fsdp"), "seq")
_VALID_SPEC = P(("data", "fsdp"))


def _block_attend(q, k, v, s_mask, scale, softcap, m, l, acc):
    """One online-softmax accumulation step. q [T,N?,H]-free layout:
    operands are [B, Tq, K, G, H] x [B, Tk, K, H]."""
    s = jnp.einsum("btkgh,bskh->bkgts", q, k).astype(jnp.float32) * scale
    if softcap > 0.0:
        s = jnp.tanh(s / softcap) * softcap
    s = jnp.where(s_mask, s, NEG_INF)                     # [B, K, G, Tq, Tk]
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bkgts,bskh->bkgth", p.astype(v.dtype), v).astype(jnp.float32)
    acc_new = acc * corr[..., 0][..., None] + pv
    return m_new, l_new, acc_new


def ring_attention(
    q: jax.Array,             # [B, T, N, H] — T sharded over `axis`
    k: jax.Array,             # [B, T, K, H]
    v: jax.Array,             # [B, T, K, H]
    q_positions: jax.Array,   # [B, T] absolute positions
    valid: jax.Array,         # [B] valid length (global sequence index bound)
    window: jax.Array,        # scalar int32; 0 = global
    scale: Optional[float] = None,
    softcap: float = 0.0,
    axis: str = "seq",
    mesh: Optional[Mesh] = None,
    use_flash: Optional[bool] = None,
    interpret: bool = False,
) -> jax.Array:
    """Causal GQA attention with K/V rotating around the ``axis`` ring.

    Mask semantics match ``models/transformer.py`` prefill: attend iff
    kv_pos <= q_pos, kv sequence index < valid, and (window == 0 or
    q_pos - kv_pos < window).

    Each ring step's local block runs through the Pallas flash kernel on
    TPU (``flash_attention_with_lse``; VERDICT r2 next-step 8 — the ring
    used to pay dense O(Tl·Tl) XLA math per step). Steps merge by their
    log-sum-exp rows, which is exact; the lse cotangent flows through the
    kernel's custom VJP, so training uses the same path. ``use_flash``
    overrides the TPU autodetect (tests force it with ``interpret``).
    """
    mesh = mesh if mesh is not None else _current_mesh()
    if mesh is None:
        raise ValueError("ring_attention needs a mesh (or jax.set_mesh context)")
    B, T, N, H = q.shape
    K = k.shape[2]
    G = N // K
    scale = scale if scale is not None else H ** -0.5
    P_ring = mesh.shape[axis]
    window = jnp.asarray(window, jnp.int32)
    Tl = T // P_ring
    if use_flash is None:
        use_flash = flash_enabled() and flash_shapes_ok(
            Tl, Tl, head_dim=H, itemsize=q.dtype.itemsize
        )

    def per_device_flash(q, k, v, qpos, valid, window):
        from pilottai_tpu.ops.pallas.flash_attention import (
            flash_attention_with_lse,
        )

        Bl, Tl = q.shape[0], q.shape[1]
        my = jax.lax.axis_index(axis)
        kpos = qpos                                   # kv chunk starts local
        start = jnp.full((1,), my * Tl, jnp.int32)    # chunk's global offset

        M = jnp.full((Bl, Tl, q.shape[2], 1), NEG_INF, jnp.float32)
        num = jnp.zeros((Bl, Tl, q.shape[2], H), jnp.float32)
        den = jnp.zeros_like(M)

        perm = [(j, (j + 1) % P_ring) for j in range(P_ring)]
        for step in range(P_ring):
            # The kernel's valid is a LOCAL kv-index bound; translate the
            # global valid length by this chunk's offset in the sequence.
            valid_eff = jnp.clip(valid - start[0], 0, Tl)
            o_i, lse_i = flash_attention_with_lse(
                q, k, v, qpos, kpos, valid_eff, window,
                scale=scale, softcap=softcap, interpret=interpret,
            )                                         # o [B,Tl,N,H]; lse [B,Tl,N,1]
            M_new = jnp.maximum(M, lse_i)
            w = jnp.where(lse_i > NEG_INF / 2, jnp.exp(lse_i - M_new), 0.0)
            corr = jnp.where(M > NEG_INF / 2, jnp.exp(M - M_new), 0.0)
            num = num * corr + o_i.astype(jnp.float32) * w
            den = den * corr + w
            M = M_new
            if step + 1 < P_ring:
                k = jax.lax.ppermute(k, axis, perm)
                v = jax.lax.ppermute(v, axis, perm)
                kpos = jax.lax.ppermute(kpos, axis, perm)
                start = jax.lax.ppermute(start, axis, perm)

        out = num / jnp.maximum(den, 1e-30)
        out = jnp.where(den > 0.0, out, 0.0)
        return out.astype(v.dtype)

    def per_device(q, k, v, qpos, valid, window):
        # Local shapes: q [Bl, Tl, Nl, H], k/v [Bl, Tl, Kl, H], qpos [Bl, Tl].
        Bl, Tl = q.shape[0], q.shape[1]
        Kl = k.shape[2]
        my = jax.lax.axis_index(axis)
        q = q.reshape(Bl, Tl, Kl, G, H)

        kpos = qpos                                   # kv chunk starts local
        jidx = my * Tl + jax.lax.broadcasted_iota(jnp.int32, (1, Tl), 1)

        m = jnp.full((Bl, Kl, G, Tl, 1), NEG_INF, jnp.float32)
        l = jnp.zeros_like(m)
        acc = jnp.zeros((Bl, Kl, G, Tl, H), jnp.float32)

        perm = [(j, (j + 1) % P_ring) for j in range(P_ring)]
        for step in range(P_ring):
            ip = qpos[:, None, :, None]               # [B, 1, Tq, 1]
            jp = kpos[:, None, None, :]               # [B, 1, 1, Tk]
            mask = (jp <= ip) & (jidx[:, None, None, :] < valid[:, None, None, None])
            mask &= (window <= 0) | ((ip - jp) < window)
            mask = mask[:, :, None, :, :]             # [B, 1, 1, Tq, Tk]
            m, l, acc = _block_attend(q, k, v, mask, scale, softcap, m, l, acc)
            if step + 1 < P_ring:
                k = jax.lax.ppermute(k, axis, perm)
                v = jax.lax.ppermute(v, axis, perm)
                kpos = jax.lax.ppermute(kpos, axis, perm)
                jidx = jax.lax.ppermute(jidx, axis, perm)

        out = acc / jnp.maximum(l, 1e-30)
        out = jnp.where(l > 0.0, out, 0.0)
        return (
            out.transpose(0, 3, 1, 2, 4)
            .reshape(Bl, Tl, Kl * G, H)
            .astype(v.dtype)
        )

    return compat_shard_map(
        per_device_flash if use_flash else per_device,
        mesh=mesh,
        in_specs=(_Q_SPEC, _KV_SPEC, _KV_SPEC, _POS_SPEC, _VALID_SPEC, P()),
        out_specs=_Q_SPEC,
        check_vma=False,
    )(q, k, v, q_positions, valid, window)
