"""Device mesh construction for the engine and trainer.

Axes (superset; size-1 axes cost nothing under XLA):

* ``data``  — data parallel (batch replicas; gradients psum over it)
* ``fsdp``  — parameter/optimizer sharding (weights gathered per layer)
* ``model`` — tensor parallel (heads / ffn sharded; activations
  all-reduced over ICI)
* ``seq``   — sequence/context parallel (ring attention over ICI)

No reference counterpart (SURVEY.md §2.13). Multi-host: `initialize()`
wraps ``jax.distributed.initialize`` so the same mesh spans hosts over DCN.
"""

from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

AXIS_NAMES = ("data", "fsdp", "model", "seq")


@dataclass(frozen=True)
class MeshConfig:
    data: int = 1
    fsdp: int = 1
    model: int = 1
    seq: int = 1

    @property
    def shape(self) -> Dict[str, int]:
        return {"data": self.data, "fsdp": self.fsdp, "model": self.model, "seq": self.seq}

    @property
    def n_devices(self) -> int:
        return self.data * self.fsdp * self.model * self.seq

    @classmethod
    def from_dict(cls, d: Optional[Dict[str, int]]) -> "MeshConfig":
        if not d:
            return cls()
        return cls(**{k: int(v) for k, v in d.items() if k in AXIS_NAMES})


def compat_shard_map(fn, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across the jax versions the repo supports: the
    top-level alias (and its ``check_vma`` kwarg) only exists on newer
    jax; 0.4.x ships ``jax.experimental.shard_map`` with ``check_rep``.
    One shim so every sharded entry point (pipeline, ring attention,
    sharded flash) degrades identically instead of each call site
    AttributeError-ing on whichever jax the host has."""
    try:
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map

        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma,
        )


def compat_set_mesh(mesh: Mesh):
    """Context manager that makes ``mesh`` the ambient mesh across jax
    versions. Newer jax requires ``jax.set_mesh`` around jitted code
    that uses explicit shardings; on 0.4.x that API does not exist AND
    the legacy ``with mesh:`` physical-mesh context must NOT be
    substituted — it flips pjit into its xmap-era semantics, which
    breaks donation aliasing (measured: trainer steps fail with
    mismatched aliased buffer sizes). On 0.4.x the NamedShardings
    attached to args/outputs already carry the mesh, so the correct
    compat is a no-op context."""
    try:
        return jax.set_mesh(mesh)
    except AttributeError:
        import contextlib

        return contextlib.nullcontext(mesh)


def create_mesh(
    config: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a 4-axis mesh over ``devices`` (default: all local devices).

    Device order follows jax.devices(), which on TPU respects the physical
    torus ordering so the innermost axis (``model``) lands on the
    fastest-ICI neighbors.
    """
    devices = list(devices if devices is not None else jax.devices())
    config = config or best_mesh_config(len(devices))
    if config.n_devices > len(devices):
        raise ValueError(
            f"mesh {config.shape} needs {config.n_devices} devices, "
            f"only {len(devices)} available"
        )
    devices = devices[: config.n_devices]
    grid = np.asarray(devices).reshape(config.data, config.fsdp, config.model, config.seq)
    return Mesh(grid, AXIS_NAMES)


def best_mesh_config(n_devices: int, tp_max: int = 8) -> MeshConfig:
    """Default layout: fill tensor parallel up to ``tp_max`` (keeps the
    all-reduce inside one slice's ICI), spread the rest over data."""
    model = math.gcd(n_devices, tp_max)
    data = n_devices // model
    return MeshConfig(data=data, model=model)


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up over DCN (reference has no equivalent — its
    "distributed" is one asyncio loop, SURVEY.md §2.14).

    No-ops when single-process or when jax.distributed is already live, so
    it is safe to call unconditionally at engine start.
    """
    if num_processes in (None, 1) and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return
    # Only double-initialization is ignorable. A genuine bring-up failure
    # (unreachable coordinator, wrong world size) must be LOUD — swallowing
    # it would let each host proceed with a local-only mesh and silently
    # inconsistent sharding.
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None and is_init():
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except RuntimeError as exc:
        if "already initialized" in str(exc).lower():
            return
        raise
