"""Per-axis collective-traffic attribution for the sharded serving path.

PR 6 built ``engine.collective_frac[.axis]`` so multichip serving could
attribute its interconnect time, but until the KV pool and decode state
actually sharded (ISSUE 13) nothing ever recorded a ``collective`` phase
and the gauges sat at 0 regardless of mesh shape. This module closes
that loop without requiring a profiler in the serving hot path:

* :class:`CollectiveModel` — a closed-form per-dispatch estimate of the
  bytes each mesh axis moves for one decode block / one prefill token,
  converted to seconds against a per-platform interconnect bandwidth.
  The batcher carves the estimate OUT of its measured dispatch walls
  (``decode`` + ``collective`` records sum to the same total), so
  ``collective_frac`` is an attribution split of real time, never
  invented time. The formulas mirror what GSPMD inserts for the
  sharding rules in ``parallel/sharding.py``:

  - **model axis** (tensor parallel): the attention output projection
    and the MLP down projection each end in a row-parallel matmul whose
    result all-reduces over ``model`` — 2 all-reduces of ``[B, T, E]``
    per layer — plus the logits all-gather over the vocab shard at the
    unembed. Ring all-reduce moves ``2 (M-1)/M`` of the payload per
    chip; all-gather ``(M-1)/M``.
  - **data axis** (batch parallel): steady-state decode is local —
    slots, decode state and the dense cache batch dim are sharded over
    ``data`` and never cross it. The cross-group term that remains is
    the PAGED pool: pages are a global resource (any slot may hold any
    page), so the pool replicates over ``data`` and every chunk-end
    ring scatter / admission prompt scatter all-gathers its updates
    across the data groups.

* :func:`collective_ops` — parse collective ops (op kind, payload
  bytes, replica groups) out of compiled/optimized HLO text and map
  each to the mesh axis its replica groups span. Not used in the hot
  path: it exists so tests can pin that the sharded decode executable
  REALLY contains model-axis collectives (the premise the analytic
  model rests on) instead of trusting the formula blindly.

Estimates are documented as estimates (docs/PERF_NOTES.md round 10):
the point is a live, always-on, per-axis split whose magnitude tracks
the mesh shape, not a profiler replacement.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# Interconnect bandwidth per chip, bytes/s, used to turn modeled bytes
# into modeled seconds. TPU v5e ICI: 1.6 Tbit/s aggregate ≈ 2e11 B/s
# usable per direction per chip (scaling-book figure); the CPU value is
# a nominal host-memcpy figure so virtual-mesh runs produce finite,
# comparable-within-themselves fractions (same contract as the CPU
# peak-FLOPs placeholder in obs/attribution.py).
_ICI_BYTES_PER_S = {"tpu": 2.0e11, "gpu": 1.0e11, "cpu": 1.0e10}

_DTYPE_BYTES = {"bfloat16": 2, "float32": 4, "float16": 2, "int8": 1}


def interconnect_bytes_per_s(platform: str) -> float:
    return _ICI_BYTES_PER_S.get(platform, _ICI_BYTES_PER_S["cpu"])


@dataclass
class CollectiveModel:
    """Closed-form per-axis collective seconds for one engine dispatch.

    Built once at batcher construction from the model config and mesh
    shape; evaluated per fold with plain float math (no locks, no jax).
    """

    model_size: int = 1       # mesh 'model' axis extent (tensor parallel)
    data_size: int = 1        # combined batch-axis extent (data × fsdp)
    data_axis: str = "data"   # gauge key for the batch-parallel term —
                              # the mesh's REAL batch axis name, so
                              # collective_frac.<axis> and the declared
                              # counters line up on an fsdp-only mesh
                              # (a combined data×fsdp mesh books the
                              # whole term under 'data')
    n_layers: int = 0
    hidden: int = 0
    vocab: int = 0
    dtype_bytes: int = 2
    paged: bool = False
    kv_bytes_per_token: int = 0   # per-token K+V bytes across layers
    bytes_per_s: float = _ICI_BYTES_PER_S["cpu"]

    @classmethod
    def for_mesh(
        cls,
        mesh: Optional[Any],
        cfg: Any,
        *,
        platform: str,
        paged: bool,
        kv_quantize: bool,
    ) -> Optional["CollectiveModel"]:
        """None when the mesh is absent or single-device (nothing to
        attribute — the gauges stay 0 exactly as before)."""
        if mesh is None or int(mesh.devices.size) <= 1:
            return None
        shape = dict(mesh.shape)
        model = int(shape.get("model", 1))
        data = int(shape.get("data", 1)) * int(shape.get("fsdp", 1))
        if model <= 1 and data <= 1:
            return None
        item = _DTYPE_BYTES.get(jnp_dtype_name(cfg.dtype), 2)
        kv_item = 1 if kv_quantize else item
        return cls(
            model_size=model,
            data_size=data,
            data_axis=(
                "data" if int(shape.get("data", 1)) > 1 else "fsdp"
            ),
            n_layers=int(cfg.n_layers),
            hidden=int(cfg.hidden_size),
            vocab=int(cfg.vocab_size),
            dtype_bytes=item,
            paged=paged,
            kv_bytes_per_token=(
                2 * int(cfg.n_layers) * int(cfg.n_kv_heads)
                * int(cfg.head_dim) * kv_item
            ),
            bytes_per_s=interconnect_bytes_per_s(platform),
        )

    # ------------------------------------------------------------------ #

    def _model_axis_bytes(self, tokens: int) -> float:
        """Per-chip bytes the ``model`` axis moves for ``tokens`` token
        positions through the trunk: 2 activation all-reduces per layer
        (attention out-projection + MLP down-projection, ring factor
        2(M-1)/M) plus the logits all-gather at the unembed
        ((M-1)/M of the full-vocab row)."""
        if self.model_size <= 1 or tokens <= 0:
            return 0.0
        m = self.model_size
        act = tokens * self.hidden * self.dtype_bytes
        allreduce = 2.0 * self.n_layers * act * 2.0 * (m - 1) / m
        # Logits are fp32 at the sampler boundary.
        logits = tokens * self.vocab * 4.0 * (m - 1) / m
        return allreduce + logits

    def _data_axis_bytes(self, tokens: int) -> float:
        """Per-chip bytes the ``data`` axis moves to keep the
        data-replicated paged pool coherent: each written token's K/V
        rows all-gather across the D groups ((D-1)/D). Dense caches
        shard their batch dim over ``data`` and pay nothing here."""
        if self.data_size <= 1 or tokens <= 0 or not self.paged:
            return 0.0
        d = self.data_size
        return tokens * self.kv_bytes_per_token * (d - 1) / d

    # ------------------------------------------------------------------ #

    def decode_seconds(
        self, n_blocks: int, batch: int, written_tokens: int
    ) -> Dict[str, float]:
        """Per-axis collective seconds for one folded decode chunk:
        ``n_blocks`` block-steps over ``batch`` slots (every slot runs
        the trunk whether or not its output is kept), with
        ``written_tokens`` accepted tokens landing in the cache at the
        chunk-end scatter."""
        out: Dict[str, float] = {}
        # Trunk all-reduces run per block over the whole slot batch; the
        # batch dim is sharded over data, so the per-chip activation
        # payload is batch / data rows.
        rows = n_blocks * max(batch, 1) / max(self.data_size, 1)
        m_bytes = self._model_axis_bytes(int(round(rows)))
        if m_bytes > 0.0:
            out["model"] = m_bytes / self.bytes_per_s
        d_bytes = self._data_axis_bytes(written_tokens)
        if d_bytes > 0.0:
            out[self.data_axis] = d_bytes / self.bytes_per_s
        return out

    def prefill_seconds(self, tokens: int) -> Dict[str, float]:
        """Per-axis collective seconds for one admission prefill over
        ``tokens`` prompt tokens (trunk all-reduces + the paged prompt
        scatter's cross-group gather)."""
        out: Dict[str, float] = {}
        m_bytes = self._model_axis_bytes(
            int(round(tokens / max(self.data_size, 1)))
        )
        if m_bytes > 0.0:
            out["model"] = m_bytes / self.bytes_per_s
        d_bytes = self._data_axis_bytes(tokens)
        if d_bytes > 0.0:
            out[self.data_axis] = d_bytes / self.bytes_per_s
        return out

    def split(
        self, wall_s: float, est: Dict[str, float], cap: float = 0.5
    ) -> Tuple[float, Dict[str, float]]:
        """Attribution split of a measured dispatch wall: scale the
        estimate down if it would claim more than ``cap`` of the wall
        (the model must never invent time — a mis-sized bandwidth
        constant degrades to a bounded overestimate, not a negative
        compute record). Returns ``(compute_s, {axis: collective_s})``."""
        total = sum(est.values())
        if total <= 0.0 or wall_s <= 0.0:
            return max(wall_s, 0.0), {}
        scale = min(1.0, (cap * wall_s) / total)
        scaled = {ax: s * scale for ax, s in est.items()}
        return max(wall_s - sum(scaled.values()), 0.0), scaled


def jnp_dtype_name(dtype: Any) -> str:
    try:
        return np.dtype(dtype).name
    except TypeError:
        return str(dtype)


# --------------------------------------------------------------------- #
# HLO inspection (tests / diagnostics — not the serving hot path)
# --------------------------------------------------------------------- #

_COLLECTIVE_RE = re.compile(
    r"^\s*(?:ROOT\s+)?(?:%?[\w.\-]+\s*=\s*)?"
    r"(?:\(?([a-z0-9]+)\[([\d,]*)\][^)]*\)?)\s*"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|all-to-all)"
    r"([\w\-.]*)\(",
)
_GROUPS_RE = re.compile(r"replica_groups=\{(\{[\d,{} ]*\})\}")
_HLO_DTYPE_BYTES = {
    "f32": 4, "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "s8": 1, "u8": 1,
    "pred": 1, "s64": 8, "u64": 8, "f64": 8,
}


@dataclass
class CollectiveOp:
    kind: str
    dtype: str
    shape: Tuple[int, ...]
    bytes: int
    groups: Tuple[Tuple[int, ...], ...] = ()
    axis: Optional[str] = None

    @property
    def group_size(self) -> int:
        return max((len(g) for g in self.groups), default=0)


def _axis_groups(mesh: Any) -> Dict[str, frozenset]:
    """For each mesh axis: the canonical set of linear-device-index
    groups a collective spanning exactly that axis would use."""
    shape = tuple(int(s) for s in mesh.devices.shape)
    lin = np.arange(int(np.prod(shape))).reshape(shape)
    out: Dict[str, frozenset] = {}
    for k, name in enumerate(mesh.axis_names):
        if shape[k] <= 1:
            continue
        moved = np.moveaxis(lin, k, -1).reshape(-1, shape[k])
        out[str(name)] = frozenset(
            frozenset(int(x) for x in row) for row in moved
        )
    return out


def collective_ops(
    hlo_text: str, mesh: Optional[Any] = None
) -> List[CollectiveOp]:
    """Collective ops in (optimized) HLO text, with payload bytes and —
    when ``mesh`` is given — the mesh axis whose device groups match
    each op's ``replica_groups`` (None when the groups span several
    axes or could not be parsed)."""
    axis_groups = _axis_groups(mesh) if mesh is not None else {}
    ops: List[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.match(line)
        if not m:
            continue
        dtype, dims, kind = m.group(1), m.group(2), m.group(3)
        # Async pairs (all-reduce-start / all-reduce-done) both carry
        # the full result payload; count the -start half only, else
        # TPU-optimized HLO reports ~2x bytes with the -done half
        # landing under "other" (no replica_groups on -done).
        if m.group(4).startswith("-done"):
            continue
        shape = tuple(int(d) for d in dims.split(",") if d) if dims else ()
        nbytes = _HLO_DTYPE_BYTES.get(dtype, 4)
        for d in shape:
            nbytes *= d
        groups: Tuple[Tuple[int, ...], ...] = ()
        gm = _GROUPS_RE.search(line)
        if gm:
            groups = tuple(
                tuple(int(x) for x in g.split(",") if x.strip())
                for g in re.findall(r"\{([\d, ]*)\}", gm.group(1))
            )
        axis = None
        if groups and axis_groups:
            gset = frozenset(frozenset(g) for g in groups if len(g) > 1)
            for name, expect in axis_groups.items():
                if gset and gset <= expect:
                    axis = name
                    break
        ops.append(CollectiveOp(
            kind=kind, dtype=dtype, shape=shape, bytes=nbytes,
            groups=groups, axis=axis,
        ))
    return ops


def collective_bytes_by_axis(
    hlo_text: str, mesh: Any
) -> Dict[str, int]:
    """Total collective payload bytes per mesh axis in ``hlo_text``
    (unattributable ops land under ``"other"``)."""
    out: Dict[str, int] = {}
    for op in collective_ops(hlo_text, mesh):
        key = op.axis or "other"
        out[key] = out.get(key, 0) + op.bytes
    return out
