"""Mesh-sharded training loop: loss, optimizer, jitted train step.

The reference has **no training path at all** (SURVEY.md §1 "What the
reference is NOT" — it is an asyncio orchestration layer over remote LLM
APIs). Training is introduced by the TPU north star: agents fine-tuned
in-tree must run the same sharded compute path the serving engine uses.

Design (scaling-book recipe):
* one 4-axis ``Mesh`` (data/fsdp/model/seq — ``parallel/mesh.py``),
* parameters placed by logical-axis rules (``parallel/sharding.py``),
* the train step is a single ``jax.jit`` with donated state; XLA inserts
  the gradient psum over data/fsdp and the TP all-reduces over ICI,
* ``jax.checkpoint`` remat inside the layer scan trades FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pilottai_tpu.models.common import ModelConfig, init_params, param_logical_axes
from pilottai_tpu.models.transformer import forward_train
from pilottai_tpu.parallel.mesh import compat_set_mesh, create_mesh
from pilottai_tpu.parallel.sharding import (
    logical_to_spec,
    shard_params,
    spec_tree_for,
)


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    learning_rate: float = 3e-4
    warmup_steps: int = 100
    total_steps: int = 10_000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0
    remat: bool = True
    param_dtype: Any = jnp.float32  # master weights fp32; compute casts to bf16
    # Ring attention over the mesh's 'seq' axis (context parallelism) —
    # K/V chunks rotate over ICI instead of XLA all-gathering them.
    context_parallel: bool = False
    # Weight on the MoE load-balancing auxiliary loss (Switch-style);
    # ignored for dense models.
    moe_aux_weight: float = 0.01


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=tc.learning_rate,
        warmup_steps=tc.warmup_steps,
        decay_steps=max(tc.total_steps, tc.warmup_steps + 1),
        end_value=tc.learning_rate * 0.1,
    )
    return optax.chain(
        optax.clip_by_global_norm(tc.grad_clip),
        optax.adamw(
            schedule, b1=tc.b1, b2=tc.b2, weight_decay=tc.weight_decay
        ),
    )


def next_token_loss(
    logits: jax.Array,   # [B, T, V] fp32
    tokens: jax.Array,   # [B, T]
    valid: jax.Array,    # [B]
    loss_start: Optional[jax.Array] = None,  # [B] first TARGET index
) -> jax.Array:
    """Mean next-token cross-entropy over valid (non-pad) positions.

    ``loss_start[b]`` masks the loss to predictions of tokens at indices
    >= loss_start[b] — prompt-masked supervised fine-tuning (the protocol
    model learns the *response*, not to model its own prompts). None (or
    zeros) is plain LM loss over the whole row.
    """
    T = tokens.shape[1]
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    pos = jnp.arange(T - 1)[None, :]  # position i predicts token i+1
    mask = (pos < (valid - 1)[:, None]).astype(jnp.float32)
    if loss_start is not None:
        mask = mask * (pos + 1 >= loss_start[:, None]).astype(jnp.float32)
    return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)


class Trainer:
    """Owns mesh, sharded state and the compiled train step.

    Usage::

        t = Trainer(model_cfg, TrainConfig(), mesh=my_mesh)
        state = t.init(jax.random.key(0))
        state, metrics = t.step(state, batch)   # batch: tokens/valid
    """

    def __init__(
        self,
        model_cfg: ModelConfig,
        train_cfg: Optional[TrainConfig] = None,
        mesh: Optional[Mesh] = None,
        rules: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.model_cfg = model_cfg
        self.train_cfg = train_cfg or TrainConfig()
        self.mesh = mesh if mesh is not None else create_mesh()
        self.rules = rules
        self.optimizer = make_optimizer(self.train_cfg)
        self._param_axes = param_logical_axes(model_cfg)
        self._param_specs = spec_tree_for(self._param_axes, rules)
        self._opt_shardings_tree = None
        self._step = self._build_step()

    # ------------------------------------------------------------- #
    # State init
    # ------------------------------------------------------------- #
    def init(self, rng: jax.Array) -> Tuple[Any, Any]:
        """Initialize (params, opt_state), placed on the mesh.

        Params are constrained to their logical shardings inside jit so
        the fp32 master copy is materialized already-sharded (never one
        full replica per host); optimizer moments inherit the same
        placement through XLA's sharding propagation.
        """
        cfg, tc = self.model_cfg, self.train_cfg
        param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._param_specs
        )

        def _init(rng):
            params = init_params(cfg, rng, dtype=tc.param_dtype)
            params = jax.lax.with_sharding_constraint(params, param_shardings)
            opt_state = self.optimizer.init(params)
            return params, opt_state

        # Pin the opt-state layout here AND on the train step's outputs:
        # leaving it unspecified lets the two jitted programs pick
        # different layouts, and the step's donated state then fails
        # aliasing at dispatch (jax 0.4.x rejects it; newer jax silently
        # copies — either way the donation is lost).
        with compat_set_mesh(self.mesh):
            return jax.jit(
                _init,
                out_shardings=(param_shardings, self._opt_shardings()),
            )(rng)

    # ------------------------------------------------------------- #
    # Train step
    # ------------------------------------------------------------- #
    def _build_step(self):
        cfg, tc = self.model_cfg, self.train_cfg
        optimizer = self.optimizer
        compute_dtype = cfg.dtype

        ring_mesh = (
            self.mesh
            if tc.context_parallel and self.mesh.shape.get("seq", 1) > 1
            else None
        )
        # No sequence sharding → the Pallas flash kernel (fwd + bwd) runs
        # per-shard under shard_map on TPU meshes; ring attention owns the
        # seq-sharded case. _full_seq_block falls back to XLA dense when
        # off-TPU or shapes don't divide.
        flash_mesh = (
            self.mesh
            if ring_mesh is None and self.mesh.devices.size > 1
            else None
        )

        def train_step(params, opt_state, tokens, valid, loss_start):
            B, T = tokens.shape
            positions = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T))

            def loss_fn(p):
                compute_p = jax.tree.map(
                    lambda a: a.astype(compute_dtype)
                    if jnp.issubdtype(a.dtype, jnp.floating)
                    else a,
                    p,
                )
                logits, moe_aux = forward_train(
                    compute_p, cfg, tokens, positions, valid,
                    remat=tc.remat, ring_mesh=ring_mesh,
                    flash_mesh=flash_mesh,
                )
                lm_loss = next_token_loss(logits, tokens, valid, loss_start)
                return lm_loss + tc.moe_aux_weight * moe_aux, (lm_loss, moe_aux)

            (loss, (lm_loss, moe_aux)), grads = jax.value_and_grad(
                loss_fn, has_aux=True
            )(params)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            metrics = {
                "loss": lm_loss,
                "total_loss": loss,
                "moe_aux": moe_aux,
                "grad_norm": optax.global_norm(grads),
                "tokens": jnp.sum(valid).astype(jnp.float32),
            }
            return params, opt_state, metrics

        batch_spec = logical_to_spec(("batch", "seq"), self.rules)
        valid_spec = logical_to_spec(("batch",), self.rules)
        param_shardings = jax.tree.map(
            lambda s: NamedSharding(self.mesh, s), self._param_specs
        )
        return jax.jit(
            train_step,
            in_shardings=(
                param_shardings,
                self._opt_shardings(),  # must match init's output layout
                NamedSharding(self.mesh, batch_spec),
                NamedSharding(self.mesh, valid_spec),
                NamedSharding(self.mesh, valid_spec),  # loss_start
            ),
            # Pin output params AND opt state to the same placement as
            # the inputs so the donated state aliases cleanly and
            # round-trips through step() without resharding.
            out_shardings=(param_shardings, self._opt_shardings(), None),
            donate_argnums=(0, 1),
        )

    def _opt_shardings(self):
        """NamedShardings for the optimizer state: moment leaves mirror
        their parameters' shardings (``optax.tree_map_params`` walks the
        state's param-shaped subtrees), everything else — step counts,
        empty states — replicates. One tree shared by ``init`` and the
        train step keeps the donated state's layout bit-stable across
        both programs."""
        if self._opt_shardings_tree is None:
            param_shardings = jax.tree.map(
                lambda s: NamedSharding(self.mesh, s), self._param_specs
            )
            shapes = jax.eval_shape(
                lambda r: init_params(
                    self.model_cfg, r, dtype=self.train_cfg.param_dtype
                ),
                jax.random.key(0),
            )
            opt_shape = jax.eval_shape(self.optimizer.init, shapes)
            repl = NamedSharding(self.mesh, P())
            self._opt_shardings_tree = optax.tree_map_params(
                self.optimizer,
                lambda _leaf, sharding: sharding,
                opt_shape,
                param_shardings,
                transform_non_params=lambda _leaf: repl,
            )
        return self._opt_shardings_tree

    def step(
        self, state: Tuple[Any, Any], batch: Dict[str, jax.Array]
    ) -> Tuple[Tuple[Any, Any], Dict[str, jax.Array]]:
        params, opt_state = state
        tokens, valid, loss_start = self.shard_batch(batch)
        with compat_set_mesh(self.mesh):
            params, opt_state, metrics = self._step(
                params, opt_state, tokens, valid, loss_start
            )
        return (params, opt_state), metrics

    def shard_batch(
        self, batch: Dict[str, Any]
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        tokens = jnp.asarray(batch["tokens"], jnp.int32)
        valid = jnp.asarray(batch["valid"], jnp.int32)
        loss_start = jnp.asarray(
            batch.get("loss_start", np.zeros(tokens.shape[0])), jnp.int32
        )
        tok_sh = NamedSharding(self.mesh, logical_to_spec(("batch", "seq"), self.rules))
        val_sh = NamedSharding(self.mesh, logical_to_spec(("batch",), self.rules))
        return (
            jax.device_put(tokens, tok_sh),
            jax.device_put(valid, val_sh),
            jax.device_put(loss_start, val_sh),
        )


def synthetic_batches(
    model_cfg: ModelConfig,
    batch_size: int,
    seq_len: int,
    seed: int = 0,
) -> Iterator[Dict[str, np.ndarray]]:
    """Deterministic synthetic LM batches for benches and tests."""
    rng = np.random.default_rng(seed)
    while True:
        yield {
            "tokens": rng.integers(
                0, model_cfg.vocab_size, size=(batch_size, seq_len), dtype=np.int32
            ),
            "valid": np.full((batch_size,), seq_len, dtype=np.int32),
        }
