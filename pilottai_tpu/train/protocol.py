"""Protocol-model training: teach a small byte-vocab model the rules.yaml
JSON wire protocol so agents COMPLETE tasks on the real engine.

The reference's entire point is the execute → evaluate → retry loop
converging on task success (``pilott/pilott.py:305-331``) — but it proves
this only against remote frontier models. This framework owns the weights,
so it can prove it end-to-end ON-DEVICE: generate supervised pairs from
the exact prompts the runtime renders (same ``PromptManager`` templates,
same ``render_generic_request`` framing, same byte tokenizer, same
left-truncation as the batcher), fine-tune ``protocol-s`` (~4M params) on
them with prompt-masked loss, and serve the checkpoint in the bench's
pipeline/swarm sections.

Training targets are COMPACT JSON in schema property order — exactly the
serialization the schema DFA (``engine/json_schema.py``) admits, so
constrained decoding and the model's own preferences never fight.

The curriculum covers every protocol call the orchestrator + agent loop
makes (SURVEY.md §3.2-3.4):

* agent: task_analysis, tool_selection, step_planning (tools/no-tools ×
  fresh/after-step histories), result_evaluation (honest: a history that
  shows a tool error evaluates success=false);
* orchestrator: task_analysis, task_decomposition, agent_selection
  (copies the first candidate id — a real induction-copy task),
  execution_strategy, result_evaluation.
"""

from __future__ import annotations

import json
import uuid
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from pilottai_tpu.core.task import Task
from pilottai_tpu.engine.base import render_generic_request
from pilottai_tpu.engine.tokenizer import ByteTokenizer
from pilottai_tpu.engine.types import ChatMessage, ToolSpec
from pilottai_tpu.prompts.manager import PromptManager
from pilottai_tpu.utils.logging import get_logger

# Serving defaults the training data mirrors (bench + example pipeline use
# these): KV budget 1024, reply budget 224 (the longest curriculum target,
# the decomposition subtask array, is ~210 bytes) → the batcher keeps the
# last 1024-1-224 = 799 prompt tokens (engine/batcher.py:415-418).
SERVE_MAX_SEQ = 1024
SERVE_MAX_NEW = 224
DEFAULT_CHECKPOINT = (
    Path(__file__).resolve().parent.parent / "assets" / "protocol-s"
)

_log = get_logger("train.protocol")


def _dumps(obj: Any) -> str:
    """Compact JSON — the only serialization the schema DFA admits."""
    return json.dumps(obj, separators=(",", ":"))


# --------------------------------------------------------------------- #
# Synthetic traffic pools (original wording; varied so the model keys on
# the protocol markers, not on any one task text)
# --------------------------------------------------------------------- #

_VERBS = [
    "summarize", "check", "extract", "validate", "analyze", "compile",
    "review", "inspect", "collect", "classify", "draft", "audit",
    "reconcile", "translate", "index", "answer from",
]
_OBJECTS = [
    "document {n}", "inventory {n}", "the quarterly report",
    "customer feedback batch {n}", "the extracted sections",
    "server logs for day {n}", "the meeting notes", "dataset {n}",
    "the incident timeline", "invoice {n}", "the design proposal",
    "section {n} of the handbook",
]
_QUALIFIERS = [
    "", " for the executive team", " before the deadline",
    " and report anomalies", " with citations", " into semantic memory",
    " for completeness", " against the checklist", " in two paragraphs",
]
_ROLES = [
    "worker", "extractor", "evaluator", "generator", "researcher",
    "analyst", "planner", "writer", "manager", "reviewer",
]
_GOALS = [
    "complete assigned tasks accurately",
    "extract document content into memory",
    "validate extraction quality",
    "produce grounded summaries",
    "coordinate the document pipeline",
    "answer questions from stored knowledge",
]
_TOOLS: List[Tuple[str, str]] = [
    ("extract_sections", "Read a document and store its sections in memory"),
    ("validate_extraction",
     "Structurally validate the extracted sections in memory"),
    ("search_notes", "Semantic-search the extracted sections"),
    ("memory_search", "Search the agent's semantic memory"),
    ("knowledge_query", "Query the attached knowledge sources"),
    ("fetch_report", "Fetch a stored report by name"),
    ("parse_log", "Parse a structured log file"),
    ("tabulate", "Aggregate rows into a summary table"),
    ("spell_check", "Check a text for spelling problems"),
    ("send_digest", "Send the daily digest"),
]
_TYPES = [
    "generic", "extract", "evaluate", "summarize", "analyze", "research",
]
# Task-type → agent-role affinity the selection curriculum teaches (the
# document pipeline's stage mapping plus the obvious ones).
_TYPE_ROLE = {
    "extract": "extractor",
    "evaluate": "evaluator",
    "summarize": "generator",
    "analyze": "analyst",
    "research": "researcher",
    "generic": "worker",
}
_TOOL_RESULTS = [
    "{'sections': 4, 'characters': 5120, 'headings': ['Overview', 'Risks']}",
    "{'valid': True, 'sections': 4, 'issues': []}",
    "['Revenue grew 12% quarter over quarter', 'Churn fell to 2.1%']",
    "{'rows': 128, 'anomalies': 0}",
    "ok",
]
_MEMORY_FACTS = [
    "Overview: the program is on track for the Q3 launch",
    "Risks: vendor delivery slipped two weeks in May",
    "the customer reported intermittent failures on node 7",
    "Findings: revenue grew 12% quarter over quarter",
    "the handbook requires dual sign-off for refunds",
]


def _history(r: _Rand, body: str) -> str:
    """Step-planning progress block, optionally led by retrieved-memory
    grounding (core/agent.py prepends this exact framing)."""
    if r.bool(0.3):
        k = int(r.rng.integers(1, 3))
        facts = "\n".join(f"- {r.choice(_MEMORY_FACTS)}" for _ in range(k))
        return f"relevant memory:\n{facts}\n{body}"
    return body


class _Rand:
    """Thin wrapper so every choice draws from one seeded generator."""

    def __init__(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)

    def choice(self, seq):
        return seq[int(self.rng.integers(len(seq)))]

    def uuid(self) -> str:
        return str(uuid.UUID(bytes=self.rng.bytes(16), version=4))

    def bool(self, p: float) -> bool:
        return float(self.rng.random()) < p


def _task(r: _Rand, with_tools: bool) -> Tuple[Task, List[Tuple[str, str]]]:
    n = int(r.rng.integers(1, 999))
    desc = (
        r.choice(_VERBS) + " " + r.choice(_OBJECTS).format(n=n)
        + r.choice(_QUALIFIERS)
    )
    tools = []
    if with_tools:
        k = int(r.rng.integers(1, 4))
        idx = r.rng.permutation(len(_TOOLS))[:k]
        tools = [_TOOLS[i] for i in idx]
    payload = {}
    if r.bool(0.4):
        payload["path"] = f"/data/doc_{n}.md"
    if r.bool(0.3):
        payload["question"] = f"What are the key findings in {n}?"
    task = Task(
        id=r.uuid(),
        description=desc,
        type=r.choice(_TYPES),
        tools=[name for name, _ in tools],
        payload=payload,
        priority=r.choice(["low", "normal", "normal", "high"]),
    )
    return task, tools


def _agent_messages(
    r: _Rand, pm: PromptManager, user_prompt: str
) -> List[ChatMessage]:
    system = pm.format_prompt(
        "system.base",
        role=r.choice(_ROLES),
        goal=r.choice(_GOALS),
        backstory="none",
    )
    return [
        ChatMessage(role="system", content=system),
        ChatMessage(role="user", content=user_prompt),
    ]


def make_example(r: _Rand, pms: Dict[str, PromptManager]) -> Tuple[str, str]:
    """One (rendered_prompt_text, target_json_text) supervised pair,
    drawn from the protocol curriculum."""
    agent_pm, orch_pm = pms["agent"], pms["orchestrator"]
    kind = r.choice(
        # Weighted by how decisive the call is for task success;
        # tooled-fresh heaviest — invoking the offered tool (a name
        # copy) is the hardest decision the loop depends on.
        ["analysis"] * 3 + ["tool_selection"] * 3
        + ["step_tools_fresh"] * 7 + ["step_tools_done"] * 5
        + ["step_plain"] * 4 + ["evaluation"] * 4
        + ["orch_analysis"] * 2 + ["orch_decompose"]
        + ["orch_select"] * 4 + ["orch_strategy"] + ["orch_eval"] * 2
    )

    if kind == "analysis":
        task, _ = _task(r, with_tools=r.bool(0.5))
        prompt = agent_pm.format_prompt("task_analysis", task=task.to_prompt())
        msgs = _agent_messages(r, agent_pm, prompt)
        target = _dumps({
            "understanding": "the task and its goal are clear",
            "approach": "execute the task directly",
            "estimated_steps": 2,
            "risks": [],
        })
        return render_generic_request(msgs), target

    if kind == "tool_selection":
        task, tools = _task(r, with_tools=True)
        prompt = agent_pm.format_prompt(
            "tool_selection",
            task=task.to_prompt(),
            tools="\n".join(f"{n}: {d}" for n, d in tools),
        )
        msgs = _agent_messages(r, agent_pm, prompt)
        specs = [ToolSpec(name=n, description=d) for n, d in tools]
        target = _dumps({
            "selected_tools": [tools[0][0]],
            "reasoning": "best fit for the task",
        })
        return render_generic_request(msgs, specs), target

    if kind in ("step_tools_fresh", "step_tools_done"):
        task, tools = _task(r, with_tools=True)
        if kind == "step_tools_fresh":
            history = "none yet"
            target = _dumps({
                "task_complete": False,
                "action": tools[0][0],
                "arguments": {},
                "reasoning": "run the tool first",
            })
        else:
            history = (
                f"step 0: {tools[0][0]} -> {r.choice(_TOOL_RESULTS)}"
            )
            # No "output" key: the agent keeps the tool result as the
            # stage output (core/agent.py step loop).
            target = _dumps({
                "task_complete": True,
                "action": "respond",
                "arguments": {},
                "reasoning": "the tool produced the result",
            })
        prompt = agent_pm.format_prompt(
            "step_planning", task=task.to_prompt(), history=_history(r, history)
        )
        msgs = _agent_messages(r, agent_pm, prompt)
        specs = [ToolSpec(name=n, description=d) for n, d in tools]
        return render_generic_request(msgs, specs), target

    if kind == "step_plain":
        task, _ = _task(r, with_tools=False)
        history = (
            "none yet" if r.bool(0.7)
            else f"step 0: respond -> {r.choice(_TOOL_RESULTS)}"
        )
        prompt = agent_pm.format_prompt(
            "step_planning", task=task.to_prompt(), history=_history(r, history)
        )
        msgs = _agent_messages(r, agent_pm, prompt)
        target = _dumps({
            "task_complete": True,
            "action": "respond",
            "arguments": {},
            "output": "The task has been completed as requested.",
            "reasoning": "direct answer",
        })
        return render_generic_request(msgs), target

    if kind == "evaluation":
        task, _ = _task(r, with_tools=r.bool(0.5))
        failed = r.bool(0.15)
        result = (
            "tool error: " + r.choice(
                ["timeout after 30s", "missing required arguments ['path']",
                 "permission denied"]
            )
            if failed else r.choice(_TOOL_RESULTS)
        )
        prompt = agent_pm.format_prompt(
            "result_evaluation", task=task.to_prompt(), result=result
        )
        msgs = _agent_messages(r, agent_pm, prompt)
        target = _dumps({
            "success": not failed,
            "quality": 0.2 if failed else 0.9,
            "issues": ["the tool call failed"] if failed else [],
            "suggestions": ["retry with different arguments"] if failed else [],
        })
        return render_generic_request(msgs), target

    # Orchestrator calls go through apredict: a single user turn.
    if kind == "orch_analysis":
        task, _ = _task(r, with_tools=False)
        prompt = orch_pm.format_prompt("task_analysis", task=task.to_prompt())
        target = _dumps({
            "requires_decomposition": False,
            "complexity": 2,
            "estimated_resources": {"agents": 1, "llm_calls": 4},
            "reasoning": "single-stage task",
        })
        return render_generic_request([ChatMessage(content=prompt)]), target

    if kind == "orch_decompose":
        task, _ = _task(r, with_tools=False)
        prompt = orch_pm.format_prompt(
            "task_decomposition", task=task.to_prompt()
        )
        target = _dumps({"subtasks": [
            {"description": "gather the needed material", "type": "extract",
             "priority": "normal", "depends_on": []},
            {"description": "produce the final result", "type": "summarize",
             "priority": "normal", "depends_on": [0]},
        ]})
        return render_generic_request([ChatMessage(content=prompt)]), target

    if kind == "orch_select":
        # Selection is ROLE-AWARE, not first-listed: the candidate whose
        # role matches the task type wins (shuffled positions force the
        # model to find the line, not copy position 0 — a first-id
        # habit routed every pipeline stage to the same agent).
        task, _ = _task(r, with_tools=False)
        n = int(r.rng.integers(2, 5))
        ids = [r.uuid() for _ in range(n)]
        match_role = _TYPE_ROLE.get(task.type)
        roles = []
        others = [x for x in _ROLES if x != match_role]
        for _ in range(n):
            roles.append(r.choice(others))
        pick = int(r.rng.integers(n))
        if match_role is not None and r.bool(0.85):
            roles[pick] = match_role
            chosen = ids[pick]
        else:
            chosen = ids[0]  # no matching role listed → first candidate
        agents = "\n".join(
            f"{aid}: {role}, load={float(r.rng.random()):.2f}, "
            f"success={float(r.rng.random()):.2f}"
            for aid, role in zip(ids, roles)
        )
        prompt = orch_pm.format_prompt(
            "agent_selection", task=task.to_prompt(), agents=agents
        )
        target = _dumps({
            "agent_id": chosen,
            "reasoning": "role matches the task",
        })
        return render_generic_request([ChatMessage(content=prompt)]), target

    if kind == "orch_strategy":
        tasks = "\n".join(
            _task(r, with_tools=False)[0].to_prompt()
            for _ in range(int(r.rng.integers(1, 3)))
        )
        prompt = orch_pm.format_prompt(
            "execution_strategy", tasks=tasks,
            state=f"{{'agents': {int(r.rng.integers(1, 32))}, "
                  f"'queued': {int(r.rng.integers(0, 8))}}}",
        )
        target = _dumps({
            "strategy": "parallel",
            "max_parallel": 4,
            "reasoning": "tasks are independent",
        })
        return render_generic_request([ChatMessage(content=prompt)]), target

    # orch_eval
    task, _ = _task(r, with_tools=False)
    prompt = orch_pm.format_prompt(
        "result_evaluation", task=task.to_prompt(),
        agent_id=r.uuid(), result=r.choice(_TOOL_RESULTS),
    )
    target = _dumps({
        "quality": 0.9,
        "requires_retry": False,
        "feedback": "",
    })
    return render_generic_request([ChatMessage(content=prompt)]), target


# --------------------------------------------------------------------- #
# Batching
# --------------------------------------------------------------------- #

def encode_example(
    prompt_text: str,
    target_text: str,
    tokenizer: ByteTokenizer,
    seq_len: int,
    max_new: int = SERVE_MAX_NEW,
    serve_max_seq: int = SERVE_MAX_SEQ,
) -> Tuple[List[int], int]:
    """(row_ids, loss_start): BOS + prompt + target + EOS, with the prompt
    left-truncated exactly like the serving batcher truncates it
    (``engine/batcher.py:415-418``) and further to fit ``seq_len``."""
    prompt_ids = tokenizer.encode(prompt_text)  # [bos] + bytes
    target_ids = tokenizer.encode(target_text, add_bos=False)
    target_ids = target_ids[: max_new - 1] + [tokenizer.eos_id]
    keep = serve_max_seq - 1 - max_new
    keep = min(max(keep, 1), serve_max_seq - 2, seq_len - len(target_ids))
    if len(prompt_ids) > keep:
        prompt_ids = prompt_ids[-keep:]
    row = prompt_ids + target_ids
    return row, len(prompt_ids)


def protocol_batches(
    batch_size: int,
    seq_len: int,
    seed: int = 0,
    tokenizer: Optional[ByteTokenizer] = None,
) -> Iterator[Dict[str, np.ndarray]]:
    """Infinite stream of prompt-masked protocol batches."""
    tokenizer = tokenizer or ByteTokenizer()
    r = _Rand(seed)
    pms = {"agent": PromptManager("agent"),
           "orchestrator": PromptManager("orchestrator")}
    pad = tokenizer.pad_id
    while True:
        tokens = np.full((batch_size, seq_len), pad, np.int32)
        valid = np.zeros((batch_size,), np.int32)
        loss_start = np.zeros((batch_size,), np.int32)
        for b in range(batch_size):
            prompt_text, target_text = make_example(r, pms)
            row, start = encode_example(
                prompt_text, target_text, tokenizer, seq_len
            )
            tokens[b, : len(row)] = row
            valid[b] = len(row)
            loss_start[b] = start
        yield {"tokens": tokens, "valid": valid, "loss_start": loss_start}


# --------------------------------------------------------------------- #
# Training entry
# --------------------------------------------------------------------- #

def train_protocol(
    model_name: str = "protocol-s",
    steps: int = 3000,
    batch_size: int = 64,
    seq_len: int = SERVE_MAX_SEQ,
    learning_rate: float = 1e-3,
    seed: int = 0,
    out_dir: Optional[str | Path] = None,
    mesh: Optional[Any] = None,
    log_every: int = 100,
) -> Dict[str, Any]:
    """Train the protocol model and save a serving checkpoint (bf16
    params, orbax layout — loadable via ``LLMConfig.checkpoint_path``)."""
    import jax
    import jax.numpy as jnp

    from pilottai_tpu.models.loader import save_params
    from pilottai_tpu.models.registry import get_model_config
    from pilottai_tpu.train.trainer import TrainConfig, Trainer

    cfg = get_model_config(model_name)
    trainer = Trainer(
        cfg,
        TrainConfig(
            learning_rate=learning_rate,
            warmup_steps=min(100, max(steps // 10, 1)),
            total_steps=steps,
        ),
        mesh=mesh,
    )
    state = trainer.init(jax.random.key(seed))
    batches = protocol_batches(batch_size, seq_len, seed=seed)
    losses: List[float] = []
    import time

    t0 = time.perf_counter()
    for step in range(steps):
        state, metrics = trainer.step(state, next(batches))
        if (step + 1) % log_every == 0 or step + 1 == steps:
            loss = float(metrics["loss"])
            losses.append(loss)
            rate = (step + 1) / (time.perf_counter() - t0)
            _log.info(
                "protocol train step %d/%d loss %.4f (%.2f steps/s)",
                step + 1, steps, loss, rate,
            )
    params, _opt = state
    serve_params = jax.tree.map(
        lambda a: a.astype(jnp.bfloat16)
        if jnp.issubdtype(a.dtype, jnp.floating) else a,
        params,
    )
    result = {"final_loss": losses[-1] if losses else None, "steps": steps}
    if out_dir is not None:
        save_params(serve_params, out_dir)
        result["out_dir"] = str(out_dir)
        _log.info("saved protocol checkpoint to %s", out_dir)
    result["params"] = serve_params
    return result


def has_checkpoint(path: Optional[str | Path] = None) -> bool:
    """True when a protocol checkpoint is present at ``path`` (default:
    the committed one). ONE definition — bench, example pipeline and
    tests all gate on this."""
    path = Path(path) if path is not None else DEFAULT_CHECKPOINT
    return path.exists() and any(path.iterdir())


def ensure_protocol_checkpoint(
    path: Optional[str | Path] = None,
    steps: int = 3000,
    **kwargs: Any,
) -> Optional[Path]:
    """The committed checkpoint if present, else train one in place.
    Returns None when training is impossible (no orbax)."""
    path = Path(path) if path is not None else DEFAULT_CHECKPOINT
    if has_checkpoint(path):
        return path
    try:
        import orbax.checkpoint  # noqa: F401 — save_params needs it
    except ImportError:
        _log.warning("orbax unavailable; cannot create protocol checkpoint")
        return None
    _log.info("no protocol checkpoint at %s; training one (steps=%d)",
              path, steps)
    train_protocol(steps=steps, out_dir=path, **kwargs)
    return path


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description="train the protocol model")
    ap.add_argument("--model", default="protocol-s")
    ap.add_argument("--steps", type=int, default=3000)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--seq-len", type=int, default=SERVE_MAX_SEQ)
    ap.add_argument("--learning-rate", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--out", default=str(DEFAULT_CHECKPOINT))
    args = ap.parse_args()
    out = train_protocol(
        model_name=args.model, steps=args.steps, batch_size=args.batch_size,
        seq_len=args.seq_len, learning_rate=args.learning_rate,
        seed=args.seed, out_dir=args.out,
    )
    print(json.dumps({k: v for k, v in out.items() if k != "params"}))
