"""Training: mesh-sharded train step over the shared transformer trunk."""

from pilottai_tpu.train.trainer import (
    TrainConfig,
    Trainer,
    make_optimizer,
    next_token_loss,
    synthetic_batches,
)

__all__ = [
    "TrainConfig",
    "Trainer",
    "make_optimizer",
    "next_token_loss",
    "synthetic_batches",
]
