"""Tool error hierarchy (reference: ``pilott/tools/tool.py:203-217``)."""

from __future__ import annotations


class ToolError(Exception):
    """Base error for tool execution failures."""

    def __init__(self, message: str, tool_name: str = "") -> None:
        super().__init__(message)
        self.tool_name = tool_name


class ToolTimeoutError(ToolError):
    """Tool exceeded its execution timeout."""


class ToolPermissionError(ToolError):
    """Caller lacks a permission the tool requires."""


class ToolValidationError(ToolError):
    """Arguments failed the tool's parameter validation."""
