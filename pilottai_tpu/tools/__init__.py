"""Tool system: user-supplied callables with timeout/retry/concurrency
control and metrics.

Reference parity: ``pilott/tools/`` (``tools/__init__.py:1-8`` exports
Tool + the error hierarchy).
"""

from pilottai_tpu.tools.errors import (
    ToolError,
    ToolPermissionError,
    ToolTimeoutError,
    ToolValidationError,
)
from pilottai_tpu.tools.tool import Tool, ToolMetrics, ToolRegistry

__all__ = [
    "Tool",
    "ToolMetrics",
    "ToolRegistry",
    "ToolError",
    "ToolTimeoutError",
    "ToolPermissionError",
    "ToolValidationError",
]
