"""Tool: a guarded wrapper around a user-supplied callable.

Reference parity: ``pilott/tools/tool.py`` — timeout=30s, retries with
backoff, cooldown, ``max_concurrent`` semaphore, enable/disable, execution
dedupe, per-error-type metrics (``:15-48,65-146,174-201``). Sync callables
run via ``asyncio.to_thread`` so they never block the event loop.
"""

from __future__ import annotations

import asyncio
import inspect
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Set

from pilottai_tpu.tools.errors import (
    ToolError,
    ToolPermissionError,
    ToolTimeoutError,
    ToolValidationError,
)
from pilottai_tpu.obs.dag import global_dag
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics


@dataclass
class ToolMetrics:
    """Rollup of executions (reference ``tool.py:15-23,126-146``)."""

    calls: int = 0
    successes: int = 0
    failures: int = 0
    total_time: float = 0.0
    errors_by_type: Dict[str, int] = field(default_factory=dict)
    last_used: Optional[float] = None

    @property
    def success_rate(self) -> float:
        return self.successes / self.calls if self.calls else 1.0

    @property
    def avg_time(self) -> float:
        return self.total_time / self.calls if self.calls else 0.0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "calls": self.calls,
            "successes": self.successes,
            "failures": self.failures,
            "success_rate": self.success_rate,
            "avg_time": self.avg_time,
            "errors_by_type": dict(self.errors_by_type),
        }


class Tool:
    """An executable capability an agent may invoke during its step loop."""

    def __init__(
        self,
        name: str,
        function: Callable[..., Any],
        description: str = "",
        parameters: Optional[Dict[str, Any]] = None,  # JSON schema
        required_permissions: Optional[Set[str]] = None,
        required_capabilities: Optional[Set[str]] = None,
        timeout: float = 30.0,
        retries: int = 3,
        retry_delay: float = 1.0,
        cooldown: float = 0.0,
        max_concurrent: int = 4,
    ) -> None:
        self.name = name
        self.function = function
        self.description = description
        self.parameters = parameters or {}
        self.required_permissions = set(required_permissions or ())
        self.required_capabilities = set(required_capabilities or ())
        self.timeout = timeout
        self.retries = retries
        self.retry_delay = retry_delay
        self.cooldown = cooldown
        self.enabled = True
        self.metrics = ToolMetrics()
        self._semaphore = asyncio.Semaphore(max_concurrent)
        # None = never ran; 0.0 would wrongly apply the cooldown before the
        # first call when time.monotonic() (uptime) < cooldown.
        self._last_finished: Optional[float] = None
        self._seen_executions: Set[str] = set()
        self._log = get_logger("tools", tool=name)
        # Per-tool lock used by agents for sorted-order acquisition
        # (deadlock-free multi-tool steps, reference ``core/agent.py:181-185``).
        self.lock = asyncio.Lock()

    # ------------------------------------------------------------------ #

    def _check_ready(self, permissions: Set[str]) -> None:
        if not self.enabled:
            raise ToolError(f"tool {self.name!r} is disabled", self.name)
        if (self.cooldown > 0 and self._last_finished is not None
                and time.monotonic() - self._last_finished < self.cooldown):
            raise ToolError(f"tool {self.name!r} is cooling down", self.name)
        missing = self.required_permissions - permissions
        if missing:
            raise ToolPermissionError(
                f"tool {self.name!r} requires permissions {sorted(missing)}",
                self.name,
            )

    def _validate_args(self, arguments: Dict[str, Any]) -> None:
        """Shallow JSON-schema check: required keys + primitive types."""
        schema = self.parameters
        if not schema:
            return
        required = schema.get("required", [])
        missing = [k for k in required if k not in arguments]
        if missing:
            raise ToolValidationError(
                f"tool {self.name!r} missing required arguments {missing}",
                self.name,
            )
        props = schema.get("properties", {})
        type_map = {
            "string": str,
            "number": (int, float),
            "integer": int,
            "boolean": bool,
            "array": list,
            "object": dict,
        }
        for key, value in arguments.items():
            spec = props.get(key)
            if not spec or "type" not in spec:
                continue
            expected = type_map.get(spec["type"])
            if expected and not isinstance(value, expected):
                raise ToolValidationError(
                    f"tool {self.name!r} argument {key!r} should be "
                    f"{spec['type']}, got {type(value).__name__}",
                    self.name,
                )

    async def _call(self, arguments: Dict[str, Any]) -> Any:
        if inspect.iscoroutinefunction(self.function):
            return await self.function(**arguments)
        return await asyncio.to_thread(self.function, **arguments)

    async def execute(
        self,
        arguments: Optional[Dict[str, Any]] = None,
        permissions: Optional[Set[str]] = None,
        execution_id: Optional[str] = None,
    ) -> Any:
        """Run the tool with dedupe, retry, timeout and concurrency cap."""
        arguments = arguments or {}
        execution_id = execution_id or str(uuid.uuid4())
        if execution_id in self._seen_executions:
            raise ToolError(
                f"duplicate execution id {execution_id!r} for tool {self.name!r}",
                self.name,
            )
        self._seen_executions.add(execution_id)
        if len(self._seen_executions) > 10000:
            self._seen_executions = set(list(self._seen_executions)[-5000:])

        self._check_ready(permissions or set())
        self._validate_args(arguments)

        start = time.perf_counter()
        last_error: Optional[Exception] = None
        try:
            async with self._semaphore:
                for attempt in range(self.retries + 1):
                    try:
                        result = await asyncio.wait_for(
                            self._call(arguments), timeout=self.timeout
                        )
                        self._record(True, start)
                        return result
                    except asyncio.TimeoutError:
                        last_error = ToolTimeoutError(
                            f"tool {self.name!r} timed out after {self.timeout}s",
                            self.name,
                        )
                    except (ToolValidationError, ToolPermissionError):
                        raise  # non-retryable
                    except Exception as exc:  # noqa: BLE001 - retry boundary
                        last_error = exc
                    if attempt < self.retries:
                        await asyncio.sleep(self.retry_delay * (attempt + 1))
            self._record(False, start, last_error)
            raise last_error if last_error else ToolError("unknown failure", self.name)
        except (ToolValidationError, ToolPermissionError):
            self._record(False, start, last_error)
            raise
        finally:
            self._last_finished = time.monotonic()

    def _record(self, success: bool, start: float, error: Optional[Exception] = None) -> None:
        elapsed = time.perf_counter() - start
        self.metrics.calls += 1
        self.metrics.total_time += elapsed
        self.metrics.last_used = time.time()
        global_metrics.observe(f"tool.{self.name}.latency", elapsed)
        # Tool node in the ambient task's DAG (no-op outside one): tool
        # time becomes a first-class breakdown component (task.tool_s)
        # and a blame target on the critical path.
        global_dag.record(
            global_dag.current_task(), "tool", self.name,
            start=start, end=time.perf_counter(), ok=success,
        )
        if success:
            self.metrics.successes += 1
        else:
            self.metrics.failures += 1
            if error is not None:
                key = type(error).__name__
                self.metrics.errors_by_type[key] = (
                    self.metrics.errors_by_type.get(key, 0) + 1
                )

    # ------------------------------------------------------------------ #

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    @property
    def success_rate(self) -> float:
        return self.metrics.success_rate

    def to_spec(self) -> Dict[str, Any]:
        """ToolSpec-compatible dict for the engine's function calling."""
        return {
            "name": self.name,
            "description": self.description,
            "parameters": self.parameters,
        }

    def get_metrics(self) -> Dict[str, Any]:
        return {"name": self.name, "enabled": self.enabled, **self.metrics.to_dict()}


class ToolRegistry:
    """Named tool collection shared by agents."""

    def __init__(self, tools: Optional[List[Tool]] = None) -> None:
        self._tools: Dict[str, Tool] = {}
        for tool in tools or []:
            self.register(tool)

    def register(self, tool: Tool) -> None:
        if tool.name in self._tools:
            raise ValueError(f"tool {tool.name!r} already registered")
        self._tools[tool.name] = tool

    def get(self, name: str) -> Tool:
        if name not in self._tools:
            raise KeyError(f"unknown tool {name!r}; available: {sorted(self._tools)}")
        return self._tools[name]

    def __contains__(self, name: str) -> bool:
        return name in self._tools

    def names(self) -> List[str]:
        return sorted(self._tools)

    def subset(self, names: List[str]) -> List[Tool]:
        return [self._tools[n] for n in names if n in self._tools]

    def describe(self) -> str:
        return "\n".join(
            f"{t.name}: {t.description or 'no description'}"
            for t in self._tools.values()
        )
