"""Per-class SLO attainment tracking: the serving objective as a metric.

LLM-Pilot (arxiv 2410.02425) characterizes inference services *under an
SLO* — "p99 TTFT below X" is the unit of capacity, not raw throughput —
and the agent-systems co-design line (PAPERS.md "Towards Efficient
Agents") splits traffic into service classes with different objectives:
a human watching tokens stream (interactive) tolerates far less latency
than a fan-out batch branch nobody reads until the join. This module
makes both first-class:

* ``SLOClass`` — a named class (``interactive``, ``batch`` by default)
  with TTFT/TPOT/e2e targets and an attainment objective (e.g. 0.99 =
  "99% of requests meet every target").
* ``SLOTracker`` — consumes finished request flights (wired as a
  ``FlightRecorder`` finish listener in ``obs/__init__``), classifies
  them by the ``slo_class`` the HTTP edge / orchestrator threaded
  through ``GenerationParams``, and maintains per class:

  ===================================  ================================
  ``slo.<class>.requests``             counter, all finished flights
  ``slo.<class>.missed``               counter, flights that missed ANY
                                       target (failures count: a shed or
                                       deadline-expired request consumed
                                       error budget even with no timing)
  ``slo.<class>.attainment``           gauge, rolling-window fraction met
  ``slo.<class>.burn_rate``            gauge, error-budget burn rate
  ``slo.<class>.ttft_s`` / ``tpot_s``
  / ``e2e_s``                          histograms (ok flights), the
                                       per-class p99 surface
  ===================================  ================================

Burn rate is the standard SRE multiple: observed miss rate over the burn
window divided by the budgeted miss rate (1 − attainment objective).
1.0 = burning budget exactly as provisioned; 2.0 = at this pace the
period's budget lasts half the period; the autoscaler
(``orchestration/scaling.py``) treats sustained burn > 1 as scale-up
pressure.

All series are ``declare``d on the registry, so they surface in
``metrics_snapshot``/Prometheus from boot and the export-completeness
check (``obs.export_completeness``) covers them.

Import cost: stdlib + utils only — no jax (``obs`` package constraint).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Any, Deque, Dict, Iterable, Optional

from collections import deque

from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics

#: Class assigned when a request carried none (bare SDK callers, warmup).
DEFAULT_CLASS = "interactive"


@dataclass(frozen=True)
class SLOClass:
    """One service class: latency targets + attainment objective.

    A target of ``None`` means that dimension is unconstrained for the
    class. ``attainment_target`` is the objective the error budget is
    provisioned against: budgeted miss rate = ``1 - attainment_target``.
    """

    name: str
    ttft_s: Optional[float] = None
    tpot_s: Optional[float] = None
    e2e_s: Optional[float] = None
    attainment_target: float = 0.99

    def met(
        self,
        ttft_s: Optional[float],
        tpot_s: Optional[float],
        e2e_s: Optional[float],
    ) -> bool:
        """True when every *constrained, observed* dimension is within
        target. Unobserved dimensions don't fail a request — a 1-token
        reply has no TPOT; failure statuses are handled by the caller."""
        for target, value in (
            (self.ttft_s, ttft_s),
            (self.tpot_s, tpot_s),
            (self.e2e_s, e2e_s),
        ):
            if target is not None and value is not None and value > target:
                return False
        return True


#: Default classes. Targets are deliberately serving-shaped, not
#: benchmark-shaped: interactive is a human watching tokens stream
#: (sub-2s first token, smooth ~4 tok/s floor); batch is fan-out /
#: pipeline traffic where only completion matters. Deployments override
#: via SLOTracker(classes=...).
DEFAULT_CLASSES = (
    SLOClass(
        name="interactive",
        ttft_s=2.0, tpot_s=0.25, e2e_s=30.0, attainment_target=0.99,
    ),
    SLOClass(
        name="batch",
        ttft_s=30.0, tpot_s=1.0, e2e_s=600.0, attainment_target=0.95,
    ),
)


class SLOTracker:
    """Rolling per-class attainment + burn rate over finished flights.

    Thread-safe: finish listeners fire from whatever thread closes the
    flight (event loop, batcher reader thread).
    """

    def __init__(
        self,
        classes: Optional[Iterable[SLOClass]] = None,
        registry: MetricsRegistry = global_metrics,
        window: int = 1024,
        burn_window_s: float = 300.0,
    ) -> None:
        self.classes: Dict[str, SLOClass] = {
            c.name: c for c in (classes or DEFAULT_CLASSES)
        }
        self._registry = registry
        self._lock = threading.Lock()
        self._window = window
        self._burn_window_s = burn_window_s
        # Per class, two windows with O(1) incremental aggregates (this
        # runs under the lock on EVERY flight finish, from the event
        # loop and the batcher reader thread — full-ledger scans would
        # grow per-request cost linearly with offered load):
        # * ``_attn``/``_attn_met``: count-bounded met booleans (last
        #   ``window`` flights) behind the attainment gauge;
        # * ``_burn``/``_burn_miss``: time-bounded (ts, met) ledger (last
        #   ``burn_window_s`` seconds) behind the burn-rate gauge — a
        #   single maxlen deque serving both silently shrank the burn
        #   window to ~window/rate seconds at high request rates.
        self._attn: Dict[str, Deque] = {
            name: deque(maxlen=window) for name in self.classes
        }
        self._attn_met: Dict[str, int] = {name: 0 for name in self.classes}
        self._burn: Dict[str, Deque] = {
            name: deque() for name in self.classes
        }
        self._burn_miss: Dict[str, int] = {name: 0 for name in self.classes}
        for name in self.classes:
            registry.declare(f"slo.{name}.requests", "counter")
            registry.declare(f"slo.{name}.missed", "counter")
            registry.declare(f"slo.{name}.attainment", "gauge")
            registry.declare(f"slo.{name}.burn_rate", "gauge")
            for dim in ("ttft_s", "tpot_s", "e2e_s"):
                registry.declare(f"slo.{name}.{dim}", "histogram")
            # No traffic = no misses: attainment boots at 1.0, not an
            # alarming declared-default 0.0.
            registry.set_gauge(f"slo.{name}.attainment", 1.0)

    # ------------------------------------------------------------------ #

    def classify(self, slo_class: Optional[str]) -> str:
        """Known class name, or the default for None/unknown — the
        tracker never drops a flight over a typo'd class (it would
        silently exempt that traffic from its SLO)."""
        if slo_class in self.classes:
            return slo_class  # type: ignore[return-value]
        return DEFAULT_CLASS if DEFAULT_CLASS in self.classes else (
            next(iter(self.classes))
        )

    def record(
        self,
        slo_class: Optional[str],
        *,
        ttft_s: Optional[float] = None,
        tpot_s: Optional[float] = None,
        e2e_s: Optional[float] = None,
        ok: bool = True,
        at: Optional[float] = None,
    ) -> bool:
        """Record one finished request; returns whether it met its SLO.
        Failures (``ok=False``: shed, deadline, error) are always misses
        — the client did not get served within objective, whatever the
        clock said."""
        name = self.classify(slo_class)
        cls = self.classes[name]
        met = ok and cls.met(ttft_s, tpot_s, e2e_s)
        now = at if at is not None else time.monotonic()
        with self._lock:
            attn = self._attn[name]
            if len(attn) == self._window and attn[0]:
                self._attn_met[name] -= 1  # about to be evicted by append
            attn.append(met)
            if met:
                self._attn_met[name] += 1
            self._burn[name].append((now, met))
            if not met:
                self._burn_miss[name] += 1
            attainment, burn = self._rates_locked(name, now)
        reg = self._registry
        reg.inc(f"slo.{name}.requests")
        if not met:
            reg.inc(f"slo.{name}.missed")
        if ok:
            for dim, value in (
                ("ttft_s", ttft_s), ("tpot_s", tpot_s), ("e2e_s", e2e_s),
            ):
                if value is not None:
                    reg.observe(f"slo.{name}.{dim}", value)
        reg.set_gauge(f"slo.{name}.attainment", attainment)
        reg.set_gauge(f"slo.{name}.burn_rate", burn)
        return met

    def _rates_locked(self, name: str, now: float) -> tuple:
        """(rolling attainment, burn rate) for ``name`` (lock held).
        Attainment is over the last ``window`` entries; burn over the
        trailing ``burn_window_s`` seconds (pruned here, amortized O(1)
        — timestamps arrive monotonically)."""
        burn_led = self._burn[name]
        cutoff = now - self._burn_window_s
        while burn_led and burn_led[0][0] < cutoff:
            _, m = burn_led.popleft()
            if not m:
                self._burn_miss[name] -= 1
        attn = self._attn[name]
        attainment = self._attn_met[name] / len(attn) if attn else 1.0
        if not burn_led:
            return attainment, 0.0
        miss_rate = self._burn_miss[name] / len(burn_led)
        budget = max(1.0 - self.classes[name].attainment_target, 1e-9)
        return attainment, miss_rate / budget

    def refresh_gauges(self, at: Optional[float] = None) -> None:
        """Recompute the attainment/burn gauges against the clock's NOW.
        ``record`` only writes gauges when a flight finishes, so after
        traffic stops the last written burn rate would otherwise freeze
        at its final (possibly alarming) value forever; time-based
        consumers — the autoscaler reads the gauges, not ``snapshot()``
        — call this before reading so an empty burn window decays to
        burn 0 instead of pinning scale-up pressure on an idle system."""
        now = at if at is not None else time.monotonic()
        with self._lock:
            rates = {
                name: self._rates_locked(name, now) for name in self.classes
            }
        for name, (attainment, burn) in rates.items():
            self._registry.set_gauge(f"slo.{name}.attainment", attainment)
            self._registry.set_gauge(f"slo.{name}.burn_rate", burn)

    # ------------------------------------------------------------------ #
    # FlightRecorder integration
    # ------------------------------------------------------------------ #

    def observe_flight(self, flight: Any) -> None:
        """Finish listener (obs/__init__ wires it onto
        ``global_flight``): classify by the flight's ``slo_class``
        attribute and record its derived phase metrics. Never raises —
        an SLO bookkeeping bug must not fail the request path."""
        try:
            derived = flight.derived()
            self.record(
                flight.attributes.get("slo_class"),
                ttft_s=derived.get("ttft_s"),
                tpot_s=derived.get("tpot_s"),
                e2e_s=derived.get("e2e_s"),
                ok=(flight.status == "ok"),
            )
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pass

    # ------------------------------------------------------------------ #
    # Inspection / exposition
    # ------------------------------------------------------------------ #

    def snapshot(self) -> Dict[str, Any]:
        """The ``/slo.json`` shape: per class targets, counts, rolling
        attainment/burn and the latency percentile surface."""
        hists = self._registry.snapshot()["histograms"]
        now = time.monotonic()
        out: Dict[str, Any] = {}
        with self._lock:
            per_class = {
                name: self._rates_locked(name, now) for name in self.classes
            }
            sizes = {
                name: len(self._attn[name]) for name in self.classes
            }
        for name, cls in self.classes.items():
            attainment, burn = per_class[name]
            entry: Dict[str, Any] = {
                "targets": {
                    "ttft_s": cls.ttft_s,
                    "tpot_s": cls.tpot_s,
                    "e2e_s": cls.e2e_s,
                    "attainment": cls.attainment_target,
                },
                "requests": self._registry.get(f"slo.{name}.requests"),
                "missed": self._registry.get(f"slo.{name}.missed"),
                "window": sizes[name],
                "attainment": round(attainment, 4),
                "burn_rate": round(burn, 4),
            }
            for dim in ("ttft_s", "tpot_s", "e2e_s"):
                summary = hists.get(f"slo.{name}.{dim}") or {}
                entry[f"{dim.replace('_s', '')}_p50_s"] = summary.get("p50")
                entry[f"{dim.replace('_s', '')}_p99_s"] = summary.get("p99")
            out[name] = entry
        return out

    def reset(self) -> None:
        """Drop the rolling windows and the per-class histograms —
        section-scoped measurement (the bench's SLO harness) must not
        inherit the previous section's misses."""
        with self._lock:
            for name in self.classes:
                self._attn[name].clear()
                self._attn_met[name] = 0
                self._burn[name].clear()
                self._burn_miss[name] = 0
        for name in self.classes:
            self._registry.reset_histograms(f"slo.{name}.")
            self._registry.set_gauge(f"slo.{name}.attainment", 1.0)
            self._registry.set_gauge(f"slo.{name}.burn_rate", 0.0)


global_slo = SLOTracker()
