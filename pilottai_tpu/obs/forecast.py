"""Arrival-rate forecasting: EWMA level x seasonal decomposition.

``DynamicScaling`` (orchestration/scaling.py) is purely reactive — every
input it blends (queue fractions, device busy, SLO burn) is a symptom of
load that already arrived, so a diurnal ramp or a scripted burst has to
hurt before capacity moves, and the cooldown then delays the next step.
This module closes ROADMAP item 5's predictive half: the profiler feeds
every request arrival into :class:`ArrivalForecast`, which maintains

* a **seasonal curve** — per-phase EWMA of the arrival rate across
  periods (the diurnal shape, at ``bucket_s`` resolution over
  ``period_s``), and
* a **level multiplier** — EWMA of observed rate over the seasonal
  expectation (how hot the deployment runs *relative to* its usual
  shape right now),

so ``forecast_rps(lead_s)`` = level x seasonal(now + lead) anticipates
the next ramp from history instead of waiting for the queues to fill.
This is classic multiplicative Holt-Winters without the trend term —
arrival traces are shape-dominated, and a trend term turns one burst
into runaway extrapolation.

Everything takes explicit timestamps (``at`` / ``now``) so tests replay
synthetic diurnal traces deterministically; live callers omit them and
get ``time.time()``. Import cost: stdlib only (the obs constraint).
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional

_EPS = 1e-9


class ArrivalForecast:
    """Seasonal arrival-rate forecaster over bucketed request counts.

    ``bucket_s`` is the aggregation step, ``period_s`` the seasonal
    period (a day for real traffic; tests use seconds-long synthetic
    periods — the math is scale-free). ``alpha`` smooths the level
    multiplier, ``gamma`` the per-phase seasonal curve; both are EWMAs,
    so one weird period fades instead of sticking.
    """

    def __init__(
        self,
        bucket_s: float = 60.0,
        period_s: float = 86400.0,
        alpha: float = 0.4,
        gamma: float = 0.3,
        clock=time.time,
    ) -> None:
        if bucket_s <= 0 or period_s < bucket_s:
            raise ValueError("need bucket_s > 0 and period_s >= bucket_s")
        # ``clock`` backs the implicit "now" when callers omit explicit
        # timestamps (DynamicScaling does) — the bench's scripted burst
        # simulation injects synthetic time through it.
        self._clock = clock
        self.bucket_s = float(bucket_s)
        self.period_s = float(period_s)
        self.alpha = float(alpha)
        self.gamma = float(gamma)
        self.n_phases = max(int(round(period_s / bucket_s)), 1)
        self._lock = threading.Lock()
        # Per-phase seasonal rate curve (rps); None until first closed
        # bucket lands in that phase, so an unseen phase falls back to
        # the overall mean instead of a fabricated zero.
        self._season: Dict[int, float] = {}
        self._level: Optional[float] = None  # observed / seasonal EWMA
        self._bucket_idx: Optional[int] = None  # open bucket (abs index)
        self._bucket_count = 0
        self._closed_buckets = 0

    # ------------------------------------------------------------------ #

    def _phase(self, bucket_idx: int) -> int:
        return bucket_idx % self.n_phases

    def _seasonal_rate(self, phase: int) -> float:
        """Seasonal expectation for ``phase`` (rps), mean-filled for
        phases with no history yet."""
        got = self._season.get(phase)
        if got is not None:
            return got
        if self._season:
            return sum(self._season.values()) / len(self._season)
        return 0.0

    def _close_bucket(self, bucket_idx: int, count: int) -> None:
        """Fold one finished bucket into the seasonal curve + level."""
        rate = count / self.bucket_s
        phase = self._phase(bucket_idx)
        expect = self._seasonal_rate(phase)
        prev = self._season.get(phase)
        if prev is None:
            self._season[phase] = rate
        else:
            self._season[phase] = (
                self.gamma * rate + (1.0 - self.gamma) * prev
            )
        # Level: how hot we run vs the seasonal shape. Only meaningful
        # once the curve has an expectation for this phase.
        ratio = rate / expect if expect > _EPS else (
            1.0 if rate <= _EPS else None
        )
        if ratio is not None:
            if self._level is None:
                self._level = ratio
            else:
                self._level = (
                    self.alpha * ratio + (1.0 - self.alpha) * self._level
                )
        self._closed_buckets += 1

    def _roll(self, now: float) -> None:
        """Close every bucket the clock has passed (empty ones count —
        silence IS data for a rate). Gaps longer than one period close
        at most one period of empty buckets: the seasonal curve only has
        ``n_phases`` slots, so older silence adds nothing."""
        idx = int(now // self.bucket_s)
        if self._bucket_idx is None:
            self._bucket_idx = idx
            return
        if idx <= self._bucket_idx:
            return
        gap = idx - self._bucket_idx
        if gap > self.n_phases:
            for empty in range(idx - self.n_phases, idx):
                self._close_bucket(empty, 0)
            self._bucket_idx = idx
            self._bucket_count = 0
            return
        self._close_bucket(self._bucket_idx, self._bucket_count)
        for empty in range(self._bucket_idx + 1, idx):
            self._close_bucket(empty, 0)
        self._bucket_idx = idx
        self._bucket_count = 0

    # ------------------------------------------------------------------ #
    # Feeding
    # ------------------------------------------------------------------ #

    def observe(self, at: Optional[float] = None, n: int = 1) -> None:
        """Record ``n`` arrivals at ``at`` (default: now)."""
        if n <= 0:
            return
        at = self._clock() if at is None else at
        with self._lock:
            self._roll(at)
            self._bucket_count += n

    def ingest_bucket(self, count: int, at: float) -> None:
        """Test/replay convenience: a whole bucket's count at once."""
        self.observe(at=at, n=max(int(count), 0))

    # ------------------------------------------------------------------ #
    # Reading
    # ------------------------------------------------------------------ #

    def ready(self) -> bool:
        """True once a full period of buckets has closed — before that
        the seasonal curve is partial and forecasts fall back to the
        current rate (consumers should treat them as advisory)."""
        with self._lock:
            return self._closed_buckets >= self.n_phases

    def current_rps(self, now: Optional[float] = None) -> float:
        """Smoothed current arrival rate: level x seasonal(now)."""
        now = self._clock() if now is None else now
        with self._lock:
            self._roll(now)
            if self._level is None:
                # No closed history: estimate from the open bucket.
                elapsed = now - (self._bucket_idx or 0) * self.bucket_s
                return self._bucket_count / max(elapsed, self.bucket_s / 4)
            phase = self._phase(int(now // self.bucket_s))
            return max(self._level * self._seasonal_rate(phase), 0.0)

    def forecast_rps(
        self, lead_s: float = 0.0, now: Optional[float] = None
    ) -> float:
        """Predicted arrival rate ``lead_s`` seconds from ``now``."""
        now = self._clock() if now is None else now
        with self._lock:
            self._roll(now)
            if self._level is None:
                elapsed = now - (self._bucket_idx or 0) * self.bucket_s
                return self._bucket_count / max(elapsed, self.bucket_s / 4)
            phase = self._phase(int((now + lead_s) // self.bucket_s))
            return max(self._level * self._seasonal_rate(phase), 0.0)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            n = len(self._season)
            mean = sum(self._season.values()) / n if n else 0.0
            peak = max(self._season.values()) if n else 0.0
            return {
                "level": round(self._level if self._level is not None else 1.0, 4),
                "seasonal_mean_rps": round(mean, 4),
                "seasonal_peak_rps": round(peak, 4),
                "phases_learned": n,
                "n_phases": self.n_phases,
                "bucket_s": self.bucket_s,
                "period_s": self.period_s,
                "ready": n >= self.n_phases,
            }

    def reset(self) -> None:
        with self._lock:
            self._season.clear()
            self._level = None
            self._bucket_idx = None
            self._bucket_count = 0
            self._closed_buckets = 0


def burstiness_cv(inter_arrivals) -> float:
    """Coefficient of variation of inter-arrival gaps: 1 ~ Poisson,
    >1 bursty, <1 metronomic. The profiler fingerprints with this."""
    xs = [x for x in inter_arrivals if x >= 0.0]
    if len(xs) < 2:
        return 0.0
    mean = sum(xs) / len(xs)
    if mean <= _EPS:
        return 0.0
    var = sum((x - mean) ** 2 for x in xs) / (len(xs) - 1)
    return math.sqrt(var) / mean


global_forecast = ArrivalForecast()

__all__ = ["ArrivalForecast", "burstiness_cv", "global_forecast"]
