"""Continuous in-process device-time and MFU attribution.

Until now the repo's MFU was a single division at bench time and its
device-time breakdown an offline Perfetto post-process
(``utils/device_profile.py``) — nothing answered "what is the engine's
MFU *right now*" or "what fraction of device time is prefill vs decode"
on a live deployment. This module is the cheap always-on estimate:

* the batcher reports each dispatch as it folds — phase (``prefill`` /
  ``decode`` / ``sampling`` / ``collective``), host-observed duration,
  tokens landed — plus the idle gaps its host-gap telemetry already
  measures;
* achieved FLOPs are derived as ``tokens x ModelConfig.flops_per_token()``
  (prefill tokens + *accepted* decode tokens from folded validity — the
  same formula bench.py uses, so live and bench MFU reconcile by
  construction);
* rolling-window gauges update on every fold:

  ==================================  =================================
  ``engine.mfu``                      achieved FLOPs / (window x peak
                                      x n_chips)
  ``engine.device_busy_frac``         1 − measured idle gaps / window
  ``engine.collective_frac``          collective share of attributed
                                      device time (0 on a single chip)
  ``engine.collective_frac.<axis>``   per-mesh-axis collective share
  ==================================  =================================

  and cumulative counters (``engine.achieved_flops``,
  ``engine.prefill_tokens``, ``engine.attributed_<phase>_s``,
  ``engine.idle_gap_s``) so section-scoped consumers (bench) take
  deltas.

Accuracy contract: durations are HOST-observed (dispatch-to-fold and
enqueue walls stand in for device occupancy, the same approximation the
host-gap telemetry makes) — pipelined chunks and interleaved prefills
can overlap, so treat per-phase seconds as attribution *shares*, not an
oscilloscope. The FLOPs/token accounting, however, is exact in tokens,
and the whole estimate is reconciled against the profiler-derived truth
(``utils/device_profile.py``) in a slow-marker test
(tests/test_attribution.py) so drift cannot ship silently.

Import cost: stdlib + utils only — no jax (``obs`` package constraint);
``peak_flops_per_chip`` takes a platform string instead of sniffing
devices.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, Optional, Tuple

from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics

PHASES = ("prefill", "decode", "sampling", "collective")

# bf16 peak per chip. TPU v5e: 197 TFLOP/s (the constant bench.py has
# always used); the CPU figure is a nominal placeholder so CPU runs
# produce finite, comparable-within-themselves MFU values.
_PEAK_FLOPS = {"tpu": 197e12, "gpu": 100e12, "cpu": 1e12}


def peak_flops_per_chip(platform: str) -> float:
    """Per-chip peak FLOP/s for ``platform`` ("tpu"/"gpu"/"cpu").
    ``PILOTTAI_PEAK_FLOPS`` overrides for other parts (v5p, v6e...)."""
    env = os.environ.get("PILOTTAI_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return _PEAK_FLOPS.get(platform, _PEAK_FLOPS["cpu"])


class DeviceTimeAttributor:
    """Windowed phase/FLOPs accountant behind the live MFU gauges.

    One global instance is shared by however many engines the process
    runs (the same sharing ``global_metrics`` already has); ``configure``
    is called at each engine boot with that model's FLOPs formula.
    """

    def __init__(
        self,
        registry: MetricsRegistry = global_metrics,
        window_s: float = 60.0,
    ) -> None:
        self._registry = registry
        self._lock = threading.Lock()
        self.window_s = window_s
        self._flops_per_token = 0.0
        self._peak_flops = _PEAK_FLOPS["cpu"]
        self._n_chips = 1
        self._mesh_axes: Tuple[str, ...] = ()
        # (t_end, phase, dur_s, flops, axis) events and (t, gap_s) idle
        # gaps, pruned to the window — with RUNNING window aggregates
        # maintained on append/evict. record()/record_gap() execute on
        # the batcher's device and reader threads per dispatch/fold;
        # full-window scans there would add O(events) host work to the
        # exact hot paths the async-feed pipeline keeps lean.
        self._events: Deque[Tuple[float, str, float, float, Optional[str]]] = (
            deque()
        )
        self._gaps: Deque[Tuple[float, float]] = deque()
        self._w_flops = 0.0
        self._w_dur = 0.0
        self._w_coll = 0.0
        self._w_gap = 0.0
        self._w_axis: Dict[str, float] = {}
        self._t0: Optional[float] = None
        registry.declare("engine.mfu", "gauge")
        registry.declare("engine.device_busy_frac", "gauge")
        registry.declare("engine.collective_frac", "gauge")
        registry.declare("engine.achieved_flops", "counter")
        registry.declare("engine.prefill_tokens", "counter")
        registry.declare("engine.idle_gap_s", "counter")
        for phase in PHASES:
            registry.declare(f"engine.attributed_{phase}_s", "counter")

    # ------------------------------------------------------------------ #

    def configure(
        self,
        *,
        flops_per_token: float,
        platform: str = "cpu",
        peak_flops: Optional[float] = None,
        n_chips: int = 1,
        mesh_axes: Tuple[str, ...] = (),
    ) -> None:
        """Engine boot hook: the model's FLOPs/token formula
        (``ModelConfig.flops_per_token()``), the platform peak and the
        mesh shape. Also declares the per-axis collective gauges so the
        full exposition surface exists before the first collective."""
        with self._lock:
            self._flops_per_token = float(flops_per_token)
            self._peak_flops = (
                peak_flops if peak_flops is not None
                else peak_flops_per_chip(platform)
            )
            self._n_chips = max(int(n_chips), 1)
            self._mesh_axes = tuple(mesh_axes)
        for axis in mesh_axes:
            self._registry.declare(f"engine.collective_frac.{axis}", "gauge")
            # Cumulative per-axis collective seconds next to the rolling
            # gauge: section-scoped consumers (bench MULTICHIP) take
            # exact deltas instead of sampling a 60 s window.
            self._registry.declare(
                f"engine.attributed_collective_s.{axis}", "counter"
            )

    # ------------------------------------------------------------------ #

    def record(
        self,
        phase: str,
        duration_s: float,
        *,
        tokens: int = 0,
        flops: Optional[float] = None,
        axis: Optional[str] = None,
        at: Optional[float] = None,
        collective: Optional[Dict[str, float]] = None,
    ) -> None:
        """One dispatch's attribution. ``flops`` defaults to
        ``tokens x flops_per_token``; pass it explicitly for work the
        token formula doesn't describe (collectives: 0). ``axis`` tags
        collective time to a mesh axis for the per-axis gauges.
        ``collective`` is this dispatch's per-axis collective-seconds
        split (the batcher's CollectiveModel carve-out): the axis events
        land in the window under the SAME lock/gauge pass as the phase
        record, so a sharded fold stays one attributor call instead of
        one per axis on the reader-thread hot path."""
        if phase not in PHASES:
            raise ValueError(f"unknown phase {phase!r}; expected {PHASES}")
        now = at if at is not None else time.perf_counter()
        duration_s = max(float(duration_s), 0.0)
        if flops is None:
            flops = tokens * self._flops_per_token
        coll = {
            ax: float(s) for ax, s in (collective or {}).items() if s > 0.0
        }
        with self._lock:
            if self._t0 is None:
                self._t0 = now - duration_s - sum(coll.values())
            self._events.append((now, phase, duration_s, flops, axis))
            self._w_flops += flops
            self._w_dur += duration_s
            if phase == "collective":
                self._w_coll += duration_s
                if axis is not None:
                    self._w_axis[axis] = (
                        self._w_axis.get(axis, 0.0) + duration_s
                    )
            for ax, coll_s in coll.items():
                self._events.append((now, "collective", coll_s, 0.0, ax))
                self._w_dur += coll_s
                self._w_coll += coll_s
                self._w_axis[ax] = self._w_axis.get(ax, 0.0) + coll_s
            self._prune_locked(now)
            gauges = self._gauges_locked(now)
        reg = self._registry
        reg.inc(f"engine.attributed_{phase}_s", duration_s)
        if phase == "collective" and axis is not None:
            reg.inc(f"engine.attributed_collective_s.{axis}", duration_s)
        for ax, coll_s in coll.items():
            reg.inc("engine.attributed_collective_s", coll_s)
            reg.inc(f"engine.attributed_collective_s.{ax}", coll_s)
        if flops:
            reg.inc("engine.achieved_flops", flops)
        if phase == "prefill" and tokens:
            reg.inc("engine.prefill_tokens", tokens)
        for name, value in gauges.items():
            reg.set_gauge(name, value)

    def record_gap(self, gap_s: float, at: Optional[float] = None) -> None:
        """One measured device-idle bubble (the batcher's host-gap
        telemetry: time the device sat with nothing in flight before a
        dispatch). The busy gauge is the complement of these over the
        window — idle is *measured*, busy inferred, so an engine that
        stops dispatching shows its last-known busy_frac rather than a
        fabricated one."""
        if gap_s <= 0.0:
            return
        now = at if at is not None else time.perf_counter()
        with self._lock:
            if self._t0 is None:
                self._t0 = now - gap_s
            self._gaps.append((now, gap_s))
            self._w_gap += gap_s
            self._prune_locked(now)
            gauges = self._gauges_locked(now)
        self._registry.inc("engine.idle_gap_s", gap_s)
        for name, value in gauges.items():
            self._registry.set_gauge(name, value)

    # ------------------------------------------------------------------ #

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        while self._events and self._events[0][0] < cutoff:
            _, phase, dur, flops, axis = self._events.popleft()
            self._w_flops -= flops
            self._w_dur -= dur
            if phase == "collective":
                self._w_coll -= dur
                if axis is not None:
                    self._w_axis[axis] = self._w_axis.get(axis, 0.0) - dur
        while self._gaps and self._gaps[0][0] < cutoff:
            self._w_gap -= self._gaps.popleft()[1]
        if not self._events and not self._gaps:
            # Empty window: reset the running sums so float residue from
            # long add/subtract chains can't accumulate into the gauges.
            self._w_flops = self._w_dur = self._w_coll = self._w_gap = 0.0
            self._w_axis.clear()

    def _elapsed_locked(self, now: float) -> float:
        if self._t0 is None:
            return 0.0
        return max(min(now - self._t0, self.window_s), 1e-9)

    def _gauges_locked(self, now: float) -> Dict[str, float]:
        """O(1): reads the running window aggregates, no event scans."""
        elapsed = self._elapsed_locked(now)
        if elapsed <= 0.0:
            return {}
        busy = max(min(1.0 - self._w_gap / elapsed, 1.0), 0.0)
        denom = elapsed * self._peak_flops * self._n_chips
        out = {
            "engine.mfu": max(self._w_flops, 0.0) / denom
            if denom > 0 else 0.0,
            "engine.device_busy_frac": busy,
        }
        total_dur = self._w_dur
        out["engine.collective_frac"] = (
            max(self._w_coll, 0.0) / total_dur if total_dur > 0 else 0.0
        )
        for ax in self._mesh_axes:
            ax_dur = max(self._w_axis.get(ax, 0.0), 0.0)
            out[f"engine.collective_frac.{ax}"] = (
                ax_dur / total_dur if total_dur > 0 else 0.0
            )
        return out

    # ------------------------------------------------------------------ #

    def snapshot(self) -> Dict[str, Any]:
        """Live window view: per-phase seconds/share, window FLOPs, the
        gauge values, configuration. The bench's per-section numbers use
        the cumulative counters instead (delta across the section)."""
        now = time.perf_counter()
        with self._lock:
            self._prune_locked(now)
            elapsed = self._elapsed_locked(now)
            events = list(self._events)
            idle_w = sum(g for _, g in self._gaps)
            gauges = self._gauges_locked(now)
            cfg = {
                "flops_per_token": self._flops_per_token,
                "peak_flops_per_chip": self._peak_flops,
                "n_chips": self._n_chips,
                "mesh_axes": list(self._mesh_axes),
                "window_s": self.window_s,
            }
        total_dur = sum(e[2] for e in events)
        phases: Dict[str, Any] = {}
        for phase in PHASES:
            dur = sum(e[2] for e in events if e[1] == phase)
            phases[phase] = {
                "seconds": round(dur, 6),
                "share": round(dur / total_dur, 4) if total_dur > 0 else 0.0,
            }
        return {
            "window_elapsed_s": round(elapsed, 3),
            "attributed_s": round(total_dur, 6),
            "idle_gap_s": round(idle_w, 6),
            "achieved_flops": sum(e[3] for e in events),
            "phases": phases,
            "mfu": round(gauges.get("engine.mfu", 0.0), 6),
            "device_busy_frac": round(
                gauges.get("engine.device_busy_frac", 0.0), 4
            ),
            "collective_frac": round(
                gauges.get("engine.collective_frac", 0.0), 4
            ),
            **cfg,
        }

    def reset_window(self) -> None:
        """Drop the rolling window (gauges keep their last values until
        the next record). Cumulative counters are untouched — bench
        sections measure by delta, not by reset."""
        with self._lock:
            self._events.clear()
            self._gaps.clear()
            self._w_flops = self._w_dur = self._w_coll = self._w_gap = 0.0
            self._w_axis.clear()
            self._t0 = None


global_attribution = DeviceTimeAttributor()
