"""Observability layer: one request-to-kernel story.

Correlates the three previously disconnected pieces — ``utils/metrics``
(numbers), ``utils/tracing`` (span trees), ``utils/dashboard``/``server``
(endpoints) — into a unified telemetry surface:

* ``flight``   — per-request flight recorder (queue wait, TTFT, ITL,
  TPOT phase ledger keyed by trace id, exported as histograms).
* ``ring``     — bounded engine step telemetry ring (slot occupancy,
  tokens/step, KV page utilization, strip width, pipeline depth).
* ``blackbox`` — dump coordinator: last N steps + the affected request's
  span tree, journaled on deadline expiry / breaker open / errors.
* ``export``   — Prometheus text exposition, Chrome/Perfetto
  ``trace_event`` JSON, the shared ``metrics_snapshot`` and the bench's
  ``phase_summary``.

Import cost: stdlib + utils + checkpoint.journal only — no jax, safe for
control-plane processes (the same constraint as ``reliability``).
"""

from pilottai_tpu.obs.blackbox import BlackBox, global_blackbox
from pilottai_tpu.obs.export import (
    metrics_snapshot,
    perfetto_trace,
    phase_summary,
    prometheus_text,
)
from pilottai_tpu.obs.flight import FlightRecorder, RequestFlight, global_flight
from pilottai_tpu.obs.ring import StepRing, global_steps

__all__ = [
    "BlackBox",
    "FlightRecorder",
    "RequestFlight",
    "StepRing",
    "global_blackbox",
    "global_flight",
    "global_steps",
    "metrics_snapshot",
    "perfetto_trace",
    "phase_summary",
    "prometheus_text",
]
