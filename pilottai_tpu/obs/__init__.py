"""Observability layer: one request-to-kernel story.

Correlates the three previously disconnected pieces — ``utils/metrics``
(numbers), ``utils/tracing`` (span trees), ``utils/dashboard``/``server``
(endpoints) — into a unified telemetry surface:

* ``flight``   — per-request flight recorder (queue wait, TTFT, ITL,
  TPOT phase ledger keyed by trace id, exported as histograms).
* ``dag``      — per-task DAG ledger: orchestration stages, queue
  residency, agent/tool/memory nodes and joined engine flights, with
  critical-path attribution (``task.*`` histograms), per-agent
  occupancy gauges (``agent.<role>.busy_frac``/``queue_depth``) and the
  ``/dag.json`` snapshot; fed by serve/agents and by every finished
  flight via the finish-listener hook below.
* ``ring``     — bounded engine step telemetry ring (slot occupancy,
  tokens/step, KV page utilization, strip width, pipeline depth).
* ``slo``      — per-class (interactive/batch) SLO attainment, error-
  budget burn rate, ``/slo.json`` snapshot; fed by every finished
  flight via the finish-listener hook below.
* ``attribution`` — continuous device-time/phase attribution and the
  live ``engine.mfu`` / ``engine.device_busy_frac`` /
  ``engine.collective_frac`` gauges; fed per dispatch by the batcher.
* ``blackbox`` — dump coordinator: last N steps + the affected request's
  span tree, journaled on deadline expiry / breaker open / errors.
* ``profile`` — rolling per-deployment workload fingerprint (lengths,
  arrival stats, class/session/DAG mix, spec acceptance, kv hit rate);
  ``/profile.json`` + the profile store next to ``autotune.json``.
* ``forecast`` — seasonal arrival-rate forecasting (EWMA level x
  diurnal curve) feeding predictive autoscaling.
* ``costmodel`` — knob-vector → predicted-metrics interpolation over
  recorded ``bench_slo`` sample points; ``scripts/recommend.py``.
* ``export``   — Prometheus text exposition, Chrome/Perfetto
  ``trace_event`` JSON, the shared ``metrics_snapshot``, the bench's
  ``phase_summary`` and the ``export_completeness`` wiring check.

Import cost: stdlib + utils + checkpoint.journal only — no jax, safe for
control-plane processes (the same constraint as ``reliability``).
"""

from pilottai_tpu.obs.attribution import (
    DeviceTimeAttributor,
    global_attribution,
    peak_flops_per_chip,
)
from pilottai_tpu.obs.blackbox import BlackBox, global_blackbox
from pilottai_tpu.obs.dag import (
    AgentOccupancy,
    DagLedger,
    TaskDag,
    global_dag,
    global_occupancy,
)
from pilottai_tpu.obs.export import (
    export_completeness,
    metrics_snapshot,
    perfetto_trace,
    phase_summary,
    prometheus_text,
)
from pilottai_tpu.obs.flight import FlightRecorder, RequestFlight, global_flight
from pilottai_tpu.obs.forecast import (
    ArrivalForecast,
    burstiness_cv,
    global_forecast,
)
from pilottai_tpu.obs.costmodel import CostModel, validate_knobs
from pilottai_tpu.obs.profile import WorkloadProfiler, global_profile
from pilottai_tpu.obs.ring import StepRing, global_steps
from pilottai_tpu.obs.slo import (
    DEFAULT_CLASS,
    SLOClass,
    SLOTracker,
    global_slo,
)

# Every finished flight feeds the SLO tracker — the wiring that makes
# "SLO attainment" a property of ALL traffic (HTTP, orchestrator, bare
# SDK callers) rather than something each caller opts into.
global_flight.add_finish_listener(global_slo.observe_flight)
# ... and the task-DAG ledger: engine flights join the issuing task's
# DAG (ambient dag context stamped at flight start; trace-id fallback),
# so a task's breakdown can split LLM time into prefill/decode.
global_flight.add_finish_listener(global_dag.observe_flight)
# ... and the workload profiler (ISSUE 18): finished flights carry the
# length/class/session/DAG shape, flight STARTS are the arrival events
# the inter-arrival stats and the seasonal forecaster key on.
global_flight.add_finish_listener(global_profile.observe_flight)
global_flight.add_start_listener(global_profile.observe_start)

# Engine admission-queue depth: maintained by the batcher (admit / fold /
# shed paths) but declared HERE so the exported surface — and the
# autoscaler signal built on it (orchestration/scaling.py) — exists from
# process boot, before (or without) an engine. 0 = empty queue.
from pilottai_tpu.utils.metrics import global_metrics as _gm

_gm.declare("engine.queue_depth", "gauge")
# Decode weight stream (ISSUE 14): resident weight bytes and the bytes
# streamed from HBM per decode token, set at engine start from the
# quantized parameter tree (models/quant.py:weight_stream_bytes) — the
# QUANT bench section reads these so "int4 halves the stream" is a
# measured series. Global logical bytes; divide by the TP shard count
# for per-chip.
_gm.declare("engine.weight_bytes", "gauge")
_gm.declare("engine.weight_bytes_per_token", "gauge")
# Engine fault domain (reliability/{watchdog,degrade}.py + batcher):
# declared at boot so dashboards and the health surface can alert on
# zero-valued gauges before the first fault ever happens.
_gm.declare("engine.stalled", "gauge")          # watchdog verdict (0/1)
_gm.declare("engine.degrade_level", "gauge")    # capability ladder rung
_gm.declare("engine.rebuilds", "counter")       # failure-path rebuilds
_gm.declare("engine.watchdog_stalls", "counter")
_gm.declare("engine.watchdog_recoveries", "counter")
_gm.declare("engine.poisoned", "counter")       # fold-boundary containment
_gm.declare("engine.recovery_requeued", "counter")
_gm.declare("engine.recovered_requests", "counter")
_gm.declare("engine.recovery_failed", "counter")
_gm.declare("engine.tokens_replayed", "counter")
_gm.declare("engine.recovery_ms", "histogram")  # snapshot → re-admission
# Global KV cache tier (engine/kvcache/ + batcher prefix lookup):
# declared at boot so hit-rate dashboards and the bench's KVCACHE
# section read a complete surface even before the first lookup.
_gm.declare("engine.kvcache.lookups", "counter")
_gm.declare("engine.kvcache.hits", "counter")        # hot + host
_gm.declare("engine.kvcache.host_hits", "counter")   # restored from host
_gm.declare("engine.kvcache.spills", "counter")      # evictions caught
_gm.declare("engine.kvcache.spill_bytes", "counter")
_gm.declare("engine.kvcache.restores", "counter")
_gm.declare("engine.kvcache.restored_tokens", "counter")
_gm.declare("engine.kvcache.evictions", "counter")   # host-tier drops
_gm.declare("engine.kvcache.prefill_tokens_saved", "counter")
_gm.declare("engine.kvcache.restore_ms", "histogram")  # host-side staging
_gm.declare("engine.kvcache.host_bytes", "gauge")
_gm.declare("engine.kvcache.host_entries", "gauge")
_gm.declare("engine.kvcache.sessions", "gauge")      # live session pins
# Degraded-mesh fault domain (parallel/meshplan.py + batcher, ISSUE 16):
# the shard-loss / re-plan / KV-integrity surface, declared at boot so a
# dashboard can alert on the zero-valued gauges before the first loss.
_gm.declare("engine.mesh_plan", "gauge")             # active ladder rung
_gm.declare("engine.shard_losses", "counter")        # devices marked lost
_gm.declare("engine.mesh_rebuild_ms", "histogram")   # re-plan → serving
_gm.declare("engine.kvcache.integrity_failures", "counter")
# Serving cell (distributed/cell.py + router.py, ISSUE 11): the cell
# front door's routed/shed/affinity/migration surface. Per-class
# routed/shed counters are declared for the DEFAULT classes here;
# ServingCell declares any deployment-defined classes at construction.
_gm.declare("cell.replicas", "gauge")
_gm.declare("cell.replicas_routable", "gauge")
_gm.declare("cell.sessions", "gauge")                # sticky session pins
_gm.declare("cell.routed.interactive", "counter")
_gm.declare("cell.routed.batch", "counter")
_gm.declare("cell.shed.interactive", "counter")      # cell-boundary sheds
_gm.declare("cell.shed.batch", "counter")
_gm.declare("cell.affinity_lookups", "counter")
_gm.declare("cell.affinity_hits", "counter")         # pinned or prefix hit
_gm.declare("cell.affinity_hit_rate", "gauge")
_gm.declare("cell.rerouted", "counter")              # fault/drain re-admits
_gm.declare("cell.migrations", "counter")
_gm.declare("cell.migrated_entries", "counter")
_gm.declare("cell.migrated_tokens", "counter")
_gm.declare("cell.migrate_rejected", "counter")      # integrity rejections
_gm.declare("cell.degraded_replicas", "gauge")       # serving on sub-mesh
_gm.declare("cell.migration_ms", "histogram")        # export→import wall
_gm.declare("cell.drains", "counter")
_gm.declare("cell.drain_s", "histogram")             # full drain wall
# Disaggregated prefill/decode serving (ISSUE 19): tier topology +
# the prefill→decode KV handoff hot path. All read 0 / stay unset in a
# colocated cell — declared here so the export surface is complete
# (and export_completeness-clean) whether or not ``cell_disagg`` is on.
_gm.declare("cell.tier.prefill_replicas", "gauge")
_gm.declare("cell.tier.decode_replicas", "gauge")
_gm.declare("cell.tier.mixed_replicas", "gauge")
_gm.declare("cell.tier.prefill_routed", "counter")   # handoff admissions
_gm.declare("cell.tier.decode_routed", "counter")    # decode-direct + legs
_gm.declare("cell.tier.bypass", "counter")           # prefix-hot bypasses
_gm.declare("cell.handoffs", "counter")              # attempts committed
_gm.declare("cell.handoff_fallbacks", "counter")     # fell back colocated
_gm.declare("cell.handoff_rejected", "counter")      # integrity rejections
_gm.declare("cell.handoff_tokens", "counter")        # KV tokens moved
_gm.declare("cell.handoff_ms", "histogram")          # prefill-done → landed
# DAG-aware scheduler (pilottai_tpu/sched/ + the batcher's priority
# backlog, ROADMAP item 4): declared at boot so the scheduling surface
# is export_completeness-clean before the first boosted admission.
# engine.backlog_wait_ms is per-priority-rung: submit → admission-pop
# wall, the histogram that makes priority inversion VISIBLE (a critical
# request waiting behind batch work shows up here, not in a debugger).
_gm.declare("engine.backlog_wait_ms.low", "histogram")
_gm.declare("engine.backlog_wait_ms.normal", "histogram")
_gm.declare("engine.backlog_wait_ms.high", "histogram")
_gm.declare("engine.backlog_wait_ms.critical", "histogram")
_gm.declare("sched.priority_boosts", "counter")   # critical-path boosts
_gm.declare("sched.priority_aged", "counter")     # aging-floor promotions
_gm.declare("sched.gang_admits", "counter")       # whole-gang admissions
_gm.declare("sched.gang_partial", "counter")      # wait-bound fallbacks
_gm.declare("sched.prewarms", "counter")          # pre-warm requests
_gm.declare("sched.prewarm_hits", "counter")      # found KV (hot or host)
_gm.declare("sched.prewarm_skipped", "counter")   # no tier / below floor
# Profile-guided configuration (ISSUE 18): the speculation-acceptance
# EMA the batcher maintains internally becomes an exported gauge (the
# workload fingerprint reads it back), declared here so the surface is
# complete before — or without — a speculating engine. The profile.*
# gauges themselves are declared by WorkloadProfiler at construction
# (import time for the global instance, same pattern as SLOTracker);
# scaling.forecast_* by DynamicScaling, which owns the scaling surface.
_gm.declare("engine.spec_acceptance", "gauge")

__all__ = [
    "AgentOccupancy",
    "ArrivalForecast",
    "BlackBox",
    "CostModel",
    "DEFAULT_CLASS",
    "DagLedger",
    "DeviceTimeAttributor",
    "FlightRecorder",
    "RequestFlight",
    "SLOClass",
    "SLOTracker",
    "StepRing",
    "TaskDag",
    "WorkloadProfiler",
    "burstiness_cv",
    "export_completeness",
    "global_attribution",
    "global_blackbox",
    "global_dag",
    "global_flight",
    "global_forecast",
    "global_occupancy",
    "global_profile",
    "global_slo",
    "global_steps",
    "metrics_snapshot",
    "peak_flops_per_chip",
    "perfetto_trace",
    "phase_summary",
    "prometheus_text",
    "validate_knobs",
]
