"""Per-task DAG ledger: critical-path attribution for the orchestrator.

``FlightRecorder`` (obs/flight.py) explains a *request*; this module
explains a *task*. Between PR 3's request flights and PR 6's device-time
attribution sits the orchestration layer — decomposition, queue wait,
routing, agent reasoning steps, tool calls, memory lookups, retries,
fan-out stragglers — and when a ``Serve`` task takes 40 s none of the
existing surfaces can say where those seconds went. "Towards Efficient
Agents" (PAPERS.md) argues scheduling co-design starts from exactly this
per-stage attribution, and ROADMAP item 4 (DAG-aware scheduling) needs
it as its input signal.

Every ``Serve`` task gets a :class:`TaskDag`: nodes are lifecycle stages
(``analyze``/``decompose``/``route``/``execute``/``evaluate``/``retry``),
queue residencies, agent executions, tool invocations, memory ops,
engine flights (joined from the flight recorder via the shared
``trace_id`` + the ambient dag context), and — for decomposed parents —
subtask rollups carrying their children's own breakdowns. Edges come
from the ambient-context nesting plus the explicit dependency structure
``Serve._deps_state`` already schedules on.

On task finish the ledger computes the **critical path** (backward
blame walk: from the latest-finishing node, repeatedly hop to the
latest-finishing predecessor — dependency edges first, overlap
containment second — recursing into children; uncovered time becomes
synthetic ``overhead`` spans) and a time breakdown over the critical
spans:

=================================  =====================================
``task.e2e_s``                     dag open → finish
``task.critical_path_s``           sum of critical-path span durations
``task.orchestrator_overhead_s``   critical time in no recorded child
                                   (scheduling, LLM-free orchestration)
``task.queue_wait_s``              queue nodes + flight queue waits
``task.llm_prefill_s``             flight time up to first token
``task.llm_decode_s``              flight time after first token
``task.tool_s`` / ``task.memory_s``  tool / memory critical time
``task.straggler_s``               slowest − median sibling fan-out
                                   branch duration (0 without fan-out)
=================================  =====================================

plus per-priority queue-wait histograms
(``task.queue_wait.<priority>_s``) fed directly by
``PriorityTaskQueue`` put/get, and the counters ``task.completed`` /
``task.failed`` / ``task.retries`` and gauge ``task.active``.

:class:`AgentOccupancy` is the per-agent utilization companion: agents
report busy intervals and queue depth from their step events and the
tracker maintains ``agent.<role>.busy_frac`` (rolling 60 s window,
normalized by the number of registered agents of the role) and
``agent.<role>.queue_depth`` gauges.

All series follow PR 6's ``declare()`` / ``export_completeness()``
discipline: declared at construction (or at role registration), so they
surface zero-valued from boot and the completeness walk gates them.

Import cost: stdlib + utils only — no jax (``obs`` package constraint).
"""

from __future__ import annotations

import contextlib
import contextvars
import statistics
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, Iterator, List, Optional, Tuple

from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics
from pilottai_tpu.utils.tracing import global_tracer

#: Priority names with dedicated queue-wait histograms (core.task
#: TaskPriority members, lower-cased; fixed so the series are declarable).
QUEUE_PRIORITIES = ("low", "normal", "high", "critical")

#: Breakdown component → histogram suffix (the ``task.*`` surface).
BREAKDOWN_COMPONENTS = (
    "orchestrator_overhead_s",
    "queue_wait_s",
    "llm_prefill_s",
    "llm_decode_s",
    "tool_s",
    "memory_s",
    "straggler_s",
)


@dataclass
class DagNode:
    """One unit of work inside a task's DAG. Timestamps are
    ``time.perf_counter()`` — the tracer's clock, so dag spans line up
    with the request span trees and the engine step ring in Perfetto."""

    node_id: int
    kind: str            # stage|queue|agent|tool|memory|flight|subtask|retry|overhead
    name: str
    start: float
    end: Optional[float] = None
    parent_id: Optional[int] = None
    deps: List[int] = field(default_factory=list)
    attributes: Dict[str, Any] = field(default_factory=dict)
    critical: bool = False

    @property
    def duration(self) -> float:
        return max((self.end or self.start) - self.start, 0.0)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "node_id": self.node_id,
            "kind": self.kind,
            "name": self.name,
            "start": self.start,
            "end": self.end,
            "duration_s": round(self.duration, 6),
            "parent_id": self.parent_id,
            "deps": list(self.deps),
            "critical": self.critical,
            "attributes": dict(self.attributes),
        }


class TaskDag:
    """One task's DAG record. NOT thread-safe on its own — all mutation
    goes through :class:`DagLedger`'s lock."""

    #: Per-task node cap — the same bounded-ring discipline as the
    #: flight recorder and step ring: a pathological task (runaway
    #: retry/iteration loop) must not grow its ledger without bound in
    #: a long-lived serving process. Overflow is counted, not silent.
    MAX_NODES = 512

    def __init__(
        self,
        task_id: str,
        trace_id: str,
        parent_task_id: Optional[str] = None,
        **attributes: Any,
    ) -> None:
        self.task_id = task_id
        self.trace_id = trace_id
        self.parent_task_id = parent_task_id
        self.attributes = dict(attributes)
        self.dropped_nodes = 0
        self.created = time.perf_counter()
        self.created_wall = time.time()
        self.ended: Optional[float] = None
        self.status: Optional[str] = None
        self.nodes: Dict[int, DagNode] = {}
        # Lifecycle marks in WALL time (time.time()) — the task event
        # bus stamps events with time.time(), and the event-vs-ledger
        # ordering test joins on this clock. First stamp wins.
        self.marks: Dict[str, float] = {}
        # task_id → node_id for finished subtasks rolled up into this
        # dag (dependency edges between siblings resolve through it).
        self.subtask_nodes: Dict[str, int] = {}
        self._seq = 0
        # Filled by finish():
        self.critical_spans: List[Dict[str, Any]] = []
        self.breakdown: Dict[str, float] = {}

    # ------------------------------------------------------------------ #
    # Construction
    # ------------------------------------------------------------------ #

    def add_node(
        self,
        kind: str,
        name: str,
        start: float,
        end: Optional[float] = None,
        parent_id: Optional[int] = None,
        deps: Optional[List[int]] = None,
        **attributes: Any,
    ) -> DagNode:
        self._seq += 1
        node = DagNode(
            node_id=self._seq, kind=kind, name=name, start=start, end=end,
            parent_id=parent_id, deps=list(deps or ()),
            attributes=attributes,
        )
        if len(self.nodes) >= self.MAX_NODES:
            # Return the (unstored) node so call sites keep working;
            # the overflow shows up in to_dict rather than vanishing.
            self.dropped_nodes += 1
            return node
        self.nodes[node.node_id] = node
        return node

    def mark(self, event: str, at: Optional[float] = None) -> None:
        self.marks.setdefault(event, at if at is not None else time.time())

    # ------------------------------------------------------------------ #
    # Critical path (called once, at finish, nodes frozen)
    # ------------------------------------------------------------------ #

    def _children_of(self) -> Dict[Optional[int], List[DagNode]]:
        children: Dict[Optional[int], List[DagNode]] = {}
        for node in self.nodes.values():
            children.setdefault(node.parent_id, []).append(node)
        return children

    def _chain(self, siblings: List[DagNode], end_cursor: float) -> List[DagNode]:
        """Backward blame walk over one sibling level: starting from the
        cursor, repeatedly pick the predecessor whose end is latest —
        explicit dependency edges of the current node first (the true
        blocking predecessor even when a scheduling gap separates them),
        any sibling starting before the cursor otherwise."""
        chain: List[DagNode] = []
        current: Optional[DagNode] = None
        cursor = end_cursor
        remaining = list(siblings)
        while True:
            pool = remaining
            if current is not None and current.deps:
                dep_pool = [n for n in remaining if n.node_id in current.deps]
                if dep_pool:
                    pool = dep_pool
            candidates = [n for n in pool if n.start < cursor - 1e-9]
            if not candidates:
                break
            best = max(candidates, key=lambda n: min(n.end or cursor, cursor))
            chain.append(best)
            remaining.remove(best)
            cursor = best.start
            current = best
        chain.reverse()
        return chain

    def _critical_spans(
        self,
        node: Optional[DagNode],
        lo: float,
        hi: float,
        children: Dict[Optional[int], List[DagNode]],
    ) -> List[Dict[str, Any]]:
        """Critical spans covering [lo, hi] attributed to ``node``'s
        children where recorded; uncovered time becomes ``overhead``
        spans blamed on ``node`` (None = the orchestrator itself)."""
        kids = children.get(node.node_id if node is not None else None, [])
        spans: List[Dict[str, Any]] = []
        if not kids:
            if node is not None:
                node.critical = True
                spans.append(self._span_of(node, lo, hi))
            else:
                spans.append(self._overhead_span(lo, hi, None))
            return spans
        chain = self._chain(kids, hi)
        cursor = lo
        for link in chain:
            l_start = max(link.start, cursor)
            l_end = min(link.end if link.end is not None else hi, hi)
            if l_start - cursor > 1e-6:
                spans.append(self._overhead_span(cursor, l_start, node))
            if l_end > l_start:
                spans.extend(
                    self._critical_spans(link, l_start, l_end, children)
                )
            cursor = max(cursor, l_end)
        if hi - cursor > 1e-6:
            spans.append(self._overhead_span(cursor, hi, node))
        return spans

    def _span_of(self, node: DagNode, lo: float, hi: float) -> Dict[str, Any]:
        return {
            "node_id": node.node_id,
            "kind": node.kind,
            "name": node.name,
            "start": lo,
            "end": hi,
            "duration_s": round(max(hi - lo, 0.0), 6),
            "attributes": dict(node.attributes),
        }

    def _overhead_span(
        self, lo: float, hi: float, node: Optional[DagNode]
    ) -> Dict[str, Any]:
        return {
            "node_id": node.node_id if node is not None else None,
            "kind": "overhead",
            "name": (
                f"overhead:{node.name}" if node is not None
                else "overhead:orchestrator"
            ),
            "start": lo,
            "end": hi,
            "duration_s": round(max(hi - lo, 0.0), 6),
            "attributes": {},
        }

    # ------------------------------------------------------------------ #
    # Finish-time computation
    # ------------------------------------------------------------------ #

    def compute(self) -> None:
        """Resolve parents, walk the critical path, derive the breakdown.
        Called under the ledger lock exactly once, from ``finish``."""
        end = self.ended if self.ended is not None else time.perf_counter()
        for node in self.nodes.values():
            if node.end is None:
                node.end = end
        self._resolve_orphans()
        children = self._children_of()
        self.critical_spans = self._critical_spans(
            None, self.created, end, children
        )
        self.breakdown = self._breakdown(end)

    def _resolve_orphans(self) -> None:
        """Flight nodes recorded from the batcher's reader thread carry
        no ambient parent — adopt the deepest non-flight node whose
        interval contains the flight's start (time containment)."""
        containers = [
            n for n in self.nodes.values()
            if n.kind in ("stage", "agent", "retry", "subtask")
        ]
        for node in self.nodes.values():
            if node.parent_id is not None or node.kind not in ("flight",):
                continue
            best: Optional[DagNode] = None
            for cand in containers:
                if cand.start - 1e-6 <= node.start and (
                    cand.end is None or cand.end + 1e-6 >= node.start
                ):
                    if best is None or cand.start >= best.start:
                        best = cand
            if best is not None:
                node.parent_id = best.node_id

    def _breakdown(self, end: float) -> Dict[str, float]:
        out = {name: 0.0 for name in BREAKDOWN_COMPONENTS}
        out["e2e_s"] = max(end - self.created, 0.0)
        for span in self.critical_spans:
            d = span["duration_s"]
            kind = span["kind"]
            attrs = span["attributes"]
            if kind == "queue":
                out["queue_wait_s"] += d
            elif kind == "flight":
                # Split the flight's critical time by its own phase
                # ledger shares (queue wait / prefill / decode).
                q = float(attrs.get("queue_wait_s") or 0.0)
                p = float(attrs.get("prefill_s") or 0.0)
                dec = float(attrs.get("decode_s") or 0.0)
                total = q + p + dec
                if total <= 0:
                    out["llm_decode_s"] += d
                else:
                    out["queue_wait_s"] += d * q / total
                    out["llm_prefill_s"] += d * p / total
                    out["llm_decode_s"] += d * dec / total
            elif kind == "tool":
                out["tool_s"] += d
            elif kind == "memory":
                out["memory_s"] += d
            elif kind == "subtask":
                # Children carry their own critical-path breakdown; merge
                # it scaled to the span's share of the child's e2e so the
                # parent's components still sum to its critical path.
                child = attrs.get("breakdown") or {}
                child_total = sum(
                    float(child.get(c) or 0.0) for c in BREAKDOWN_COMPONENTS
                )
                if child_total > 0:
                    scale = d / child_total
                    for comp in BREAKDOWN_COMPONENTS:
                        out[comp] += float(child.get(comp) or 0.0) * scale
                else:
                    out["orchestrator_overhead_s"] += d
            else:  # overhead / stage / agent / retry leaf time
                out["orchestrator_overhead_s"] += d
        out["critical_path_s"] = round(
            sum(s["duration_s"] for s in self.critical_spans), 6
        )
        # Straggler time: across sibling fan-out branches (subtask nodes
        # at the top level), slowest minus median duration — the price
        # of the join waiting on its slowest branch.
        branches = [
            n.duration for n in self.nodes.values()
            if n.kind == "subtask" and n.parent_id is None
        ]
        if len(branches) >= 2:
            out["straggler_s"] = max(
                max(branches) - statistics.median(branches), 0.0
            )
        for key in list(out):
            out[key] = round(out[key], 6)
        return out

    # ------------------------------------------------------------------ #

    def to_dict(self, nodes: bool = True) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "task_id": self.task_id,
            "trace_id": self.trace_id,
            "parent_task_id": self.parent_task_id,
            "status": self.status,
            "attributes": dict(self.attributes),
            "created_wall": self.created_wall,
            "e2e_s": round(
                ((self.ended or time.perf_counter()) - self.created), 6
            ),
            "marks": {
                k: round(v - self.created_wall, 6)
                for k, v in sorted(self.marks.items(), key=lambda kv: kv[1])
            },
            "breakdown": dict(self.breakdown),
            "critical_path": list(self.critical_spans),
            "dropped_nodes": self.dropped_nodes,
        }
        if nodes:
            out["nodes"] = [
                n.to_dict() for n in sorted(
                    self.nodes.values(), key=lambda n: n.node_id
                )
            ]
        return out


class DagLedger:
    """Registry of in-flight and recently finished task DAGs.

    Thread-safe: serve and agents mutate from the event loop while the
    flight recorder's finish listener attaches engine flights from the
    batcher's reader thread. Every method is a cheap no-op for unknown
    task ids — instrumentation call sites (tools, memory, agents running
    outside an orchestrated task) never need guards.
    """

    def __init__(
        self,
        max_finished: int = 256,
        registry: MetricsRegistry = global_metrics,
        tracer: Any = global_tracer,
    ) -> None:
        self._active: Dict[str, TaskDag] = {}
        self._finished: Deque[TaskDag] = deque(maxlen=max_finished)
        self._lock = threading.Lock()
        self._registry = registry
        self._tracer = tracer
        # Queue residency start times (task_id → (perf_counter, priority)).
        self._queued: Dict[str, Tuple[float, str]] = {}
        # Live criticality model (ROADMAP item 4 → pilottai_tpu/sched/):
        # per-task-type stage profiles learned from finished dags — the
        # ordered top-level stage names and an EMA of each stage's
        # duration — so criticality() can blame-walk a PARTIALLY
        # complete dag and estimate its remaining critical path while
        # the task is still running.
        self._stage_ema: Dict[Tuple[str, str], float] = {}
        self._stage_seq: Dict[str, Tuple[str, ...]] = {}
        # Ambient (task_id, node_id) stack — contextvars so interleaved
        # asyncio task executions each see their own nesting.
        self._ctx: contextvars.ContextVar[tuple] = contextvars.ContextVar(
            f"pilottai_dag_ctx_{id(self)}", default=()
        )
        registry.declare("task.e2e_s", "histogram")
        registry.declare("task.critical_path_s", "histogram")
        for comp in BREAKDOWN_COMPONENTS:
            registry.declare(f"task.{comp}", "histogram")
        registry.declare("task.queue_wait_total_s", "histogram")
        for prio in QUEUE_PRIORITIES:
            registry.declare(f"task.queue_wait.{prio}_s", "histogram")
        registry.declare("task.completed", "counter")
        registry.declare("task.failed", "counter")
        registry.declare("task.cancelled", "counter")
        registry.declare("task.retries", "counter")
        registry.declare("task.active", "gauge")
        registry.set_gauge("task.active", 0.0)

    # ------------------------------------------------------------------ #
    # Ambient context
    # ------------------------------------------------------------------ #

    def current(self) -> Optional[Tuple[str, int]]:
        stack = self._ctx.get()
        return stack[-1] if stack else None

    def current_task(self) -> Optional[str]:
        cur = self.current()
        return cur[0] if cur is not None else None

    # ------------------------------------------------------------------ #
    # Lifecycle
    # ------------------------------------------------------------------ #

    def start(
        self,
        task_id: str,
        trace_id: Optional[str] = None,
        parent_task_id: Optional[str] = None,
        **attributes: Any,
    ) -> TaskDag:
        """Get-or-create the active dag for ``task_id`` (idempotent:
        ``requeue_task`` re-enters ``_queue_task`` for a task whose dag
        already exists — the record, and its history, survive)."""
        with self._lock:
            dag = self._active.get(task_id)
            if dag is None:
                dag = TaskDag(
                    task_id, trace_id or task_id,
                    parent_task_id=parent_task_id, **attributes,
                )
                self._active[task_id] = dag
                self._registry.set_gauge("task.active", len(self._active))
            else:
                dag.attributes.update(attributes)
            return dag

    def mark(self, task_id: str, event: str, at: Optional[float] = None) -> None:
        with self._lock:
            dag = self._active.get(task_id)
            if dag is not None:
                dag.mark(event, at)

    def record(
        self,
        task_id: Optional[str],
        kind: str,
        name: str,
        start: float,
        end: float,
        deps: Optional[List[int]] = None,
        **attributes: Any,
    ) -> Optional[int]:
        """Record an already-finished node. The ambient dag context (when
        it matches ``task_id``) supplies the parent node."""
        if task_id is None:
            return None
        parent_id = None
        cur = self.current()
        if cur is not None and cur[0] == task_id:
            parent_id = cur[1]
        with self._lock:
            dag = self._active.get(task_id)
            if dag is None:
                return None
            node = dag.add_node(
                kind, name, start, end=end, parent_id=parent_id,
                deps=deps, **attributes,
            )
            return node.node_id

    @contextlib.contextmanager
    def recorded(self, kind: str, name: str, **attributes: Any) -> Iterator[None]:
        """Record the wrapped block as a node under the AMBIENT task (a
        no-op outside one) — the one-liner for instrumenting tool-like
        call sites (memory ops, lookups) without threading a task id."""
        start = time.perf_counter()
        try:
            yield
        finally:
            self.record(
                self.current_task(), kind, name,
                start=start, end=time.perf_counter(), **attributes,
            )

    @contextlib.contextmanager
    def span(
        self,
        task_id: str,
        kind: str,
        name: str,
        trace: bool = True,
        **attributes: Any,
    ) -> Iterator[Optional[DagNode]]:
        """Open a dag node around a code block, push it as the ambient
        dag context (tools/memory/flights nest under it), and — unless
        ``trace=False`` — mirror it as a tracer span so the stage shows
        up in the task's Perfetto tree with correct parentage for the
        engine spans opened inside. No-op (yields None) for unknown
        tasks, so direct ``BaseAgent.execute_task`` callers outside an
        orchestrated task pay nothing."""
        start = time.perf_counter()
        parent_id = None
        cur = self.current()
        if cur is not None and cur[0] == task_id:
            parent_id = cur[1]
        with self._lock:
            dag = self._active.get(task_id)
            node = (
                dag.add_node(kind, name, start, parent_id=parent_id,
                             **attributes)
                if dag is not None else None
            )
            dag_trace = dag.trace_id if dag is not None else None
        if node is None:
            yield None
            return
        token = self._ctx.set(self._ctx.get() + ((task_id, node.node_id),))
        span_cm = (
            self._tracer.span(
                f"{kind}.{name}", trace_id=dag_trace, task_id=task_id,
                **attributes,
            )
            if trace else contextlib.nullcontext()
        )
        try:
            with span_cm:
                yield node
        finally:
            self._ctx.reset(token)
            with self._lock:
                if node.end is None:  # finish() may have clamped it already
                    node.end = time.perf_counter()

    # ------------------------------------------------------------------ #
    # Queue residency (PriorityTaskQueue put/get)
    # ------------------------------------------------------------------ #

    def queue_enter(self, task_id: str, priority: str) -> None:
        with self._lock:
            if task_id in self._active:
                self._queued[task_id] = (time.perf_counter(), priority)

    def queue_exit(self, task_id: str) -> None:
        now = time.perf_counter()
        with self._lock:
            entry = self._queued.pop(task_id, None)
            if entry is None:
                return
            entered, priority = entry
            dag = self._active.get(task_id)
            if dag is not None:
                dag.add_node(
                    "queue", "task_queue", entered, end=now, priority=priority
                )
        wait = max(now - entered, 0.0)
        self._registry.observe("task.queue_wait_total_s", wait)
        prio = priority.lower()
        if prio in QUEUE_PRIORITIES:
            self._registry.observe(f"task.queue_wait.{prio}_s", wait)

    # ------------------------------------------------------------------ #
    # FlightRecorder integration (finish listener; any thread)
    # ------------------------------------------------------------------ #

    def observe_flight(self, flight: Any) -> None:
        """Join an engine flight into its task's dag. The handler stamps
        ``dag_task``/``dag_node`` attributes at flight start (the
        ambient dag context of the asyncio task that issued the LLM
        call); trace-id match is the fallback for flights started
        outside any dag context. Never raises."""
        try:
            task_id = flight.attributes.get("dag_task")
            parent_node = flight.attributes.get("dag_node")
            with self._lock:
                dag = self._active.get(task_id) if task_id else None
                if dag is None:
                    dag = next(
                        (
                            d for d in self._active.values()
                            if d.trace_id == flight.trace_id
                        ),
                        None,
                    )
                    parent_node = None
                if dag is None:
                    return
                derived = flight.derived()
                started = flight.started
                ended = flight.ended or time.perf_counter()
                queue_wait = derived.get("queue_wait_s") or 0.0
                ttft = derived.get("ttft_s")
                prefill = max(ttft - queue_wait, 0.0) if ttft is not None \
                    else 0.0
                decode = max(
                    (ended - started) - queue_wait - prefill, 0.0
                )
                dag.add_node(
                    "flight",
                    flight.attributes.get("model", "llm"),
                    started,
                    end=ended,
                    parent_id=(
                        parent_node
                        if isinstance(parent_node, int)
                        and parent_node in dag.nodes else None
                    ),
                    flight_id=flight.flight_id,
                    status=flight.status,
                    tokens=flight.n_tokens,
                    queue_wait_s=round(queue_wait, 6),
                    prefill_s=round(prefill, 6),
                    decode_s=round(decode, 6),
                )
        except Exception:  # noqa: BLE001 — telemetry must not raise
            pass

    # ------------------------------------------------------------------ #
    # Live criticality (the control signal of pilottai_tpu/sched/)
    # ------------------------------------------------------------------ #

    @staticmethod
    def _top_stages(dag: TaskDag) -> List[DagNode]:
        """Top-level lifecycle stages in start order — the per-type
        profile's alphabet. ``agent`` nodes count as stages (they ARE
        the execute body for non-decomposed tasks)."""
        return sorted(
            (
                n for n in dag.nodes.values()
                if n.parent_id is None and n.kind in ("stage", "agent")
            ),
            key=lambda n: n.start,
        )

    def _learn_profile_locked(self, dag: TaskDag) -> None:
        """Update the per-type stage profile from a finished dag (ledger
        lock held): the ordered stage-name sequence (last run wins — the
        pipeline shape, not an average) and a duration EMA per stage."""
        stages = self._top_stages(dag)
        if not stages:
            return
        ttype = str(dag.attributes.get("type") or "generic")
        seen: List[str] = []
        for node in stages:
            if node.name not in seen:
                seen.append(node.name)
            key = (ttype, node.name)
            prev = self._stage_ema.get(key)
            dur = node.duration
            self._stage_ema[key] = (
                dur if prev is None else 0.7 * prev + 0.3 * dur
            )
        self._stage_seq[ttype] = tuple(seen)

    def _criticality_locked(self, dag: TaskDag, now: float) -> float:
        """Blame walk over one partially complete dag (ledger lock
        held): completed profile stages contribute 0, the open stage
        its EMA minus its elapsed time (floored at 0), stages not yet
        started their full EMA."""
        ttype = str(dag.attributes.get("type") or "generic")
        seq = self._stage_seq.get(ttype)
        if not seq:
            return 0.0
        by_name: Dict[str, DagNode] = {}
        for node in self._top_stages(dag):
            # Latest occurrence wins: a retried stage restarts its
            # clock, and blaming the stale first run would zero out
            # live work.
            by_name[node.name] = node
        remaining = 0.0
        for name in seq:
            ema = self._stage_ema.get((ttype, name), 0.0)
            node = by_name.get(name)
            if node is None:
                remaining += ema
            elif node.end is None:
                remaining += max(ema - (now - node.start), 0.0)
        return remaining

    def criticality(self, task_id: str) -> float:
        """Estimated REMAINING critical-path seconds for an active
        task. 0.0 for unknown tasks or types with no finished history
        (the estimator stays silent until it has evidence — the
        scheduler then falls back to the task's static priority)."""
        now = time.perf_counter()
        with self._lock:
            dag = self._active.get(task_id)
            if dag is None:
                return 0.0
            return self._criticality_locked(dag, now)

    def criticalities(self) -> Dict[str, float]:
        """Remaining-critical-path estimates for every active task (the
        scheduler's boost decision compares a task against this set).
        ONE lock acquisition for the whole walk — this runs on every
        agent LLM call, and per-task re-acquisition would serialize
        agent threads on the observability lock."""
        now = time.perf_counter()
        with self._lock:
            return {
                tid: self._criticality_locked(dag, now)
                for tid, dag in self._active.items()
            }

    # ------------------------------------------------------------------ #
    # Finish
    # ------------------------------------------------------------------ #

    def finish(
        self, task_id: str, status: str = "ok"
    ) -> Optional[Dict[str, Any]]:
        """Close the task's dag: compute critical path + breakdown,
        observe the ``task.*`` histograms, roll the record up into its
        parent's dag (when one is active), emit the critical path as
        tracer spans (flagged ``critical_path``) and move the record to
        the finished ring. Returns the summary dict, or None when no
        active dag exists — safe on every finalize path."""
        with self._lock:
            dag = self._active.pop(task_id, None)
            if dag is None:
                return None
            self._queued.pop(task_id, None)
            dag.status = status
            if dag.ended is None:  # synthetic ledgers may pre-stamp it
                dag.ended = time.perf_counter()
            dag.compute()
            self._learn_profile_locked(dag)
            self._finished.append(dag)
            self._registry.set_gauge("task.active", len(self._active))
            parent = (
                self._active.get(dag.parent_task_id)
                if dag.parent_task_id else None
            )
            if parent is not None:
                deps = [
                    parent.subtask_nodes[d]
                    for d in dag.attributes.get("dependencies", ())
                    if d in parent.subtask_nodes
                ]
                node = parent.add_node(
                    "subtask", task_id[:8], dag.created, end=dag.ended,
                    deps=deps, status=status,
                    breakdown=dict(dag.breakdown),
                )
                parent.subtask_nodes[task_id] = node.node_id
        reg = self._registry
        bd = dag.breakdown
        reg.observe("task.e2e_s", bd.get("e2e_s", 0.0))
        reg.observe("task.critical_path_s", bd.get("critical_path_s", 0.0))
        for comp in BREAKDOWN_COMPONENTS:
            reg.observe(f"task.{comp}", bd.get(comp, 0.0))
        # Cancellation is routine (shutdown drains, queue eviction) —
        # it must not inflate the failure counter an alert keys on.
        if status == "ok":
            reg.inc("task.completed")
        elif status == "cancelled":
            reg.inc("task.cancelled")
        else:
            reg.inc("task.failed")
        retries = sum(1 for n in dag.nodes.values() if n.kind == "retry")
        if retries:
            reg.inc("task.retries", retries)
        # Critical path as a span lane in the task's Perfetto trace:
        # each critical span emitted as a finished tracer span flagged
        # ``critical_path`` — load /trace.json?trace_id=<task trace> and
        # the blamed lane renders alongside the live span tree.
        for span in dag.critical_spans:
            self._tracer.emit(
                f"dag.critical.{span['kind']}",
                trace_id=dag.trace_id,
                start=span["start"],
                end=span["end"],
                task_id=task_id,
                node=span["name"],
                critical_path=True,
            )
        return dag.to_dict(nodes=False)

    # ------------------------------------------------------------------ #
    # Inspection (/dag.json)
    # ------------------------------------------------------------------ #

    def describe(self, task_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            dag = self._active.get(task_id)
            if dag is None:
                dag = next(
                    (d for d in reversed(self._finished)
                     if d.task_id == task_id),
                    None,
                )
            return dag.to_dict() if dag is not None else None

    def finished(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._finished)
        if n is not None:
            records = records[-n:]
        return [d.to_dict(nodes=False) for d in records]

    def snapshot(self, n_finished: int = 32) -> Dict[str, Any]:
        """The ``/dag.json`` shape: active task summaries + the most
        recent finished breakdowns/critical paths."""
        with self._lock:
            active = [
                {
                    "task_id": d.task_id,
                    "trace_id": d.trace_id,
                    "age_s": round(time.perf_counter() - d.created, 3),
                    "nodes": len(d.nodes),
                    "marks": {
                        k: round(v - d.created_wall, 3)
                        for k, v in d.marks.items()
                    },
                }
                for d in self._active.values()
            ]
        return {
            "active": active,
            "finished": self.finished(n_finished),
        }

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)

    def reset(self) -> None:
        """Drop all state (tests / bench section isolation) — including
        the learned stage profiles, so one suite's task shapes can't
        leak criticality estimates into another's."""
        with self._lock:
            self._active.clear()
            self._finished.clear()
            self._queued.clear()
            self._stage_ema.clear()
            self._stage_seq.clear()
            self._registry.set_gauge("task.active", 0.0)


class AgentOccupancy:
    """Per-role busy-fraction and queue-depth gauges, sampled from
    ``BaseAgent`` step events.

    ``busy_frac`` is busy-seconds over a rolling window (60 s, or the
    time since the role registered when younger), normalized by the
    number of registered agents of the role — 1.0 means every agent of
    the role was executing for the whole window. Gauges follow the
    ``declare()`` discipline per role at registration.
    """

    def __init__(
        self,
        registry: MetricsRegistry = global_metrics,
        window_s: float = 60.0,
    ) -> None:
        self._registry = registry
        self._window_s = window_s
        self._lock = threading.Lock()
        self._agents: Dict[str, set] = {}
        self._since: Dict[str, float] = {}
        # Per role: closed busy intervals (start, end) within the window
        # plus currently-open step starts keyed by (agent_id, task_id).
        self._busy: Dict[str, Deque[Tuple[float, float]]] = {}
        self._open: Dict[str, Dict[Any, float]] = {}

    def register(self, role: str, agent_id: str) -> None:
        with self._lock:
            fresh = role not in self._agents
            self._agents.setdefault(role, set()).add(agent_id)
            if fresh:
                self._since[role] = time.perf_counter()
                self._busy[role] = deque()
                self._open[role] = {}
                self._registry.declare(f"agent.{role}.busy_frac", "gauge")
                self._registry.declare(f"agent.{role}.queue_depth", "gauge")
                self._registry.set_gauge(f"agent.{role}.busy_frac", 0.0)
                self._registry.set_gauge(f"agent.{role}.queue_depth", 0.0)

    def unregister(self, role: str, agent_id: str) -> None:
        """Remove an agent from its role's denominator; the LAST agent
        of a role retires the role's tracking entirely (gauges zeroed,
        declarations kept) — a stale role would otherwise bias every
        mean-over-roles consumer (bench busy_frac means, scaler reads)
        and, after agent replacement, halve busy_frac forever."""
        with self._lock:
            agents = self._agents.get(role)
            if not agents:
                return
            agents.discard(agent_id)
            if agents:
                return
            for table in (self._agents, self._since, self._busy, self._open):
                table.pop(role, None)
        self._registry.set_gauge(f"agent.{role}.busy_frac", 0.0)
        self._registry.set_gauge(f"agent.{role}.queue_depth", 0.0)

    def step_started(self, role: str, key: Any) -> None:
        with self._lock:
            if role in self._open:
                self._open[role][key] = time.perf_counter()
        self._refresh_role(role)

    def step_finished(self, role: str, key: Any) -> None:
        now = time.perf_counter()
        with self._lock:
            if role not in self._busy:
                return
            start = self._open[role].pop(key, None)
            if start is not None:
                self._busy[role].append((start, now))
        self._refresh_role(role)

    def set_queue_depth(self, role: str, depth: int) -> None:
        if role in self._busy:
            self._registry.set_gauge(f"agent.{role}.queue_depth", float(depth))

    def _busy_frac_locked(self, role: str, now: float) -> float:
        window = min(
            self._window_s, max(now - self._since.get(role, now), 1e-6)
        )
        cutoff = now - window
        intervals = self._busy[role]
        while intervals and intervals[0][1] < cutoff:
            intervals.popleft()
        busy = sum(
            min(end, now) - max(start, cutoff)
            for start, end in intervals
            if end > cutoff
        )
        busy += sum(
            now - max(start, cutoff) for start in self._open[role].values()
        )
        n = max(len(self._agents.get(role, ())), 1)
        return min(busy / (window * n), 1.0)

    def _refresh_role(self, role: str) -> None:
        now = time.perf_counter()
        with self._lock:
            if role not in self._busy:
                return
            frac = self._busy_frac_locked(role, now)
        self._registry.set_gauge(f"agent.{role}.busy_frac", frac)

    def refresh(self) -> Dict[str, float]:
        """Recompute every role's busy_frac against NOW (bench reads
        gauges after a section; step-event-only writes would freeze the
        last mid-run value). Returns role → busy_frac."""
        now = time.perf_counter()
        with self._lock:
            fracs = {
                role: self._busy_frac_locked(role, now)
                for role in self._busy
            }
        for role, frac in fracs.items():
            self._registry.set_gauge(f"agent.{role}.busy_frac", frac)
        return fracs

    def roles(self) -> List[str]:
        with self._lock:
            return sorted(self._busy)

    def reset(self) -> None:
        with self._lock:
            roles = list(self._busy)
            for role in roles:
                self._busy[role].clear()
                self._open[role].clear()
                self._since[role] = time.perf_counter()
        for role in roles:
            self._registry.set_gauge(f"agent.{role}.busy_frac", 0.0)


global_dag = DagLedger()
global_occupancy = AgentOccupancy()
