"""Workload profiler: a rolling per-deployment workload fingerprint.

LLM-Pilot predicts the right configuration *per workload* — which
requires knowing what the workload IS. Every signal already exists in
the obs layer; this module is the aggregation point that turns them
into one comparable fingerprint:

* **prompt/output length** distributions — from finished flights (the
  handler stamps ``prompt_tokens`` as a flight attribute; generated
  tokens come from the flight's token ledger);
* **arrival process** — rate + burstiness (CV of inter-arrival gaps)
  from the flight recorder's *start* listener, which also feeds the
  seasonal forecaster (obs/forecast.py);
* **SLO-class mix** and **session fraction** — flight attributes;
* **DAG stage mix** — which orchestration stages the traffic runs
  (``dag_node`` attributes from the scheduler's ambient context);
* **speculation acceptance** and **kvcache prefix hit rate** — read
  back from the engine's exported gauges/counters.

The fingerprint is exported three ways: ``profile.*`` gauges (declared
here, so ``export_completeness`` covers them from import), the
``/profile.json`` route on APIServer + dashboard (``fingerprint()``),
and the per-deployment profile store next to ``autotune.json``
(``persist()`` → ``utils.compile_cache.store_profile``) where
``scripts/recommend.py`` picks it up.

Import cost: stdlib + utils only (the obs constraint — no jax).
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Any, Dict, List, Optional

from pilottai_tpu.obs.forecast import (
    ArrivalForecast,
    burstiness_cv,
    global_forecast,
)
from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics

# Gauges the profiler owns. Declared at construction (import time for
# the global instance) so the surface is export_completeness-clean
# before the first request.
_GAUGES = (
    "profile.arrival_rps",
    "profile.burstiness_cv",
    "profile.prompt_tokens_p50",
    "profile.prompt_tokens_p95",
    "profile.output_tokens_p50",
    "profile.output_tokens_p95",
    "profile.session_frac",
    "profile.dag_frac",
    "profile.kv_hit_rate",
    "profile.class_frac.interactive",
    "profile.class_frac.batch",
)

_RATE_WINDOW_S = 300.0


def _pct(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return float(sorted_vals[idx])


class WorkloadProfiler:
    """Rolling fingerprint over the last ``window`` finished requests.

    Wired in ``obs/__init__`` as both a start listener (arrivals) and a
    finish listener (lengths/mix) on the global flight recorder; tests
    construct their own with an isolated registry/forecast.
    """

    def __init__(
        self,
        window: int = 2048,
        registry: MetricsRegistry = global_metrics,
        forecast: ArrivalForecast = global_forecast,
    ) -> None:
        self._registry = registry
        self._forecast = forecast
        self._lock = threading.Lock()
        self._deployment: Optional[str] = None
        self._prompt_tokens: deque = deque(maxlen=window)
        self._output_tokens: deque = deque(maxlen=window)
        self._arrivals: deque = deque(maxlen=window)     # wall-clock stamps
        self._classes: deque = deque(maxlen=window)      # slo_class per finish
        self._sessions: deque = deque(maxlen=window)     # bool per finish
        self._dag: deque = deque(maxlen=window)          # dag_node or None
        self._finished = 0
        for name in _GAUGES:
            registry.declare(name, "gauge")

    # ------------------------------------------------------------------ #

    def configure(self, deployment: Optional[str]) -> None:
        """Set the deployment key the fingerprint persists under
        (the engine passes its model name at boot)."""
        with self._lock:
            self._deployment = deployment

    @property
    def deployment(self) -> Optional[str]:
        return self._deployment

    # ------------------------------------------------------------------ #
    # Flight listeners
    # ------------------------------------------------------------------ #

    def observe_start(self, flight: Any) -> None:
        """Start listener: one arrival. Feeds the inter-arrival window
        and the seasonal forecaster (wall clock — the forecaster's
        seasonal phase is a time-of-day concept)."""
        now = time.time()
        with self._lock:
            self._arrivals.append(now)
        self._forecast.observe(at=now)

    def observe_flight(self, flight: Any) -> None:
        """Finish listener (any status): fold the flight's shape into
        the rolling windows."""
        attrs = getattr(flight, "attributes", {}) or {}
        prompt = attrs.get("prompt_tokens")
        tokens = getattr(flight, "n_tokens", 0) or attrs.get(
            "completion_tokens", 0
        )
        with self._lock:
            if isinstance(prompt, (int, float)) and prompt >= 0:
                self._prompt_tokens.append(int(prompt))
            if tokens:
                self._output_tokens.append(int(tokens))
            self._classes.append(str(attrs.get("slo_class") or "interactive"))
            self._sessions.append(bool(attrs.get("session_id")))
            self._dag.append(attrs.get("dag_node"))
            self._finished += 1
        if self._finished % 32 == 0:
            self.refresh_gauges()

    # ------------------------------------------------------------------ #
    # Derived views
    # ------------------------------------------------------------------ #

    def _arrival_stats(self, now: Optional[float] = None) -> Dict[str, float]:
        now = time.time() if now is None else now
        with self._lock:
            stamps = list(self._arrivals)
        recent = [t for t in stamps if now - t <= _RATE_WINDOW_S]
        span = (now - recent[0]) if recent else 0.0
        rps = len(recent) / span if span > 1e-9 else float(len(recent))
        gaps = [b - a for a, b in zip(stamps, list(stamps)[1:])]
        gaps_sorted = sorted(gaps)
        return {
            "rps": round(rps, 4),
            "burstiness_cv": round(burstiness_cv(gaps), 4),
            "interarrival_p50_s": round(_pct(gaps_sorted, 0.50), 4),
            "interarrival_p95_s": round(_pct(gaps_sorted, 0.95), 4),
            "observed": len(stamps),
        }

    def _mix(self, values: List[Any]) -> Dict[str, float]:
        total = len(values)
        if not total:
            return {}
        counts = Counter(str(v) for v in values if v is not None)
        return {
            k: round(c / total, 4) for k, c in sorted(counts.items())
        }

    def _engine_signals(self) -> Dict[str, float]:
        reg = self._registry
        lookups = float(reg.get("engine.kvcache.lookups") or 0.0)
        hits = float(reg.get("engine.kvcache.hits") or 0.0)
        return {
            "spec_acceptance": round(
                float(reg.get("engine.spec_acceptance") or 0.0), 4
            ),
            "kv_hit_rate": round(hits / lookups, 4) if lookups else 0.0,
        }

    def fingerprint(self) -> Dict[str, Any]:
        """The ``/profile.json`` body — everything the cost model needs
        to match this deployment's traffic against recorded workloads."""
        with self._lock:
            prompts = sorted(self._prompt_tokens)
            outputs = sorted(self._output_tokens)
            classes = list(self._classes)
            sessions = list(self._sessions)
            dag = list(self._dag)
            deployment = self._deployment
            finished = self._finished
        n = len(classes)
        engine = self._engine_signals()
        fp: Dict[str, Any] = {
            "deployment": deployment,
            "updated": round(time.time(), 3),
            "requests": finished,
            "window": n,
            "prompt_tokens": {
                "p50": _pct(prompts, 0.50),
                "p95": _pct(prompts, 0.95),
                "p99": _pct(prompts, 0.99),
                "mean": round(sum(prompts) / len(prompts), 2) if prompts else 0.0,
            },
            "output_tokens": {
                "p50": _pct(outputs, 0.50),
                "p95": _pct(outputs, 0.95),
                "p99": _pct(outputs, 0.99),
                "mean": round(sum(outputs) / len(outputs), 2) if outputs else 0.0,
            },
            "arrival": self._arrival_stats(),
            "class_mix": self._mix(classes),
            "session_frac": round(sum(sessions) / n, 4) if n else 0.0,
            "dag": {
                "frac": round(
                    sum(1 for d in dag if d) / n, 4
                ) if n else 0.0,
                "stage_mix": self._mix([d for d in dag if d]),
            },
            "spec_acceptance": engine["spec_acceptance"],
            "kv_hit_rate": engine["kv_hit_rate"],
            "forecast": self._forecast.snapshot(),
        }
        return fp

    def refresh_gauges(self) -> None:
        """Publish the fingerprint's headline numbers as ``profile.*``
        gauges — the autoscaler-visible / Prometheus-scrapable view."""
        fp = self.fingerprint()
        reg = self._registry
        reg.set_gauge("profile.arrival_rps", fp["arrival"]["rps"])
        reg.set_gauge("profile.burstiness_cv", fp["arrival"]["burstiness_cv"])
        reg.set_gauge("profile.prompt_tokens_p50", fp["prompt_tokens"]["p50"])
        reg.set_gauge("profile.prompt_tokens_p95", fp["prompt_tokens"]["p95"])
        reg.set_gauge("profile.output_tokens_p50", fp["output_tokens"]["p50"])
        reg.set_gauge("profile.output_tokens_p95", fp["output_tokens"]["p95"])
        reg.set_gauge("profile.session_frac", fp["session_frac"])
        reg.set_gauge("profile.dag_frac", fp["dag"]["frac"])
        reg.set_gauge("profile.kv_hit_rate", fp["kv_hit_rate"])
        mix = fp["class_mix"]
        for cls in sorted({"interactive", "batch"} | set(mix)):
            reg.set_gauge(f"profile.class_frac.{cls}", mix.get(cls, 0.0))

    # ------------------------------------------------------------------ #
    # Persistence
    # ------------------------------------------------------------------ #

    def persist(self, key: Optional[str] = None) -> Optional[str]:
        """Write the current fingerprint into the per-deployment profile
        store (``profiles.json`` next to ``autotune.json``), preserving
        any stored recommendation for the deployment. Returns the store
        key used, or None when no deployment key is known."""
        from pilottai_tpu.utils.compile_cache import load_profile, store_profile

        key = key or self._deployment
        if not key:
            return None
        blob = load_profile(key) or {}
        blob["fingerprint"] = self.fingerprint()
        store_profile(key, blob)
        return key

    def reset(self) -> None:
        with self._lock:
            self._prompt_tokens.clear()
            self._output_tokens.clear()
            self._arrivals.clear()
            self._classes.clear()
            self._sessions.clear()
            self._dag.clear()
            self._finished = 0


global_profile = WorkloadProfiler()

__all__ = ["WorkloadProfiler", "global_profile"]
