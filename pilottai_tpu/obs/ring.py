"""Engine step telemetry ring: the in-memory half of the flight recorder.

Every decode chunk, admission wave and handler request appends one small
dict to a bounded ring. The ring is cheap enough to run always-on (a
deque append under a lock, a few hundred bytes per record) and is what
the black-box dumper snapshots when something goes wrong: the last N
steps before a deadline blew or the breaker opened are exactly the
context a postmortem needs and exactly what process logs lose.

Record shape (by ``kind``):

``engine.chunk``   one fused decode chunk folded on the host — slot
                   occupancy, tokens landed, dispatched block count
                   (``chunk_blocks``, the adaptive scheduler's per-
                   dispatch pick) and useful-block utilization, the
                   dispatch's ``host_gap_ms`` (device idle time between
                   the previous fold/feed and this dispatch; 0 = the
                   pipeline kept the device fed), queue depth, KV
                   page-pool utilization, active strip width, pipeline
                   depth.
``engine.admit``   one admission wave — group size, queue depth.
``engine.shed``    an admission-control shed.
``handler.request`` one completed/failed LLMHandler request — status,
                   latency, tokens (the only kind mock deployments emit).

Every record carries ``ts`` (epoch seconds, human correlation) and
``ts_mono`` (``time.perf_counter()``, the tracer's clock) so steps line
up with span trees in the Perfetto export.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional


class StepRing:
    """Thread-safe bounded ring of telemetry step records."""

    def __init__(self, capacity: int = 512) -> None:
        self._records: Deque[Dict[str, Any]] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, kind: str, **fields: Any) -> Dict[str, Any]:
        rec = {
            "kind": kind,
            "ts": time.time(),
            "ts_mono": time.perf_counter(),
            **fields,
        }
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._records.append(rec)
        return rec

    def snapshot(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        """The last ``n`` records (all retained when None), oldest first."""
        with self._lock:
            records = list(self._records)
        if n is not None and n >= 0:
            records = records[-n:]
        return records

    def clear(self) -> None:
        with self._lock:
            self._records.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


global_steps = StepRing()
