"""Per-request flight recorder: phase timestamps from accept to last token.

LLM-Pilot (arxiv 2410.02425) argues per-phase characterization — queue
wait, prefill, time-to-first-token, per-token decode — is the
prerequisite for capacity planning; an aggregate request latency can't
tell an admission backlog from a slow decode. Each request therefore
accumulates a ``RequestFlight``: the handler opens one keyed by a
per-request ``flight_id`` (the shared ``trace_id`` rides along for
correlation — many flights can share one orchestrator trace), the
engine layers mark phases as they happen (admission on the device
thread, token folds on the reader thread), and ``finish`` derives the
serving metrics and feeds them into ``global_metrics`` histograms:

===========================  ==========================================
``request.queue_wait_s``     submit → batcher admission (slot granted)
``request.ttft_s``           start → first generated token on the host
``request.itl_s``            inter-token latency, observed per fold
``request.tpot_s``           (last − first token) / (n − 1)
``request.e2e_s``            start → finish
===========================  ==========================================

plus ``request.completed`` / ``request.failed`` counters labelled by the
finish status in ``request.finished.<status>``.

Backends that cannot see individual tokens (the mock, pre-token-callback
custom backends) call ``synthesize_tokens`` with the response envelope —
TTFT/TPOT become envelope-derived estimates rather than absent, so
mock-engine runs still produce the full percentile surface.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Deque, Dict, List, Optional

from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics


@dataclass
class RequestFlight:
    """One request's phase ledger. All timestamps are
    ``time.perf_counter()`` — the tracer's clock.

    ``flight_id`` is the UNIQUE ledger key (one per engine request);
    ``trace_id`` is the shared correlation id — orchestrator traffic
    runs many engine calls under one trace, and keying the ledger by
    trace would merge concurrent siblings' phases (review finding)."""

    flight_id: str
    trace_id: str
    started: float = field(default_factory=time.perf_counter)
    attributes: Dict[str, Any] = field(default_factory=dict)
    marks: Dict[str, float] = field(default_factory=dict)
    n_tokens: int = 0
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    status: Optional[str] = None  # set by finish()
    ended: Optional[float] = None

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "flight_id": self.flight_id,
            "trace_id": self.trace_id,
            "status": self.status,
            "attributes": dict(self.attributes),
            "marks": {
                k: round(v - self.started, 6) for k, v in self.marks.items()
            },
            "tokens": self.n_tokens,
        }
        for name, value in self.derived().items():
            out[name] = round(value, 6)
        return out

    def derived(self) -> Dict[str, float]:
        """Phase durations computable from the ledger so far."""
        out: Dict[str, float] = {}
        admitted = self.marks.get("admitted")
        if admitted is not None:
            out["queue_wait_s"] = max(admitted - self.started, 0.0)
        if self.first_token_at is not None:
            out["ttft_s"] = max(self.first_token_at - self.started, 0.0)
        if (
            self.n_tokens > 1
            and self.first_token_at is not None
            and self.last_token_at is not None
        ):
            out["tpot_s"] = max(
                (self.last_token_at - self.first_token_at)
                / (self.n_tokens - 1),
                0.0,
            )
        if self.ended is not None:
            out["e2e_s"] = max(self.ended - self.started, 0.0)
        return out


class FlightRecorder:
    """Registry of in-flight and recently finished request flights.

    Thread-safe: the HTTP edge and handler run on the event loop while
    the batcher marks phases from its device and reader threads. All
    mutation happens under one lock; every method is a cheap no-op for
    unknown trace ids, so instrumentation call sites never need guards.
    """

    def __init__(
        self,
        max_finished: int = 1024,
        registry: MetricsRegistry = global_metrics,
    ) -> None:
        self._active: Dict[str, RequestFlight] = {}
        self._finished: Deque[RequestFlight] = deque(maxlen=max_finished)
        self._lock = threading.Lock()
        self._registry = registry
        # Finish listeners: called with the closed RequestFlight after
        # its metrics are observed (obs/__init__ wires the SLO tracker
        # here). Outside the lock; exceptions are swallowed — derived
        # telemetry must never fail the request path.
        self._listeners: List[Any] = []
        # Start listeners: called once per flight on its FIRST start()
        # (the idempotent re-entry that merely enriches attributes does
        # not re-fire) — the arrival event the workload profiler and
        # the seasonal forecaster key on. Same outside-the-lock,
        # swallow-exceptions contract as finish listeners.
        self._start_listeners: List[Any] = []

    def add_finish_listener(self, fn: Any) -> None:
        """Register ``fn(flight: RequestFlight)`` to run on every
        ``finish`` (any status)."""
        self._listeners.append(fn)

    def add_start_listener(self, fn: Any) -> None:
        """Register ``fn(flight: RequestFlight)`` to run once per flight
        when it is first opened."""
        self._start_listeners.append(fn)

    # ------------------------------------------------------------------ #
    # Lifecycle (handler / HTTP edge)
    # ------------------------------------------------------------------ #

    def start(
        self,
        flight_id: str,
        trace_id: Optional[str] = None,
        **attributes: Any,
    ) -> RequestFlight:
        """Get-or-create the active flight for ``flight_id`` (idempotent:
        the server may open it before the handler enriches it).
        ``trace_id`` defaults to the flight id for callers with a
        one-request trace (the HTTP edge)."""
        created = False
        with self._lock:
            flight = self._active.get(flight_id)
            if flight is None:
                flight = RequestFlight(
                    flight_id=flight_id, trace_id=trace_id or flight_id
                )
                self._active[flight_id] = flight
                created = True
            flight.attributes.update(attributes)
        if created:
            for listener in self._start_listeners:
                try:
                    listener(flight)
                except Exception:  # noqa: BLE001 — telemetry must not raise
                    pass
        return flight

    def finish(self, flight_id: str, status: str = "ok") -> Optional[Dict[str, Any]]:
        """Close the flight: derive phase metrics, observe them into the
        registry, move the record to the finished ring. Returns the
        flight's summary dict, or None when no active flight exists
        (already finished, or never started) — safe to call from every
        error path without bookkeeping.

        Phase histograms are observed for ``ok`` flights ONLY: a storm
        of shed/breaker-fast-fails would otherwise flood the (window-
        aware) latency percentiles with ~0 ms samples and make p99 read
        "healthy" mid-outage — failures are counted, not timed."""
        with self._lock:
            flight = self._active.pop(flight_id, None)
            if flight is None:
                return None
            flight.status = status
            flight.ended = time.perf_counter()
            self._finished.append(flight)
        if status == "ok":
            for name, value in flight.derived().items():
                self._registry.observe(f"request.{name}", value)
            self._registry.inc("request.completed")
        else:
            self._registry.inc("request.failed")
        self._registry.inc(f"request.finished.{status}")
        for listener in self._listeners:
            try:
                listener(flight)
            except Exception:  # noqa: BLE001 — telemetry must not raise
                pass
        return flight.to_dict()

    # ------------------------------------------------------------------ #
    # Phase marks (any thread)
    # ------------------------------------------------------------------ #

    def mark(self, flight_id: str, phase: str, at: Optional[float] = None) -> None:
        """Stamp a named phase (first stamp wins — a retry re-entering a
        phase must not erase when the request FIRST reached it)."""
        with self._lock:
            flight = self._active.get(flight_id)
            if flight is not None:
                flight.marks.setdefault(
                    phase, at if at is not None else time.perf_counter()
                )

    def token(self, flight_id: str, n: int = 1, at: Optional[float] = None) -> None:
        """Record ``n`` generated tokens surfacing on the host at ``at``.
        The first call fixes TTFT; later calls observe the inter-token
        gap (per token) into ``request.itl_s``."""
        if n <= 0:
            return
        at = at if at is not None else time.perf_counter()
        itl: Optional[float] = None
        with self._lock:
            flight = self._active.get(flight_id)
            if flight is None:
                return
            if flight.first_token_at is None:
                flight.first_token_at = at
                if n > 1:
                    itl = max(at - flight.started, 0.0) / n
            else:
                prev = flight.last_token_at or flight.first_token_at
                itl = max(at - prev, 0.0) / n
            flight.last_token_at = at
            flight.n_tokens += n
        if itl is not None:
            self._registry.observe("request.itl_s", itl)

    def synthesize_tokens(
        self, flight_id: str, n: int, t_start: float, t_end: float
    ) -> None:
        """Envelope fallback for backends with no token visibility: model
        ``n`` tokens spread uniformly over [t_start, t_end], so TTFT ≈
        latency/n and TPOT ≈ latency/n. No-op when real token marks
        already landed (the native engine's batcher feeds those)."""
        if n <= 0:
            return
        with self._lock:
            flight = self._active.get(flight_id)
            if flight is None or flight.n_tokens:
                return
            per_tok = max(t_end - t_start, 0.0) / n
            flight.first_token_at = t_start + per_tok
            flight.last_token_at = t_end
            flight.n_tokens = n

    def reset_tokens(self, flight_id: str) -> None:
        """Clear the token timeline at a retry boundary: a new attempt's
        first token must not register as an inter-token gap from the
        aborted attempt's last token (the backoff sleep would land in
        ``request.itl_s`` as a multi-second sample). ``started`` and the
        phase marks stay — TTFT/e2e remain client-perceived, retries
        included."""
        with self._lock:
            flight = self._active.get(flight_id)
            if flight is not None:
                flight.n_tokens = 0
                flight.first_token_at = None
                flight.last_token_at = None

    def set_token_envelope(
        self, flight_id: str, n: int, first_at: float, last_at: float
    ) -> None:
        """Stream fallback: the consumer observed ``n`` deltas between
        ``first_at``/``last_at`` but the backend recorded no per-token
        marks (mock/custom backends) — adopt the delta envelope as the
        token timeline. No-op when real marks exist."""
        if n <= 0:
            return
        with self._lock:
            flight = self._active.get(flight_id)
            if flight is None or flight.n_tokens:
                return
            flight.first_token_at = first_at
            flight.last_token_at = last_at
            flight.n_tokens = n

    # ------------------------------------------------------------------ #
    # Inspection
    # ------------------------------------------------------------------ #

    def get(self, flight_id: str) -> Optional[RequestFlight]:
        with self._lock:
            return self._active.get(flight_id)

    def describe(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Summary of the trace's most recent flight, active or finished
        (black-box dumps call this for the request that tripped them —
        by TRACE id, the correlation key the dump carries)."""
        with self._lock:
            flight = next(
                (f for f in self._active.values() if f.trace_id == trace_id),
                None,
            )
            if flight is None:
                for done in reversed(self._finished):
                    if done.trace_id == trace_id:
                        flight = done
                        break
            return flight.to_dict() if flight is not None else None

    def finished(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._finished)
        if n is not None:
            records = records[-n:]
        return [f.to_dict() for f in records]

    def active_count(self) -> int:
        with self._lock:
            return len(self._active)


global_flight = FlightRecorder()
