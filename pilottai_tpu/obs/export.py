"""Standard-format exporters: Prometheus text exposition and
Chrome/Perfetto ``trace_event`` JSON.

One ``metrics_snapshot`` feeds every metrics endpoint — the API server's
``/metrics`` and the dashboard's ``/metrics.json`` previously built
different shapes from the same registry, so dashboards and scrapers
could not share tooling. Both now serve this snapshot, and both accept
``?format=prometheus`` for the text exposition a Prometheus scraper (or
``promtool check metrics``) consumes directly.

The Perfetto exporter turns finished span trees plus engine step-ring
records into ``{"traceEvents": [...]}`` JSON loadable at
https://ui.perfetto.dev (or chrome://tracing). Spans become complete
("X") slices — one track per trace id, nesting by time containment —
and engine steps become counter ("C") tracks (slot occupancy, tokens
per chunk, free KV pages, queue depth), on the same
``time.perf_counter`` clock so host spans line up with the device-side
``jax.profiler.TraceAnnotation`` markers the tracer already emits into
XLA traces.
"""

from __future__ import annotations

import re
from typing import Any, Dict, Iterable, List, Optional

from pilottai_tpu.utils.metrics import MetricsRegistry, global_metrics
from pilottai_tpu.utils.tracing import Span

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_ESC = str.maketrans({"\\": r"\\", '"': r"\"", "\n": r"\n"})


def metrics_snapshot(
    component: Optional[Any] = None,
    registry: MetricsRegistry = global_metrics,
) -> Dict[str, Any]:
    """THE metrics snapshot: registry counters/gauges/histogram summaries
    plus an optional component's ``get_metrics()`` dict (a Serve, an
    LLMHandler, a handler map). Component failures degrade to an error
    entry — a metrics endpoint must never 500 because one source did."""
    snap = registry.snapshot()
    if component is not None:
        if hasattr(component, "get_metrics"):
            try:
                snap["component"] = component.get_metrics()
            except Exception as exc:  # noqa: BLE001 — metrics must not raise
                snap["component"] = {"error": str(exc)}
        else:
            snap["component"] = component
    return snap


def _metric_name(prefix: str, name: str) -> str:
    name = _NAME_OK.sub("_", name)
    if not re.match(r"[a-zA-Z_:]", name):
        name = "_" + name
    return f"{prefix}_{name}" if prefix else name


def _fmt(value: Any) -> Optional[str]:
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, (int, float)):
        return repr(float(value))
    return None


def prometheus_text(
    snapshot: Dict[str, Any], prefix: str = "pilottai"
) -> str:
    """Render a ``metrics_snapshot`` dict as Prometheus text exposition
    (version 0.0.4). Counters/gauges map directly; histograms render as
    summaries (quantile-labelled lines + ``_count``/``_sum``); numeric
    leaves of the component dict flatten under ``<prefix>_component_``.
    """
    lines: List[str] = []

    def emit(name: str, kind: str, samples: Iterable[str]) -> None:
        lines.append(f"# TYPE {name} {kind}")
        lines.extend(samples)

    for raw, value in sorted(snapshot.get("counters", {}).items()):
        val = _fmt(value)
        if val is not None:
            name = _metric_name(prefix, raw)
            emit(name, "counter", [f"{name} {val}"])
    gauges = dict(snapshot.get("gauges", {}))
    if "uptime_s" in snapshot:
        gauges.setdefault("uptime_s", snapshot["uptime_s"])
    for raw, value in sorted(gauges.items()):
        val = _fmt(value)
        if val is not None:
            name = _metric_name(prefix, raw)
            emit(name, "gauge", [f"{name} {val}"])
    for raw, summary in sorted(snapshot.get("histograms", {}).items()):
        name = _metric_name(prefix, raw)
        samples = []
        for q_label, key in (("0.5", "p50"), ("0.9", "p90"), ("0.99", "p99")):
            val = _fmt(summary.get(key))
            if val is not None:
                samples.append(f'{name}{{quantile="{q_label}"}} {val}')
        count = summary.get("count", 0)
        mean = summary.get("mean") or 0.0
        samples.append(f"{name}_count {_fmt(count)}")
        samples.append(f"{name}_sum {_fmt(count * mean)}")
        emit(name, "summary", samples)

    component = snapshot.get("component")
    if isinstance(component, dict):
        flat: Dict[str, Any] = {}
        _flatten(component, "", flat)
        for raw, value in sorted(flat.items()):
            val = _fmt(value)
            if val is not None:
                name = _metric_name(f"{prefix}_component", raw)
                emit(name, "gauge", [f"{name} {val}"])
    return "\n".join(lines) + "\n"


def _flatten(tree: Dict[str, Any], path: str, out: Dict[str, Any]) -> None:
    for key, value in tree.items():
        sub = f"{path}_{key}" if path else str(key)
        if isinstance(value, dict):
            _flatten(value, sub, out)
        elif isinstance(value, (int, float, bool)):
            out[sub] = value


def export_completeness(
    registry: MetricsRegistry = global_metrics, prefix: str = "pilottai"
) -> List[str]:
    """Walk the registry's DECLARED series and verify each reaches both
    export surfaces: the ``metrics_snapshot`` dict and the Prometheus
    text exposition. Returns the list of problems (empty = fully wired).

    This is the ship-gate for new metrics (tests/test_slo.py): a series
    a subsystem registers via ``MetricsRegistry.declare`` but that never
    surfaces in ``/metrics`` — because an exporter filters it, renames
    it into a collision, or the declaration kind mismatches the writer —
    fails CI instead of shipping half-wired."""
    problems: List[str] = []
    snap = metrics_snapshot(registry=registry)
    text = prometheus_text(snap, prefix=prefix)
    section = {"counter": "counters", "gauge": "gauges",
               "histogram": "histograms"}
    # How each declared kind renders in the exposition (histograms are
    # emitted as Prometheus summaries).
    prom_kind = {"counter": "counter", "gauge": "gauge",
                 "histogram": "summary"}
    exposed: Dict[str, set] = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, mname, mkind = line.split()
            exposed.setdefault(mname, set()).add(mkind)
    for name, kind in sorted(registry.declared().items()):
        if name not in snap.get(section[kind], {}):
            problems.append(f"{name} ({kind}): missing from metrics_snapshot")
            continue
        # Declared one kind, written as another: the declaration's
        # zero-fill makes the declared section look populated while the
        # real data lives in a sibling section under the same name.
        others = [
            k for k, sec in section.items()
            if k != kind and name in snap.get(sec, {})
        ]
        if others:
            problems.append(
                f"{name}: declared {kind} but also written as "
                f"{'/'.join(others)}"
            )
        kinds = exposed.get(_metric_name(prefix, name))
        if not kinds:
            problems.append(
                f"{name} ({kind}): missing from Prometheus exposition"
            )
        elif prom_kind[kind] not in kinds:
            problems.append(
                f"{name} ({kind}): exposed as {'/'.join(sorted(kinds))}, "
                f"expected {prom_kind[kind]}"
            )
    return problems


# ---------------------------------------------------------------------- #
# Perfetto / Chrome trace_event
# ---------------------------------------------------------------------- #

_SPAN_PID = 1
_ENGINE_PID = 2

# Step-record fields exported as counter tracks. host_gap_ms is the
# device-feed health signal (time the device sat idle waiting on host
# work before the dispatch — 0 when the pipeline kept it fed).
_STEP_COUNTERS = (
    "slots_active", "tokens", "queue_depth", "kv_pages_free",
    "chunk_blocks", "utilization", "host_gap_ms",
)


def perfetto_trace(
    spans: Iterable[Any],
    steps: Optional[Iterable[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """Build a Chrome ``trace_event`` JSON object from finished spans
    (``Span`` objects or their ``to_dict`` form) and optional step-ring
    records. Each trace id gets its own named thread track so concurrent
    requests render side by side; parent/child nesting is preserved by
    time containment within the track."""
    events: List[Dict[str, Any]] = []
    tids: Dict[str, int] = {}

    def tid_for(trace_id: str) -> int:
        if trace_id not in tids:
            tids[trace_id] = len(tids) + 1
            events.append({
                "ph": "M", "name": "thread_name", "pid": _SPAN_PID,
                "tid": tids[trace_id],
                "args": {"name": f"trace {trace_id}"},
            })
        return tids[trace_id]

    for span in spans:
        d = span.to_dict() if isinstance(span, Span) else dict(span)
        start, end = d.get("start"), d.get("end")
        if start is None or end is None:
            continue  # still open — a complete event needs both edges
        args = {
            "trace_id": d.get("trace_id"),
            "span_id": d.get("span_id"),
            "parent_id": d.get("parent_id"),
            **(d.get("attributes") or {}),
        }
        events.append({
            "name": d.get("name", "span"),
            "ph": "X",
            "ts": start * 1e6,           # perf_counter seconds → µs
            "dur": max(end - start, 0.0) * 1e6,
            "pid": _SPAN_PID,
            "tid": tid_for(str(d.get("trace_id"))),
            "cat": "request",
            "args": args,
        })

    if steps:
        named_engine = False
        for rec in steps:
            ts = rec.get("ts_mono")
            if ts is None:
                continue
            if not named_engine:
                named_engine = True
                events.append({
                    "ph": "M", "name": "process_name", "pid": _ENGINE_PID,
                    "tid": 0, "args": {"name": "engine steps"},
                })
            kind = rec.get("kind", "step")
            for field in _STEP_COUNTERS:
                if field in rec:
                    events.append({
                        "name": f"engine/{field}",
                        "ph": "C",
                        "ts": ts * 1e6,
                        "pid": _ENGINE_PID,
                        "args": {field: rec[field]},
                    })
            if kind not in ("engine.chunk",):
                # Discrete events (admits, sheds, handler requests) show
                # as instants on the engine track.
                events.append({
                    "name": kind,
                    "ph": "i",
                    "s": "p",
                    "ts": ts * 1e6,
                    "pid": _ENGINE_PID,
                    "tid": 0,
                    "args": {
                        k: v for k, v in rec.items()
                        if k not in ("ts", "ts_mono", "kind")
                        and isinstance(v, (int, float, str, bool))
                    },
                })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# ---------------------------------------------------------------------- #
# Phase percentiles (bench / capacity planning)
# ---------------------------------------------------------------------- #

_PHASES = {
    "queue_wait": "request.queue_wait_s",
    "prefill": "engine.prefill_latency",
    "ttft": "request.ttft_s",
    "tpot": "request.tpot_s",
    "itl": "request.itl_s",
    "e2e": "request.e2e_s",
}


def phase_summary(
    registry: MetricsRegistry = global_metrics,
) -> Dict[str, Dict[str, Any]]:
    """Per-phase latency percentiles (ms) from the flight-recorder
    histograms — the breakdown bench.py emits so perf PRs get a
    phase-attributed trajectory instead of an aggregate step rate.
    Percentiles are window-aware (the most recent ≤4096 samples)."""
    hists = registry.snapshot()["histograms"]
    out: Dict[str, Dict[str, Any]] = {}
    for phase, metric in _PHASES.items():
        summary = hists.get(metric)
        if not summary or not summary.get("count"):
            continue
        out[phase] = {
            "p50_ms": _ms(summary.get("p50")),
            "p90_ms": _ms(summary.get("p90")),
            "p99_ms": _ms(summary.get("p99")),
            "count": summary.get("count"),
        }
    return out


def _ms(seconds: Optional[float]) -> Optional[float]:
    return None if seconds is None else round(seconds * 1e3, 3)
