"""Black-box dumper: when a request dies, write down what the engine was
doing.

A deadline that expires, a breaker that opens or a request that errors
out currently leaves only a status code; the context that explains it —
was the queue deep? were slots full? was the page pool exhausted? — is
gone by the time anyone looks. On each such event this module snapshots
the last N step-ring records plus the affected request's span tree and
flight ledger, keeps the dump in a bounded in-memory ring, and (when a
dump path is configured) appends it to a ``BlackBoxJournal`` JSONL file
via ``checkpoint/journal.py`` — the same degraded-write semantics and
``checkpoint.write`` chaos point as the task journal.

Dump record shape (one JSON object per line)::

    {"ev": "blackbox", "ts": ..., "reason": "deadline_expired",
     "trace_id": "...", "steps": [last N ring records],
     "spans": [finished spans of the trace], "flight": {...}, ...extra}

Repeated (reason, trace_id) pairs are deduplicated: the handler and the
batcher both observe the same expiry, and one dump per event is the
point — a dump storm during an outage would bury the first, most
interesting record.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from typing import Any, Deque, Dict, List, Optional, Tuple

from pilottai_tpu.obs.flight import global_flight
from pilottai_tpu.obs.ring import global_steps
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics
from pilottai_tpu.utils.tracing import global_tracer


class BlackBox:
    """Dump coordinator. Always-on in memory; file output is opt-in via
    ``configure`` (serving deployments point it next to the task
    journal; tests point it at tmp_path)."""

    def __init__(
        self,
        keep_steps: int = 64,
        max_recent: int = 16,
        dedup_window: float = 30.0,
    ) -> None:
        self.keep_steps = keep_steps
        self.dedup_window = dedup_window
        self._journal = None  # BlackBoxJournal once configured
        self._recent: Deque[Dict[str, Any]] = deque(maxlen=max_recent)
        # (reason, trace_id) → last dump time. Time-bounded: trace ids
        # are client-controlled (x-request-id), and a gateway reusing a
        # fixed id must not suppress postmortem dumps forever — only
        # the double-report of ONE event (handler + batcher observing
        # the same expiry within seconds).
        self._seen: Dict[Tuple[str, str], float] = {}
        self._lock = threading.Lock()
        # Journal writes run on a dedicated daemon thread: dump() is
        # called from the batcher's device thread and the event loop —
        # JSON serialization + file flush there would stall decode
        # dispatch (or the loop) exactly when the engine is drowning.
        self._write_q: "queue.Queue[Optional[Dict[str, Any]]]" = queue.Queue(
            maxsize=64
        )
        self._writer: Optional[threading.Thread] = None
        self._log = get_logger("obs.blackbox")

    # ------------------------------------------------------------------ #

    def configure(
        self,
        path: str,
        keep_steps: Optional[int] = None,
        fsync: bool = False,
    ) -> "BlackBox":
        """Attach (or re-point) the JSONL dump file."""
        from pilottai_tpu.checkpoint.journal import BlackBoxJournal

        with self._lock:
            if self._journal is not None:
                self._journal.close()
            self._journal = BlackBoxJournal(path, fsync=fsync)
            if keep_steps is not None:
                self.keep_steps = keep_steps
            if self._writer is None:
                self._writer = threading.Thread(
                    target=self._write_loop,
                    name="pilottai-blackbox-writer",
                    daemon=True,
                )
                self._writer.start()
        self._log.info("black-box dumps -> %s", path)
        return self

    def disable(self) -> None:
        self.flush()
        with self._lock:
            if self._journal is not None:
                self._journal.close()
                self._journal = None

    def flush(self, timeout: float = 5.0) -> None:
        """Block until every queued dump has been written (tests; clean
        shutdown). Bounded wait — a wedged disk must not wedge stop()."""
        deadline = time.monotonic() + timeout
        # unfinished_tasks (not empty()): a record mid-write has left the
        # queue but isn't on disk until task_done runs.
        while self._write_q.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)

    def _write_loop(self) -> None:
        while True:
            record = self._write_q.get()
            try:
                with self._lock:
                    journal = self._journal
                if journal is not None:
                    journal.write(record)
            except Exception:  # noqa: BLE001 — writer must survive
                pass
            finally:
                self._write_q.task_done()

    @property
    def enabled(self) -> bool:
        return self._journal is not None

    # ------------------------------------------------------------------ #

    def dump(
        self,
        reason: str,
        trace_id: Optional[str] = None,
        **extra: Any,
    ) -> Optional[Dict[str, Any]]:
        """Capture and persist one dump. Returns the record, or None when
        this (reason, trace_id) was already dumped. Never raises — this
        runs on failure paths that must stay failure paths."""
        try:
            if trace_id is not None:
                # Dedup only trace-carrying dumps (trace-less events
                # like breaker opens are intentionally never deduped),
                # and only within a short horizon.
                key = (reason, trace_id)
                now = time.monotonic()
                with self._lock:
                    last = self._seen.get(key)
                    if last is not None and now - last < self.dedup_window:
                        return None
                    if len(self._seen) > 1024:
                        cutoff = now - self.dedup_window
                        self._seen = {
                            k: t for k, t in self._seen.items()
                            if t > cutoff
                        }
                    self._seen[key] = now
            record: Dict[str, Any] = {
                "ev": "blackbox",
                "ts": time.time(),
                "reason": reason,
                "trace_id": trace_id,
                "steps": global_steps.snapshot(self.keep_steps),
                "spans": (
                    [s.to_dict() for s in global_tracer.for_trace(trace_id)]
                    if trace_id is not None else []
                ),
                "flight": (
                    global_flight.describe(trace_id)
                    if trace_id is not None else None
                ),
                **extra,
            }
            with self._lock:
                self._recent.append(record)
                journal = self._journal
            if journal is not None:
                try:
                    self._write_q.put_nowait(record)
                except queue.Full:
                    # A dump storm outran the disk: the in-memory recent
                    # ring still has the record; count the drop.
                    global_metrics.inc("blackbox.dropped")
            global_metrics.inc("blackbox.dumps")
            global_metrics.inc(f"blackbox.dumps.{reason}")
            self._log.warning(
                "black-box dump: %s trace_id=%s (%d steps captured)",
                reason, trace_id, len(record["steps"]),
            )
            return record
        except Exception as exc:  # noqa: BLE001 — never worsen a failure
            try:
                self._log.error("black-box dump failed: %s", exc)
            except Exception:  # pragma: no cover
                pass
            return None

    def recent(self, n: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            records = list(self._recent)
        return records[-n:] if n is not None else records


global_blackbox = BlackBox()
