"""Deployment cost model: predict serving metrics from a knob vector.

LLM-Pilot (arxiv 2410.02425) characterizes an inference service across
configurations once, fits a predictive model, and answers "which config
meets this SLO cheapest?" without re-benchmarking per deployment. This
module is that loop over OUR knob space: ``bench_slo`` runs produce
sample points — a knob vector (chunk, slots, speculation, kvcache MB,
quant, scheduler, ...) plus the measured outcome (steps/s, TTFT/TPOT
percentiles, attainment, burn) on a tagged workload — and
:class:`CostModel` interpolates over them:

* **exact** on recorded points (a recorded configuration predicts its
  own measurement — anything else would be a model bug), and
* **bounded + monotone between** recorded points: prediction is an
  inverse-distance blend of the two nearest recorded neighbours, so a
  query between two knob vectors lands between their measurements and
  moves monotonically as the query slides from one to the other. No
  fitted curve ever extrapolates outside observed outcomes — a cost
  model that invents throughput cliffs is worse than none.

``recommend()`` scores every *recorded* knob vector for a workload
fingerprint (obs/profile.py) — attainment first, steps/s as tiebreak,
canonical-JSON order as the final deterministic tiebreak — and returns
the winner with predicted-vs-default deltas. Recommendations are always
points the bench actually measured: interpolation ranks, measurement
recommends. ``scripts/recommend.py`` is the CLI over this.

Import cost: stdlib only (the obs constraint — no jax).
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Tuple

_EPS = 1e-12

# The knob space recommendations may range over, with the bounds the CI
# gate enforces: numeric knobs as (lo, hi) inclusive, categorical knobs
# as the allowed value set (mirrors core/config.py validators; upper
# bounds are the widest values any committed bench has exercised).
KNOB_BOUNDS: Dict[str, Any] = {
    "engine_chunk": (1, 512),
    "engine_chunk_policy": ("fixed", "adaptive"),
    "engine_slots": (1, 256),
    "engine_speculate": (0, 8),
    "engine_page_strip": (1, 64),
    "engine_page_size": (8, 1024),
    "engine_overlap_admission": (False, True),
    "engine_kvcache_host_mb": (0, 1 << 20),
    "engine_kvcache_policy": ("cost", "lru"),
    "engine_prefix_cache": (0, 4096),
    "engine_quant": (None, "none", "int8", "int4"),
    "engine_quant_group": (1, 4096),
    "engine_sched_policy": ("fifo", "dag"),
    "engine_pipeline": (1, 8),
}


def validate_knobs(knobs: Dict[str, Any]) -> List[str]:
    """Violation strings for any knob outside :data:`KNOB_BOUNDS`
    (empty list = in-bounds). Unknown knob names are violations too —
    a recommendation must stay inside the modeled space."""
    problems: List[str] = []
    for name, value in sorted(knobs.items()):
        bounds = KNOB_BOUNDS.get(name)
        if bounds is None:
            problems.append(f"{name}: not a modeled knob")
            continue
        if all(isinstance(b, bool) for b in bounds):
            if not isinstance(value, bool):
                problems.append(f"{name}={value!r}: expected bool")
        elif all(isinstance(b, (int, float)) for b in bounds):
            lo, hi = bounds
            if not isinstance(value, (int, float)) or isinstance(value, bool):
                problems.append(f"{name}={value!r}: expected number")
            elif not (lo <= value <= hi):
                problems.append(f"{name}={value!r}: outside [{lo}, {hi}]")
        else:
            if value not in bounds:
                problems.append(f"{name}={value!r}: not in {bounds}")
    return problems


def _canon(knobs: Dict[str, Any]) -> str:
    """Canonical (sorted-JSON) key for a knob vector — the dedup and
    final-tiebreak key, so recommendation order never depends on dict
    insertion order."""
    return json.dumps(knobs, sort_keys=True, default=str)


class CostModel:
    """Interpolating model over recorded ``bench_slo`` sample points."""

    def __init__(self, samples: Optional[List[Dict[str, Any]]] = None) -> None:
        self._samples: List[Dict[str, Any]] = []
        for s in samples or []:
            self.add_sample(
                s.get("knobs", {}), s.get("metrics", {}), s.get("workload")
            )

    def add_sample(
        self,
        knobs: Dict[str, Any],
        metrics: Dict[str, float],
        workload: Optional[str] = None,
    ) -> None:
        self._samples.append({
            "knobs": dict(knobs),
            "metrics": {k: float(v) for k, v in metrics.items()},
            "workload": workload,
            "_key": _canon(knobs),
        })

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "CostModel":
        return cls(samples=list(data.get("samples", [])))

    @classmethod
    def from_json(cls, path: str) -> "CostModel":
        with open(path) as fh:
            return cls.from_dict(json.load(fh))

    def to_dict(self) -> Dict[str, Any]:
        return {
            "samples": [
                {k: v for k, v in s.items() if not k.startswith("_")}
                for s in self._samples
            ]
        }

    @property
    def samples(self) -> List[Dict[str, Any]]:
        return list(self._samples)

    # ------------------------------------------------------------------ #
    # Distance
    # ------------------------------------------------------------------ #

    def _ranges(self, names: List[str]) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for name in names:
            vals = [
                s["knobs"][name] for s in self._samples
                if isinstance(s["knobs"].get(name), (int, float))
                and not isinstance(s["knobs"].get(name), bool)
            ]
            out[name] = (max(vals) - min(vals)) if len(vals) > 1 else 0.0
        return out

    def _distance(
        self,
        a: Dict[str, Any],
        b: Dict[str, Any],
        ranges: Dict[str, float],
    ) -> float:
        names = sorted(set(a) | set(b))
        if not names:
            return 0.0
        total = 0.0
        for name in names:
            va, vb = a.get(name), b.get(name)
            if va is None and vb is None:
                continue
            if va is None or vb is None:
                total += 1.0
            elif (
                isinstance(va, (int, float)) and not isinstance(va, bool)
                and isinstance(vb, (int, float)) and not isinstance(vb, bool)
            ):
                span = ranges.get(name, 0.0)
                if span > _EPS:
                    total += abs(va - vb) / span
                elif abs(va - vb) > _EPS:
                    total += 1.0
            else:
                total += 0.0 if va == vb else 1.0
        return total

    # ------------------------------------------------------------------ #
    # Prediction
    # ------------------------------------------------------------------ #

    def predict(
        self,
        knobs: Dict[str, Any],
        metric: str,
        workload: Optional[str] = None,
    ) -> Optional[float]:
        """Predicted ``metric`` at ``knobs`` (None when no sample has
        the metric). Exact on recorded points; otherwise the inverse-
        distance blend of the TWO nearest recorded neighbours — a convex
        combination, so between two adjacent recorded vectors along one
        knob the prediction slides monotonically from one measurement to
        the other and never leaves the observed range."""
        pool = [s for s in self._samples if metric in s["metrics"]]
        if workload is not None:
            tagged = [s for s in pool if s["workload"] == workload]
            if tagged:
                pool = tagged
        if not pool:
            return None
        key = _canon(knobs)
        exact = [s for s in pool if s["_key"] == key]
        if exact:
            return sum(s["metrics"][metric] for s in exact) / len(exact)
        ranges = self._ranges(
            sorted({n for s in pool for n in s["knobs"]} | set(knobs))
        )
        scored: List[Tuple[float, str, Dict[str, Any]]] = sorted(
            (self._distance(knobs, s["knobs"], ranges), s["_key"], s)
            for s in pool
        )
        nearest = scored[:2]
        weights = [1.0 / max(d, _EPS) ** 2 for d, _, _ in nearest]
        total = sum(weights)
        return sum(
            w * s["metrics"][metric] for w, (_, _, s) in zip(weights, nearest)
        ) / total

    def predict_all(
        self, knobs: Dict[str, Any], workload: Optional[str] = None
    ) -> Dict[str, float]:
        metrics = sorted({m for s in self._samples for m in s["metrics"]})
        out: Dict[str, float] = {}
        for m in metrics:
            got = self.predict(knobs, m, workload=workload)
            if got is not None:
                out[m] = got
        return out

    # ------------------------------------------------------------------ #
    # Recommendation
    # ------------------------------------------------------------------ #

    def _workload_weights(
        self, profile: Optional[Dict[str, Any]]
    ) -> Dict[str, float]:
        """Per-workload weights from a profile fingerprint's workload /
        class mix; uniform when the profile carries neither."""
        mix: Dict[str, float] = {}
        if profile:
            raw = profile.get("workload_mix") or profile.get("class_mix") or {}
            mix = {
                str(k): float(v) for k, v in raw.items() if float(v) > 0.0
            }
        if not mix:
            return {}
        total = sum(mix.values())
        return {k: v / total for k, v in mix.items()}

    def _score(
        self, key: str, weights: Dict[str, float]
    ) -> Tuple[float, float]:
        """(weighted attainment, weighted steps/s) for one recorded knob
        vector across its per-workload samples."""
        mine = [s for s in self._samples if s["_key"] == key]

        def avg(metric: str, subset: List[Dict[str, Any]]) -> float:
            vals = [s["metrics"][metric] for s in subset if metric in s["metrics"]]
            return sum(vals) / len(vals) if vals else 0.0

        if not weights:
            return avg("attainment", mine), avg("steps_per_s", mine)
        att = spd = wsum = 0.0
        for workload, w in sorted(weights.items()):
            subset = [s for s in mine if s["workload"] == workload]
            if not subset:
                subset = mine  # unmodeled workload: fall back to all
            att += w * avg("attainment", subset)
            spd += w * avg("steps_per_s", subset)
            wsum += w
        return (att / wsum, spd / wsum) if wsum else (0.0, 0.0)

    def recommend(
        self,
        profile: Optional[Dict[str, Any]] = None,
        default_knobs: Optional[Dict[str, Any]] = None,
    ) -> Optional[Dict[str, Any]]:
        """Best recorded knob vector for ``profile``. Deterministic:
        attainment desc, steps/s desc, canonical key asc. Returns the
        knobs, their predicted metrics, the default's predictions and
        the deltas (recommended − default); None with no samples."""
        if not self._samples:
            return None
        weights = self._workload_weights(profile)
        keys = sorted({s["_key"] for s in self._samples})
        ranked = sorted(
            keys,
            key=lambda k: (
                tuple(-x for x in self._score(k, weights)), k
            ),
        )
        best_key = ranked[0]
        best_knobs = next(
            dict(s["knobs"]) for s in self._samples if s["_key"] == best_key
        )
        att, spd = self._score(best_key, weights)
        out: Dict[str, Any] = {
            "knobs": best_knobs,
            "score": {"attainment": round(att, 6), "steps_per_s": round(spd, 6)},
            "predicted": {
                k: round(v, 6) for k, v in self.predict_all(best_knobs).items()
            },
        }
        if default_knobs is not None:
            datt, dspd = self._score(_canon(default_knobs), weights)
            default_pred = {
                k: round(v, 6)
                for k, v in self.predict_all(default_knobs).items()
            }
            out["default_knobs"] = dict(default_knobs)
            out["default_predicted"] = default_pred
            out["delta"] = {
                k: round(out["predicted"][k] - default_pred[k], 6)
                for k in out["predicted"]
                if k in default_pred
            }
            out["default_score"] = {
                "attainment": round(datt, 6), "steps_per_s": round(dspd, 6)
            }
        out["violations"] = validate_knobs(best_knobs)
        return out


__all__ = ["CostModel", "KNOB_BOUNDS", "validate_knobs"]
