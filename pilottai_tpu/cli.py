"""Command-line entry point: launch the serving endpoint, list models.

The reference has no CLI at all (its README Quick Start is a Python
snippet, ``/root/reference/README.md:83-100``); an installable serving
framework needs a launchable server. ``pip install pilottai-tpu`` puts
``pilottai-tpu`` on PATH (pyproject ``[project.scripts]``):

    pilottai-tpu serve --model llama3-8b-byte --quantize int8 --port 8000
    pilottai-tpu models
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pilottai-tpu",
        description="TPU-native multi-agent LLM framework",
    )
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("serve", help="serve a model over HTTP (OpenAI wire)")
    s.add_argument("--model", default="llama3-1b-byte",
                   help="registry model name (see `pilottai-tpu models`)")
    s.add_argument("--provider", default="tpu",
                   choices=["tpu", "cpu", "mock"],
                   help="tpu = attached accelerator; cpu = host jax; "
                        "mock = deterministic protocol fake")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--auth-token", default=None,
                   help="require 'Authorization: Bearer <token>' on /v1/*")
    s.add_argument("--checkpoint", default=None,
                   help="HF safetensors directory (random init without)")
    s.add_argument("--tokenizer", default=None,
                   help="local HF tokenizer path (byte tokenizer without)")
    s.add_argument("--quantize", default=None, choices=["int8", "int4"],
                   help="weight-only quantization: int8 fits llama3-8b on "
                        "one 16GB chip; int4 halves the decode weight "
                        "stream again (packed nibbles + group scales)")
    s.add_argument("--quant-group", type=int, default=128,
                   help="int4 scale-group width over the contraction axis")
    s.add_argument("--kv-quantize", default=None, choices=["int8"])
    s.add_argument("--slots", type=int, default=8,
                   help="continuous-batching slots")
    s.add_argument("--max-seq", type=int, default=None,
                   help="KV capacity per slot (>=4096 auto-enables paging)")
    s.add_argument("--speculate", type=int, default=0,
                   help="verify-block width D (0 = off)")
    s.add_argument("--draft-layers", type=int, default=0,
                   help="adaptive shallow-layer drafting (needs --speculate)")
    s.add_argument("--chunk", type=int, default=16,
                   help="decode blocks per dispatch")
    s.add_argument("--agents", type=int, default=0, metavar="N",
                   help="attach a Serve orchestrator with N generic agents "
                        "(enables /v1/tasks incl. SSE task streaming)")
    s.add_argument("--embedder", default=None, metavar="MODEL",
                   help="also serve /v1/embeddings with this encoder model")
    s.add_argument("--embedder-checkpoint", default=None,
                   help="HF safetensors for the embedder (random init "
                        "without — fine for tests, wrong for production)")
    s.add_argument("--embedder-tokenizer", default=None,
                   help="local HF tokenizer path for the embedder")
    s.add_argument("--dashboard-port", type=int, default=None,
                   help="also start the HTML metrics dashboard")

    t = sub.add_parser("train", help="mesh-parallel training run")
    t.add_argument("--model", default="llama-tiny")
    t.add_argument("--steps", type=int, default=100)
    t.add_argument("--batch-size", type=int, default=8)
    t.add_argument("--seq-len", type=int, default=128)
    t.add_argument("--learning-rate", type=float, default=3e-4)
    t.add_argument("--warmup-steps", type=int, default=10)
    t.add_argument("--seed", type=int, default=0)
    t.add_argument("--mesh", default=None, metavar="AXES",
                   help="mesh axes, e.g. 'fsdp=4,model=2' "
                        "(default: auto over all devices)")
    t.add_argument("--context-parallel", action="store_true",
                   help="ring attention over the mesh's seq axis")
    t.add_argument("--data", default=None, metavar="FILE",
                   help="UTF-8 text corpus, byte-tokenized into fixed "
                        "rows (default: deterministic synthetic batches)")
    t.add_argument("--checkpoint-dir", default=None)
    t.add_argument("--save-every", type=int, default=50)
    t.add_argument("--resume", action="store_true",
                   help="restore the latest checkpoint before training")
    t.add_argument("--log-every", type=int, default=10)

    sub.add_parser("models", help="list registry models")
    return p


def _parse_mesh(spec: str | None):
    """'fsdp=4,model=2' → a 4-axis Mesh; None → auto layout."""
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh

    if not spec:
        return create_mesh()
    axes = {}
    for part in spec.split(","):
        k, _, v = part.partition("=")
        if k.strip() not in ("data", "fsdp", "model", "seq"):
            raise SystemExit(
                f"unknown mesh axis {k.strip()!r} "
                "(use data/fsdp/model/seq)"
            )
        try:
            n = int(v)
        except ValueError:
            raise SystemExit(
                f"mesh axis {k.strip()}={v!r} is not an integer"
            ) from None
        if n < 1:
            raise SystemExit(f"mesh axis {k.strip()} must be >= 1, got {n}")
        axes[k.strip()] = n
    return create_mesh(MeshConfig(**axes))


def _text_batches(path: str, vocab_cap: int, batch_size: int, seq_len: int):
    """Byte-tokenized fixed-length rows over a text corpus, cycling."""
    from pathlib import Path

    import numpy as np

    from pilottai_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    ids = np.asarray(
        tok.encode(
            Path(path).read_text(encoding="utf-8", errors="replace"),
            add_bos=False,
        ),
        np.int32,
    ) % vocab_cap
    if len(ids) == 0:
        raise SystemExit(f"empty corpus: {path}")
    if len(ids) < batch_size * seq_len:
        reps = -(-(batch_size * seq_len) // max(len(ids), 1))
        ids = np.tile(ids, reps)
    pos = 0
    while True:
        rows = []
        for _ in range(batch_size):
            if pos + seq_len > len(ids):
                pos = 0
            rows.append(ids[pos: pos + seq_len])
            pos += seq_len
        yield {
            "tokens": np.stack(rows),
            "valid": np.full((batch_size,), seq_len, np.int32),
        }


def run_train(args) -> int:
    """Training entry point: synthetic or text-corpus next-token LM on
    a sharded mesh, with optional checkpoint save/resume."""
    import time

    import jax

    from pilottai_tpu.models.registry import get_model_config
    from pilottai_tpu.train.trainer import (
        TrainConfig,
        Trainer,
        synthetic_batches,
    )

    if args.resume and not args.checkpoint_dir:
        raise SystemExit("--resume requires --checkpoint-dir")
    if args.save_every < 1 or args.log_every < 1:
        raise SystemExit("--save-every and --log-every must be >= 1")
    model_cfg = get_model_config(args.model)
    mesh = _parse_mesh(args.mesh)
    trainer = Trainer(
        model_cfg,
        TrainConfig(
            learning_rate=args.learning_rate,
            warmup_steps=args.warmup_steps,
            total_steps=args.steps,
            context_parallel=args.context_parallel,
        ),
        mesh=mesh,
    )
    print(f"training {args.model} on mesh {dict(mesh.shape)}",
          file=sys.stderr, flush=True)
    state = trainer.init(jax.random.key(args.seed))

    ckpt = None
    start_step = 0
    if args.checkpoint_dir:
        from pilottai_tpu.checkpoint.train_io import TrainCheckpointer

        ckpt = TrainCheckpointer(args.checkpoint_dir)
        if args.resume and ckpt.latest_step() is not None:
            state, start_step = ckpt.restore(state)
            print(f"resumed from step {start_step}", file=sys.stderr)

    batches = (
        _text_batches(args.data, model_cfg.vocab_size,
                      args.batch_size, args.seq_len)
        if args.data
        else synthetic_batches(model_cfg, args.batch_size, args.seq_len,
                               seed=args.seed)
    )
    # Resume fast-forwards the data stream: without this, steps after a
    # restore would re-train on the exact batches steps 0..start_step
    # already consumed and diverge from an uninterrupted run.
    for _ in range(start_step):
        next(batches)
    t0 = time.perf_counter()
    last = None
    last_saved = start_step
    for step in range(start_step, args.steps):
        state, metrics = trainer.step(state, next(batches))
        if (step + 1) % args.log_every == 0 or step + 1 == args.steps:
            loss = float(metrics["loss"])
            rate = (step + 1 - start_step) / (time.perf_counter() - t0)
            print(f"step {step + 1}/{args.steps} loss {loss:.4f} "
                  f"({rate:.2f} steps/s)", flush=True)
            last = loss
        if ckpt is not None and (step + 1) % args.save_every == 0:
            ckpt.save(step + 1, state)
            last_saved = step + 1
    # Final save only when the run actually advanced past the last save
    # (a redundant rewrite is gigabytes of I/O for a sharded model; and
    # resuming with --steps <= the restored step must never relabel the
    # restored weights under a smaller step number).
    if ckpt is not None and start_step < args.steps and last_saved != args.steps:
        ckpt.save(args.steps, state)
    print(f"done; final loss {last}", file=sys.stderr)
    return 0


async def run_serve(args, ready: Optional[asyncio.Event] = None,
                    stop: Optional[asyncio.Event] = None) -> None:
    """Bring up handler (+ optional embedder/dashboard) and serve until
    ``stop`` is set (tests) or forever (CLI, until SIGINT)."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.server import APIServer

    config = LLMConfig(
        model_name=args.model,
        provider=args.provider,
        checkpoint_path=args.checkpoint,
        tokenizer_path=args.tokenizer,
        quantize=args.quantize,
        engine_quant_group=args.quant_group,
        engine_kv_quantize=args.kv_quantize,
        engine_slots=args.slots,
        engine_max_seq=args.max_seq,
        engine_speculate=args.speculate,
        engine_draft_layers=args.draft_layers,
        engine_chunk=args.chunk,
    )
    handler = LLMHandler(config)
    embedder = None
    dashboard = None
    server = None
    serve = None
    # try/finally from the FIRST resource: a bad --checkpoint or a bound
    # --port must not leak the dashboard thread or a half-started engine
    # (and a programmatic caller waiting on ``ready`` gets the exception,
    # not a hang).
    try:
        if args.embedder:
            from pilottai_tpu.engine.tokenizer import load_tokenizer
            from pilottai_tpu.memory.embedder import Embedder

            if not args.embedder_checkpoint:
                print(
                    "warning: --embedder without --embedder-checkpoint "
                    "uses RANDOM weights (test-only embeddings)",
                    file=sys.stderr, flush=True,
                )
            embedder = Embedder(
                model_name=args.embedder,
                checkpoint_path=args.embedder_checkpoint,
                tokenizer=(
                    load_tokenizer(args.embedder_tokenizer)
                    if args.embedder_tokenizer else None
                ),
            )
        if args.dashboard_port is not None:
            from pilottai_tpu.utils.dashboard import MetricsDashboard

            dashboard = MetricsDashboard(
                source=handler, host=args.host, port=args.dashboard_port
            ).start()
        # Compile/load BEFORE accepting traffic, so the first request
        # isn't a minutes-long surprise (the persistent compile cache
        # makes warm boots seconds). Same policy for the embedder: one
        # warmup encode compiles its first length bucket.
        if args.provider != "mock":
            print(f"loading {args.model} ({args.provider})…",
                  file=sys.stderr, flush=True)
            await handler.start()
        if embedder is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, embedder.encode, ["warmup"])
        if args.agents > 0:
            from pilottai_tpu.core.agent import BaseAgent
            from pilottai_tpu.core.config import AgentConfig, ServeConfig
            from pilottai_tpu.serve import Serve

            serve = Serve(
                name="pilottai-tpu",
                manager_llm=handler,
                agents=[
                    BaseAgent(
                        config=AgentConfig(
                            role=f"worker{i}", specializations=["generic"],
                        ),
                        llm=handler,
                    )
                    for i in range(args.agents)
                ],
                config=ServeConfig(max_concurrent_tasks=args.agents),
            )
            await serve.start()
        server = await APIServer(
            handler, serve=serve, embedder=embedder,
            host=args.host, port=args.port, auth_token=args.auth_token,
        ).start()
        print(f"serving {args.model} on http://{args.host}:{server.port}/v1",
              file=sys.stderr, flush=True)
        args._bound_port = server.port  # port 0 resolves here (tests read it)
        if ready is not None:
            ready.set()
        if stop is not None:
            await stop.wait()
        else:
            await asyncio.Event().wait()  # until SIGINT
    finally:
        if server is not None:
            await server.stop()
        if serve is not None:
            await serve.stop()
        if dashboard is not None:
            dashboard.stop()
        await handler.stop()


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "models":
        from pilottai_tpu.models.registry import list_models

        for name in list_models():
            print(name)
        return 0
    if args.command == "train":
        return run_train(args)
    if args.command == "serve":
        try:
            asyncio.run(run_serve(args))
        except KeyboardInterrupt:
            print("shutting down", file=sys.stderr)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
