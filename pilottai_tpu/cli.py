"""Command-line entry point: launch the serving endpoint, list models.

The reference has no CLI at all (its README Quick Start is a Python
snippet, ``/root/reference/README.md:83-100``); an installable serving
framework needs a launchable server. ``pip install pilottai-tpu`` puts
``pilottai-tpu`` on PATH (pyproject ``[project.scripts]``):

    pilottai-tpu serve --model llama3-8b-byte --quantize int8 --port 8000
    pilottai-tpu models
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from typing import List, Optional


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="pilottai-tpu",
        description="TPU-native multi-agent LLM framework",
    )
    sub = p.add_subparsers(dest="command", required=True)

    s = sub.add_parser("serve", help="serve a model over HTTP (OpenAI wire)")
    s.add_argument("--model", default="llama3-1b-byte",
                   help="registry model name (see `pilottai-tpu models`)")
    s.add_argument("--provider", default="tpu",
                   choices=["tpu", "cpu", "mock"],
                   help="tpu = attached accelerator; cpu = host jax; "
                        "mock = deterministic protocol fake")
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--auth-token", default=None,
                   help="require 'Authorization: Bearer <token>' on /v1/*")
    s.add_argument("--checkpoint", default=None,
                   help="HF safetensors directory (random init without)")
    s.add_argument("--tokenizer", default=None,
                   help="local HF tokenizer path (byte tokenizer without)")
    s.add_argument("--quantize", default=None, choices=["int8"],
                   help="weight-only int8 (fits llama3-8b on one 16GB chip)")
    s.add_argument("--kv-quantize", default=None, choices=["int8"])
    s.add_argument("--slots", type=int, default=8,
                   help="continuous-batching slots")
    s.add_argument("--max-seq", type=int, default=None,
                   help="KV capacity per slot (>=4096 auto-enables paging)")
    s.add_argument("--speculate", type=int, default=0,
                   help="verify-block width D (0 = off)")
    s.add_argument("--draft-layers", type=int, default=0,
                   help="adaptive shallow-layer drafting (needs --speculate)")
    s.add_argument("--chunk", type=int, default=16,
                   help="decode blocks per dispatch")
    s.add_argument("--agents", type=int, default=0, metavar="N",
                   help="attach a Serve orchestrator with N generic agents "
                        "(enables /v1/tasks incl. SSE task streaming)")
    s.add_argument("--embedder", default=None, metavar="MODEL",
                   help="also serve /v1/embeddings with this encoder model")
    s.add_argument("--embedder-checkpoint", default=None,
                   help="HF safetensors for the embedder (random init "
                        "without — fine for tests, wrong for production)")
    s.add_argument("--embedder-tokenizer", default=None,
                   help="local HF tokenizer path for the embedder")
    s.add_argument("--dashboard-port", type=int, default=None,
                   help="also start the HTML metrics dashboard")

    sub.add_parser("models", help="list registry models")
    return p


async def run_serve(args, ready: Optional[asyncio.Event] = None,
                    stop: Optional[asyncio.Event] = None) -> None:
    """Bring up handler (+ optional embedder/dashboard) and serve until
    ``stop`` is set (tests) or forever (CLI, until SIGINT)."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.server import APIServer

    config = LLMConfig(
        model_name=args.model,
        provider=args.provider,
        checkpoint_path=args.checkpoint,
        tokenizer_path=args.tokenizer,
        quantize=args.quantize,
        engine_kv_quantize=args.kv_quantize,
        engine_slots=args.slots,
        engine_max_seq=args.max_seq,
        engine_speculate=args.speculate,
        engine_draft_layers=args.draft_layers,
        engine_chunk=args.chunk,
    )
    handler = LLMHandler(config)
    embedder = None
    dashboard = None
    server = None
    serve = None
    # try/finally from the FIRST resource: a bad --checkpoint or a bound
    # --port must not leak the dashboard thread or a half-started engine
    # (and a programmatic caller waiting on ``ready`` gets the exception,
    # not a hang).
    try:
        if args.embedder:
            from pilottai_tpu.engine.tokenizer import load_tokenizer
            from pilottai_tpu.memory.embedder import Embedder

            if not args.embedder_checkpoint:
                print(
                    "warning: --embedder without --embedder-checkpoint "
                    "uses RANDOM weights (test-only embeddings)",
                    file=sys.stderr, flush=True,
                )
            embedder = Embedder(
                model_name=args.embedder,
                checkpoint_path=args.embedder_checkpoint,
                tokenizer=(
                    load_tokenizer(args.embedder_tokenizer)
                    if args.embedder_tokenizer else None
                ),
            )
        if args.dashboard_port is not None:
            from pilottai_tpu.utils.dashboard import MetricsDashboard

            dashboard = MetricsDashboard(
                source=handler, host=args.host, port=args.dashboard_port
            ).start()
        # Compile/load BEFORE accepting traffic, so the first request
        # isn't a minutes-long surprise (the persistent compile cache
        # makes warm boots seconds). Same policy for the embedder: one
        # warmup encode compiles its first length bucket.
        if args.provider != "mock":
            print(f"loading {args.model} ({args.provider})…",
                  file=sys.stderr, flush=True)
            await handler.start()
        if embedder is not None:
            loop = asyncio.get_running_loop()
            await loop.run_in_executor(None, embedder.encode, ["warmup"])
        if args.agents > 0:
            from pilottai_tpu.core.agent import BaseAgent
            from pilottai_tpu.core.config import AgentConfig, ServeConfig
            from pilottai_tpu.serve import Serve

            serve = Serve(
                name="pilottai-tpu",
                manager_llm=handler,
                agents=[
                    BaseAgent(
                        config=AgentConfig(
                            role=f"worker{i}", specializations=["generic"],
                        ),
                        llm=handler,
                    )
                    for i in range(args.agents)
                ],
                config=ServeConfig(max_concurrent_tasks=args.agents),
            )
            await serve.start()
        server = await APIServer(
            handler, serve=serve, embedder=embedder,
            host=args.host, port=args.port, auth_token=args.auth_token,
        ).start()
        print(f"serving {args.model} on http://{args.host}:{server.port}/v1",
              file=sys.stderr, flush=True)
        args._bound_port = server.port  # port 0 resolves here (tests read it)
        if ready is not None:
            ready.set()
        if stop is not None:
            await stop.wait()
        else:
            await asyncio.Event().wait()  # until SIGINT
    finally:
        if server is not None:
            await server.stop()
        if serve is not None:
            await serve.stop()
        if dashboard is not None:
            dashboard.stop()
        await handler.stop()


def main(argv: Optional[List[str]] = None) -> int:
    args = _build_parser().parse_args(argv)
    if args.command == "models":
        from pilottai_tpu.models.registry import list_models

        for name in list_models():
            print(name)
        return 0
    if args.command == "serve":
        try:
            asyncio.run(run_serve(args))
        except KeyboardInterrupt:
            print("shutting down", file=sys.stderr)
        return 0
    return 2


if __name__ == "__main__":
    sys.exit(main())
