"""Task model: the unit of work flowing through the framework.

Reference parity: ``pilott/core/task.py`` (363 LoC) — 8-state ``TaskStatus``
(:11-19), ``TaskPriority`` (:22-26), ``TaskResult`` (:29-66), pydantic
``Task`` (:70-99) with circular-dependency detection (:120-136), lifecycle
mutators (:247-279), ``to_prompt()`` (:352-363) and ``copy()`` for retry
mutation (:306-311).

Deliberate fixes over the reference (SURVEY.md §2.12-h):
  * ``TaskPriority`` is an IntEnum so priority comparisons are numeric, not
    lexicographic on strings (the reference compares string enums at
    ``pilott/pilott.py:253-254``).
  * ``subtasks``/``parent_task_id`` are declared fields (the reference
    writes them undeclared at ``task.py:347-350``).
  * ``required_skills`` is declared (read undeclared at ``task.py:359``).
"""

from __future__ import annotations

import asyncio
import enum
import time
import uuid
from contextlib import asynccontextmanager
from typing import Any, Callable, Dict, List, Optional

from pydantic import BaseModel, ConfigDict, Field, field_validator, model_validator


class TaskStatus(str, enum.Enum):
    """8-state task lifecycle (reference: ``pilott/core/task.py:11-19``)."""

    PENDING = "pending"
    QUEUED = "queued"
    BLOCKED = "blocked"
    IN_PROGRESS = "in_progress"
    RETRYING = "retrying"
    COMPLETED = "completed"
    FAILED = "failed"
    CANCELLED = "cancelled"

    @property
    def is_terminal(self) -> bool:
        return self in (TaskStatus.COMPLETED, TaskStatus.FAILED, TaskStatus.CANCELLED)

    @property
    def is_active(self) -> bool:
        return self in (TaskStatus.IN_PROGRESS, TaskStatus.RETRYING)


class TaskPriority(enum.IntEnum):
    """Numeric task priority — higher is more urgent.

    IntEnum (not str) so ordering and queue eviction compare numerically;
    the reference's string enum compares lexicographically
    (``pilott/pilott.py:253-254``, flagged in SURVEY.md §2.12-h).
    """

    LOW = 0
    NORMAL = 1
    HIGH = 2
    CRITICAL = 3

    @classmethod
    def coerce(cls, value: Any) -> "TaskPriority":
        if isinstance(value, cls):
            return value
        if isinstance(value, str):
            try:
                return cls[value.upper()]
            except KeyError:
                raise ValueError(
                    f"unknown priority {value!r}; expected one of "
                    f"{[m.name.lower() for m in cls]}"
                ) from None
        return cls(int(value))


def _cleanup_files(file_handles: List[Any], temp_files: List[str]) -> List[str]:
    """Close handles and unlink temp files; clears both lists in place and
    returns per-item failure descriptions (shared by Task and TaskResult so
    their error accounting cannot diverge)."""
    import os

    errors: List[str] = []
    for handle in file_handles:
        try:
            handle.close()
        except Exception as exc:  # noqa: BLE001 — best-effort teardown
            errors.append(f"close {handle!r}: {exc}")
    file_handles.clear()
    for path in temp_files:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        except OSError as exc:
            errors.append(f"unlink {path}: {exc}")
    temp_files.clear()
    return errors


class TaskResult(BaseModel):
    """Outcome of one task execution (reference: ``pilott/core/task.py:29-66``).

    Carries OS resources a task's tools may hand over (open file handles,
    temp files) and owns their cleanup: ``cleanup_resources()`` is
    idempotent, runs on ``__del__`` as a last resort, and is invoked by
    ``Task.cleanup_resources()``. Unlike the reference (whose ``except:
    pass`` hides everything), per-item failures are recorded in
    ``metadata["cleanup_errors"]``.
    """

    model_config = ConfigDict(arbitrary_types_allowed=True)

    success: bool
    output: Any = None
    error: Optional[str] = None
    execution_time: float = 0.0
    metadata: Dict[str, Any] = Field(default_factory=dict)
    completed_at: float = Field(default_factory=time.time)
    resources_cleaned: bool = False
    # Excluded from serialization: handles and paths are process-local.
    file_handles: List[Any] = Field(default_factory=list, exclude=True)
    temp_files: List[str] = Field(default_factory=list, exclude=True)

    def register_file_handle(self, handle: Any) -> None:
        if handle is None:
            raise ValueError("file handle must not be None")
        self.file_handles.append(handle)
        self.resources_cleaned = False

    def register_temp_file(self, path: Any) -> None:
        if not path:
            raise ValueError("temp file path must not be empty")
        self.temp_files.append(str(path))
        self.resources_cleaned = False

    def cleanup_resources(self) -> None:
        """Close registered handles and unlink temp files (idempotent)."""
        errors = _cleanup_files(self.file_handles, self.temp_files)
        if errors:
            self.metadata.setdefault("cleanup_errors", []).extend(errors)
        self.resources_cleaned = True

    def __del__(self) -> None:  # pragma: no cover — GC-timing dependent
        try:
            if not self.resources_cleaned and (
                self.file_handles or self.temp_files
            ):
                self.cleanup_resources()
        except Exception:  # noqa: BLE001 — never raise from a finalizer
            pass

    def to_dict(self) -> Dict[str, Any]:
        return self.model_dump()


class Task(BaseModel):
    """A unit of work with lifecycle, dependencies, retries and deadlines.

    Reference: ``pilott/core/task.py:70-99``. ``context`` holds parent/related
    tasks for prompt rendering; ``dependencies`` are task ids that must be
    COMPLETED before this task may run (enforced by the agent at validation
    time, reference ``core/agent.py:231-246``).
    """

    model_config = ConfigDict(arbitrary_types_allowed=True, validate_assignment=True)

    id: str = Field(default_factory=lambda: str(uuid.uuid4()))
    type: str = "generic"
    description: str
    priority: TaskPriority = TaskPriority.NORMAL
    status: TaskStatus = TaskStatus.PENDING

    # Routing / execution hints
    agent_id: Optional[str] = None
    required_capabilities: List[str] = Field(default_factory=list)
    required_skills: List[str] = Field(default_factory=list)
    tools: List[str] = Field(default_factory=list)
    complexity: int = Field(default=1, ge=1, le=10)

    # Scheduling
    max_retries: int = 3
    retry_count: int = 0
    timeout: float = Field(default=300.0, gt=0)
    deadline: Optional[float] = None  # absolute unix timestamp

    # Structure
    dependencies: List[str] = Field(default_factory=list)
    parent_task_id: Optional[str] = None
    subtasks: List[str] = Field(default_factory=list)
    context: Dict[str, Any] = Field(default_factory=dict)
    payload: Dict[str, Any] = Field(default_factory=dict)

    # Bookkeeping
    created_at: float = Field(default_factory=time.time)
    started_at: Optional[float] = None
    completed_at: Optional[float] = None
    result: Optional[TaskResult] = None
    error_history: List[str] = Field(default_factory=list)
    metadata: Dict[str, Any] = Field(default_factory=dict)

    # Resource management (reference ``core/task.py:94,172-202``: the
    # reference declares output_file + handle/temp-file sets but never
    # writes the output; here completion actually persists it).
    output_file: Optional[str] = None
    file_handles: List[Any] = Field(default_factory=list, exclude=True)
    temp_files: List[str] = Field(default_factory=list, exclude=True)

    # ------------------------------------------------------------------ #
    # Validation
    # ------------------------------------------------------------------ #

    @field_validator("priority", mode="before")
    @classmethod
    def _coerce_priority(cls, v: Any) -> TaskPriority:
        return TaskPriority.coerce(v)

    @model_validator(mode="after")
    def _deadline_after_creation(self) -> "Task":
        # Reference: deadline validator at ``core/task.py:216-221``. Compared
        # against created_at (not wall-clock now) so model_dump() round-trips
        # and clone_for_retry() of an already-expired task keep working.
        if self.deadline is not None and self.deadline <= self.created_at:
            raise ValueError("deadline must be after task creation time")
        return self

    @field_validator("output_file")
    @classmethod
    def _output_file_writable_target(cls, v: Optional[str]) -> Optional[str]:
        # Reference validator (``core/task.py:223-231``): an existing
        # path that is not a regular file (directory, socket) can never
        # receive the output — reject at construction.
        if v is None:
            return None
        import os

        if os.path.exists(v) and not os.path.isfile(v):
            raise ValueError(f"output_file {v!r} exists and is not a file")
        return v

    @model_validator(mode="after")
    def _no_self_dependency(self) -> "Task":
        # Reference runs a circular-dependency check on construction
        # (``core/task.py:120-136``); with id-based deps only direct
        # self-reference is checkable here — graph cycles are checked by
        # ``detect_cycle`` below against a task registry.
        if self.id in self.dependencies:
            raise ValueError(f"task {self.id} depends on itself")
        return self

    @staticmethod
    def detect_cycle(tasks: Dict[str, "Task"]) -> Optional[List[str]]:
        """Return a dependency cycle among ``tasks`` if one exists.

        Iterative DFS with coloring over the dependency graph (replaces the
        reference's construction-time recursive check, ``task.py:120-136``,
        which could not see the full graph). Iterative so 1000+-deep chains
        (ServeConfig.max_queue_size scale) don't hit the recursion limit.
        """
        WHITE, GRAY, BLACK = 0, 1, 2
        color = {tid: WHITE for tid in tasks}

        for root in tasks:
            if color[root] != WHITE:
                continue
            path: List[str] = []
            # Stack of (task_id, iterator over its deps)
            stack = [(root, iter(tasks[root].dependencies))]
            color[root] = GRAY
            path.append(root)
            while stack:
                tid, deps = stack[-1]
                advanced = False
                for dep in deps:
                    if dep not in tasks:
                        continue
                    if color[dep] == GRAY:
                        return path[path.index(dep):] + [dep]
                    if color[dep] == WHITE:
                        color[dep] = GRAY
                        path.append(dep)
                        stack.append((dep, iter(tasks[dep].dependencies)))
                        advanced = True
                        break
                if not advanced:
                    color[tid] = BLACK
                    path.pop()
                    stack.pop()
        return None

    # ------------------------------------------------------------------ #
    # Lifecycle mutators (reference: ``core/task.py:247-279,334-345``)
    # ------------------------------------------------------------------ #

    def mark_queued(self) -> None:
        self.status = TaskStatus.QUEUED

    def mark_started(self, agent_id: Optional[str] = None) -> None:
        self.status = TaskStatus.IN_PROGRESS
        self.started_at = time.time()
        if agent_id is not None:
            self.agent_id = agent_id

    def mark_completed(self, result: TaskResult) -> None:
        self.status = TaskStatus.COMPLETED
        self.completed_at = time.time()
        self.result = result
        if self.output_file:
            self._write_output(result)

    def _write_output(self, result: TaskResult) -> None:
        """Persist the completed output to ``output_file`` (JSON for
        structured outputs, text otherwise). Failure to write is recorded
        on the result, never raised — completion already happened."""
        import json as _json

        try:
            out = result.output
            text = (
                out if isinstance(out, str)
                else _json.dumps(out, indent=2, default=repr)
            )
            with open(self.output_file, "w", encoding="utf-8") as f:
                f.write(text if text is not None else "")
        except (OSError, ValueError, TypeError) as exc:
            # ValueError covers json circular refs and surrogate encode
            # errors from write(); completion already happened, so record
            # instead of raising out of mark_completed.
            result.metadata.setdefault("cleanup_errors", []).append(
                f"write {self.output_file}: {exc}"
            )

    def register_file_handle(self, handle: Any) -> None:
        if handle is None:
            raise ValueError("file handle must not be None")
        self.file_handles.append(handle)

    def register_temp_file(self, path: Any) -> None:
        if not path:
            raise ValueError("temp file path must not be empty")
        self.temp_files.append(str(path))

    def cleanup_resources(self) -> None:
        """Close registered handles, remove temp files, and cascade to the
        result (reference ``core/task.py:172-202``). Idempotent."""
        errors = _cleanup_files(self.file_handles, self.temp_files)
        if errors:
            self.metadata.setdefault("cleanup_errors", []).extend(errors)
        if self.result is not None:
            self.result.cleanup_resources()

    def mark_failed(self, error: str, result: Optional[TaskResult] = None) -> None:
        self.status = TaskStatus.FAILED
        self.completed_at = time.time()
        self.error_history.append(error)
        self.result = result or TaskResult(success=False, error=error)

    def mark_cancelled(self) -> None:
        self.status = TaskStatus.CANCELLED
        self.completed_at = time.time()

    def prepare_retry(self) -> bool:
        """Transition to RETRYING if budget remains; returns whether allowed.

        Reference: retry bookkeeping at ``core/task.py:268-279`` and the
        orchestrator retry path ``pilott/pilott.py:538-551``.
        """
        if self.retry_count >= self.max_retries:
            return False
        self.retry_count += 1
        self.status = TaskStatus.RETRYING
        self.started_at = None
        self.completed_at = None
        self.result = None
        return True

    @property
    def is_expired(self) -> bool:
        if self.deadline is not None and time.time() > self.deadline:
            return True
        if (
            self.started_at is not None
            and self.status.is_active
            and time.time() - self.started_at > self.timeout
        ):
            return True
        return False

    @property
    def execution_time(self) -> Optional[float]:
        if self.started_at is None:
            return None
        end = self.completed_at or time.time()
        return end - self.started_at

    # ------------------------------------------------------------------ #
    # Prompt rendering (reference: ``core/task.py:352-363``)
    # ------------------------------------------------------------------ #

    def to_prompt(self) -> str:
        """Render the task as context for an LLM prompt."""
        lines = [
            f"Task ID: {self.id}",
            f"Type: {self.type}",
            f"Description: {self.description}",
            f"Priority: {self.priority.name}",
        ]
        if self.required_capabilities:
            lines.append("Required capabilities: " + ", ".join(self.required_capabilities))
        if self.required_skills:
            lines.append("Required skills: " + ", ".join(self.required_skills))
        if self.tools:
            lines.append("Available tools: " + ", ".join(self.tools))
        if self.payload:
            lines.append(f"Payload: {self.payload}")
        if self.context:
            lines.append(f"Context: {self.context}")
        return "\n".join(lines)

    def clone_for_retry(self) -> "Task":
        """A fresh copy for retry-with-mutation (reference ``task.py:306-311``)."""
        data = self.model_dump()
        data.update(
            id=str(uuid.uuid4()),
            status=TaskStatus.PENDING,
            started_at=None,
            completed_at=None,
            result=None,
            metadata={**self.metadata, "retry_of": self.id},
        )
        return Task(**data)


class ResourceLockRegistry:
    """Per-resource asyncio locks with a context-manager interface.

    Reference: ``pilott/core/task.py:138-170`` attaches per-resource locks to
    each Task; here they are a shared registry so two tasks touching the same
    named resource actually serialize.
    """

    def __init__(self) -> None:
        self._locks: Dict[str, asyncio.Lock] = {}

    def get(self, resource: str) -> asyncio.Lock:
        if resource not in self._locks:
            self._locks[resource] = asyncio.Lock()
        return self._locks[resource]

    @asynccontextmanager
    async def acquire(self, *resources: str):
        """Acquire several resource locks in sorted order (deadlock-free).

        The sorted-order discipline mirrors the reference's tool-lock
        acquisition (``core/agent.py:181-185``).
        """
        ordered = sorted(set(resources))
        acquired: List[asyncio.Lock] = []
        try:
            for name in ordered:
                lock = self.get(name)
                await lock.acquire()
                acquired.append(lock)
            yield
        finally:
            for lock in reversed(acquired):
                lock.release()


TaskCallback = Callable[[Task], Any]
