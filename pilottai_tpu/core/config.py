"""Unified configuration layer — one pydantic model per subsystem.

Reference parity: ``pilott/core/config.py`` (SecureConfig/LLMConfig/
LogConfig/AgentConfig), ``pilott/pilott.py:17-27`` (ServeConfig),
``pilott/core/router.py:15-20`` (RouterConfig),
``pilott/orchestration/load_balancer.py:22-30`` (LoadBalancerConfig),
``pilott/orchestration/orchestration.py:19-28`` (ScalingConfig),
``pilott/orchestration/scaling.py:49-58`` (FaultToleranceConfig).

The reference ships TWO incompatible ``AgentConfig`` classes
(SURVEY.md §2.12-c); here there is exactly one, carrying the union of the
fields actually read anywhere in the reference tree.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from pathlib import Path
from typing import Any, ClassVar, Dict, List, Literal, Optional

from pydantic import BaseModel, Field, SecretStr, field_validator

from pilottai_tpu.core.status import AgentRole

Provider = Literal["tpu", "cpu", "mock"]


class SecureConfig:
    """Symmetric encryption helper for sensitive config values.

    Reference: ``pilott/core/config.py:10-39`` (Fernet). The cryptography
    dependency is optional here; without it the helpers raise cleanly
    instead of breaking import of the whole config layer.
    """

    def __init__(self, key: Optional[bytes] = None) -> None:
        try:
            from cryptography.fernet import Fernet
        except ImportError as exc:  # pragma: no cover - env dependent
            raise RuntimeError("cryptography is not installed") from exc
        self._fernet = Fernet(key or Fernet.generate_key())

    @staticmethod
    def generate_key() -> bytes:
        from cryptography.fernet import Fernet

        return Fernet.generate_key()

    def encrypt(self, value: str) -> str:
        return self._fernet.encrypt(value.encode()).decode()

    def decrypt(self, token: str) -> str:
        return self._fernet.decrypt(token.encode()).decode()


class SamplingConfig(BaseModel):
    """Decode-time sampling parameters (engine surface, no reference analog —
    the reference forwards temperature/max_tokens to remote APIs,
    ``pilott/engine/llm.py:49``)."""

    temperature: float = Field(default=0.7, ge=0.0)
    top_k: int = Field(default=0, ge=0)  # 0 = disabled
    top_p: float = Field(default=1.0, gt=0.0, le=1.0)
    max_new_tokens: int = Field(default=256, ge=1)
    seed: Optional[int] = None
    json_mode: bool = False  # grammar-constrained JSON decoding


class ReliabilityConfig(BaseModel):
    """Overload, deadline and failure-handling knobs (reliability/ — no
    reference analog: the reference has no admission control at all).

    Semantics are documented in docs/SERVING.md "Overload & failure
    semantics": queue-depth shedding → 429, breaker open → 503, deadline
    exceeded → 408.
    """

    # Engine admission control: submits beyond this many queued-but-not-
    # admitted requests are rejected (EngineOverloaded → HTTP 429).
    # None = unbounded (the seed behavior).
    max_queue_depth: Optional[int] = Field(default=None, ge=1)
    # Per-request deadline defaults at the HTTP edge. Clients set
    # ``timeout`` in the body or an ``x-request-timeout`` header;
    # ``default_timeout`` applies when they don't (None = no deadline),
    # and ``max_timeout`` caps whatever they ask for.
    default_timeout: Optional[float] = Field(default=None, gt=0)
    max_timeout: float = Field(default=600.0, gt=0)
    # Retry backoff shaping (engine/handler.py): capped exponential with
    # jitter — synchronized retry herds re-break a recovering backend.
    retry_max_delay: float = Field(default=30.0, ge=0)
    retry_jitter: bool = True
    # Circuit breaker over engine calls (reliability/breaker.py).
    breaker_enabled: bool = True
    breaker_failure_threshold: int = Field(default=5, ge=1)
    breaker_recovery_timeout: float = Field(default=30.0, gt=0)
    breaker_half_open_max: int = Field(default=1, ge=1)
    # In-flight request recovery (engine/batcher.py): on a device/reader
    # failure each occupied slot's progress (prompt + accepted tokens)
    # re-admits through the normal admission path after the device-state
    # rebuild instead of failing the request — greedy output stays
    # byte-identical across a mid-decode crash. Attempts are bounded per
    # request; exhausting them fails with the original exception.
    # 0 disables (the pre-0.10 fail-all behavior).
    recovery_max_attempts: int = Field(default=2, ge=0)
    # Device watchdog (reliability/watchdog.py): declare the engine
    # stalled when fold/prefill heartbeats go stale this many seconds
    # with work in flight — a hung dispatch becomes a 503 with
    # diagnostics instead of silent client hangs. Must exceed the
    # slowest healthy dispatch (warmup compiles are excluded). None
    # disables.
    watchdog_stall_s: Optional[float] = Field(default=None, gt=0)
    # Degradation ladder (reliability/degrade.py): this many faults
    # inside the rolling window step capability down one rung
    # (drafting → chunk size → slots → batch-class shed); a clean
    # promote-window soak steps back up.
    degrade_enabled: bool = True
    degrade_fault_threshold: int = Field(default=3, ge=1)
    degrade_window_s: float = Field(default=30.0, gt=0)
    degrade_promote_s: float = Field(default=60.0, gt=0)
    # Per-SLO-class shedding: non-interactive (batch) requests shed at
    # this fraction of max_queue_depth, so backlog pressure sheds the
    # traffic nobody is watching before the traffic someone is.
    batch_shed_frac: float = Field(default=0.5, gt=0, le=1.0)


class LLMConfig(BaseModel):
    """LLM engine configuration (reference: ``pilott/core/config.py:41-77``).

    ``provider`` selects an in-tree backend instead of a remote API:
    ``"tpu"`` (JAX engine on TPU), ``"cpu"`` (same engine on host JAX),
    ``"mock"`` (deterministic scripted backend for tests — the first-class
    test fixture SURVEY.md §4 calls for).
    """

    model_name: str = "llama3-8b"
    provider: Provider = "mock"
    api_key: Optional[SecretStr] = None  # kept for config-file parity; unused by in-tree providers
    checkpoint_path: Optional[str] = None
    tokenizer_path: Optional[str] = None

    sampling: SamplingConfig = Field(default_factory=SamplingConfig)
    function_calling: bool = True

    # Client-side throttling (reference: max_rpm limiter ``engine/llm.py:68-89``,
    # Semaphore(5) concurrency cap ``engine/llm.py:36``).
    max_rpm: Optional[int] = None
    max_concurrent_requests: int = Field(default=64, ge=1)
    retries: int = Field(default=3, ge=0)
    retry_delay: float = Field(default=1.0, ge=0)
    timeout: float = Field(default=120.0, gt=0)

    # Engine placement / serving shape
    mesh_shape: Optional[Dict[str, int]] = None  # e.g. {"data": 1, "model": 8}
    # Degraded-mesh ladder (parallel/meshplan.py): the ordered list of
    # mesh plans the engine may re-plan onto when a shard is lost
    # mid-serving. "auto" derives a halving ladder from the boot plan
    # (parallel axes halve first, model last, down to single-chip);
    # "off" disables shard-loss re-planning (a lost device fails over
    # PR 8's generic recovery path instead); an explicit list of plan
    # dicts (e.g. [{"model": 4, "data": 2}, {"model": 4}, {"model": 2}])
    # pins the rungs — every rung must fit the boot device set.
    engine_mesh_ladder: Any = "auto"
    dtype: str = "bfloat16"
    # Weight-only quantization for serving — legacy spelling, kept as an
    # alias for ``engine_quant`` ("int8"/"int4" or None). Shrinks the
    # per-token HBM weight stream that bounds decode (models/quant.py).
    quantize: Optional[str] = None
    # Weight quantization mode ("none" | "int8" | "int4"; None = follow
    # the ``quantize`` alias above). int8 halves the decode weight
    # stream with per-output-channel scales; int4 halves it AGAIN with
    # packed nibbles + per-group scales (``engine_quant_group``), with
    # quantization-sensitive fallbacks: lm_head stays int8, the MoE
    # router stays dense. Greedy output of the packed path is
    # byte-identical to an unpacked int4-dequant reference
    # (tests/test_quant_parity.py).
    engine_quant: Optional[str] = None

    @field_validator("quantize")
    @classmethod
    def _valid_quantize(cls, v: Optional[str]) -> Optional[str]:
        # Same value set as engine_quant — the fields are aliases.
        if v not in (None, "none", "int8", "int4"):
            raise ValueError(
                f"unknown quantize mode {v!r}; "
                "supported: 'none', 'int8', 'int4'"
            )
        return v

    @field_validator("engine_quant")
    @classmethod
    def _valid_engine_quant(cls, v: Optional[str]) -> Optional[str]:
        if v not in (None, "none", "int8", "int4"):
            raise ValueError(
                "engine_quant must be 'none', 'int8' or 'int4'"
            )
        return v

    @field_validator("engine_mesh_ladder")
    @classmethod
    def _valid_mesh_ladder(cls, v: Any) -> Any:
        if isinstance(v, str):
            if v not in ("auto", "off"):
                raise ValueError(
                    "engine_mesh_ladder must be 'auto', 'off' or a "
                    "list of mesh-plan dicts"
                )
            return v
        if isinstance(v, (list, tuple)):
            for plan in v:
                if not isinstance(plan, dict) or not all(
                    isinstance(a, str)
                    and isinstance(n, int) and n >= 1
                    for a, n in plan.items()
                ):
                    raise ValueError(
                        "engine_mesh_ladder rungs must be dicts of "
                        "axis name -> positive int, e.g. "
                        "[{'model': 4, 'data': 2}, {'model': 2}]"
                    )
            return list(v)
        raise ValueError(
            "engine_mesh_ladder must be 'auto', 'off' or a list of "
            "mesh-plan dicts"
        )
    # int4 scale-group width over the contraction axis (rows per shared
    # scale). Smaller groups bound quantization error tighter at
    # 4/group extra bits per weight; 128 is the standard trade. Also
    # part of the page-strip autotune key — a winner timed under one
    # quantization shape is never silently reused under another.
    engine_quant_group: int = Field(default=128, ge=1)
    # Fused decode epilogue (engine/decode.py:fused_greedy_epilogue):
    # when every occupied slot is greedy (temperature 0) and
    # unconstrained (no JSON/schema grammar), the logits projection and
    # sampling fuse into one vocab-tiled argmax — the [B, V] fp32
    # logits never round-trip HBM and the sampler's full-vocab sort
    # masks are skipped. Byte-identical on/off (the non-fusable shapes
    # — JSON/schema decoding, sampled slots — take the unfused path per
    # dispatch automatically).
    engine_fused_epilogue: bool = True
    engine_slots: int = Field(default=8, ge=1)       # continuous-batching slots
    # Admission group width: prompts prefilled per fused admission
    # dispatch (padded to this, so compile variants stay bounded). A full
    # 32-slot wave admits in ceil(32/width) dispatches.
    engine_admit_batch: int = Field(default=8, ge=1)
    engine_max_seq: Optional[int] = None             # KV length cap (default model max)
    engine_chunk: int = Field(default=16, ge=1)      # decode tokens per dispatch
    # Chunk-length scheduling (engine/batcher.py:_pick_chunk_blocks):
    # "adaptive" sizes each decode dispatch from the live slots'
    # remaining-token budgets, deadline budgets and the speculation
    # acceptance EMA, quantized to engine_chunk_buckets — finished slots
    # fold (and release their pages) at the earliest useful boundary
    # instead of riding out the straggler's full chunk. "fixed" restores
    # the constant engine_chunk dispatch. Greedy output is byte-identical
    # either way (tests/test_adaptive_chunk.py).
    engine_chunk_policy: str = Field(default="adaptive")
    # Adaptive dispatch sizes (blocks). None = a quartile ladder of
    # engine_chunk ({4, 8, 12, 16} at the default 16). The ladder is the
    # compile-cache bound: one decode executable per bucket per
    # prefix-bound rung, all compiled at warmup.
    engine_chunk_buckets: Optional[List[int]] = None

    @field_validator("engine_chunk_policy")
    @classmethod
    def _valid_chunk_policy(cls, v: str) -> str:
        if v not in ("fixed", "adaptive"):
            raise ValueError(
                "engine_chunk_policy must be 'fixed' or 'adaptive'"
            )
        return v
    # Decode dispatch pipeline depth: chunks in flight before the device
    # thread blocks on the reader. Each extra level hides one
    # host↔device round trip behind compute — the lever when the chip
    # sits behind a high-latency tunnel; early-exit chunks keep
    # over-dispatched levels nearly free (a chunk whose slots are all
    # done retires without running a weight pass). Every level carries
    # its own dispatch-time D2H copy, so any depth ≥ 1 pipelines.
    engine_pipeline: int = Field(default=2, ge=1)
    # Overlapped admission (engine/batcher.py:_prep_loop): admission
    # prep — slot selection, page allocation, prefix matching, staging-
    # buffer packing — runs on a dedicated prep thread, and the device
    # thread only enqueues the prebuilt prefill behind in-flight decode
    # chunks. Greedy output is byte-identical on/off
    # (tests/test_overlap_admission.py); False restores the inline path.
    engine_overlap_admission: bool = True
    # Paged KV cache (ops/paged.py): None = auto (paged when the per-slot
    # capacity is ≥ 4096 — that is where dense slots × max_seq reservation
    # stops fitting HBM). Pool size in pages; None = the HBM a dense
    # min(max_seq, 2048) cache would use.
    engine_paged_kv: Optional[bool] = None
    engine_kv_pages: Optional[int] = None
    engine_page_size: int = Field(default=128, ge=8)
    # Pages per paged-attention grid cell (the strip width of
    # ops/pallas/paged_attention.py). The long-context decode path is
    # grid-cell-latency bound (round-5 page A/B: 64→268, 128→243,
    # 256→309 device ms/step — a per-cell launch/index floor), so wider
    # strips amortize the per-cell overhead. None = autotune over
    # {1, 2, 4, 8} at warmup on TPU (result cached alongside the compile
    # cache); an explicit int forces it.
    engine_page_strip: Optional[int] = Field(default=None, ge=1)
    # Speculative decoding: verify-blocks of N tokens per weight pass via
    # n-gram self-drafting (0 = off; >= 2 enables; dense KV only). Decode
    # is weight-stream-bound, so accepted drafts are nearly free tokens
    # (engine/decode.py:decode_chunk_spec).
    engine_speculate: int = Field(default=0, ge=0)
    # Automatic prefix caching: keep the K/V of the last N admitted
    # prompt prefixes on device; repeated/shared prefixes skip their
    # prefill FLOPs (engine/prefix_cache.py). 0 disables; dense KV only.
    # Entry HBM cost: 2 (K and V) x L x K x bucket(len, cap 1024) x H x
    # itemsize — ~67 MB for llama3-8b bf16 at bucket 512.
    engine_prefix_cache: int = Field(default=4, ge=0)
    # Global KV cache tier (engine/kvcache/): host-RAM cold-tier budget
    # in MB. Evicted prefix KV (dense panel entries, paged chain pages)
    # spills to pinned host buffers via async D2H instead of being
    # dropped; a session resume or repeated preamble restores via async
    # H2D instead of re-prefilling. 0 disables the cold tier (evictions
    # discard KV — the pre-tier behavior). Greedy output is
    # byte-identical on/off (tests/test_kvcache.py).
    engine_kvcache_host_mb: int = Field(default=0, ge=0)
    # Tier eviction policy ("cost" | "lru"): "cost" scores entries by
    # recency x reconstruction cost (prefill FLOPs saved per byte held),
    # so densely packed preambles outlive equally old mostly-padding
    # entries; "lru" is plain recency. Applies to the device-resident
    # dense store and the host tier.
    engine_kvcache_policy: str = Field(default="cost")

    @field_validator("engine_kvcache_policy")
    @classmethod
    def _valid_kvcache_policy(cls, v: str) -> str:
        if v not in ("cost", "lru"):
            raise ValueError(
                "engine_kvcache_policy must be 'cost' or 'lru'"
            )
        return v
    # DAG-aware admission scheduling (pilottai_tpu/sched/ +
    # engine/batcher.py, ROADMAP item 4). "dag" orders the admission
    # backlog by request priority (Task.priority threads the full
    # lattice through GenerationParams.priority), groups gang-tagged
    # fan-out siblings, and ages waiting work one rung per
    # engine_priority_aging_s so nothing starves; "fifo" is the seed's
    # submission order. Greedy output is byte-identical either way
    # (tests/test_sched.py).
    engine_sched_policy: str = Field(default="dag")

    @field_validator("engine_sched_policy")
    @classmethod
    def _valid_sched_policy(cls, v: str) -> str:
        if v not in ("fifo", "dag"):
            raise ValueError(
                "engine_sched_policy must be 'fifo' or 'dag'"
            )
        return v
    # Gang admission wait bound (ms): how long an incomplete gang — or
    # one the free slots+pages can't take whole — may defer behind
    # other work before it admits partially anyway.
    engine_gang_wait_ms: float = Field(default=50.0, ge=0)
    # Aging floor: seconds of backlog wait per promoted priority rung
    # (LOW reaches CRITICAL after 3x this and can never starve under
    # sustained critical-path load). 0 disables aging.
    engine_priority_aging_s: float = Field(default=2.0, ge=0)
    # Speculative stage pre-warm depth: how many tokens of a predicted
    # next-stage prompt prefix the scheduler may ask the engine to
    # pre-warm (KV cache tier restore staged on the prep thread — the
    # next hop's prefill finds device-resident KV). 0 detaches the
    # engine from the scheduler's pre-warm loop entirely.
    engine_prewarm_depth: int = Field(default=512, ge=0)
    # Dense prefix-store entry floor in tokens (None = the prefill
    # bucket floor, 64 by default): prompts at or below it never cache
    # — the engine warns ONCE when such a prompt is seen instead of
    # missing silently (engine/prefix_cache.py).
    engine_prefix_min_len: Optional[int] = Field(default=None, ge=1)
    # Adaptive draft-model speculation: >0 enables shallow-layer
    # self-drafting (the target's own first N layers + unembed propose
    # drafts — LayerSkip-style, no second checkpoint, no extra HBM) for
    # slots whose n-gram acceptance collapses on novel text
    # (engine/decode.py:_model_drafts). Requires engine_speculate >= 2.
    engine_draft_layers: int = Field(default=0, ge=0)
    # Chunked prefill: long cold prompts admit in page-aligned segments
    # of this many tokens, one per device-loop cycle, so live slots'
    # decode chunks interleave with the prefill instead of stalling
    # behind it (paged KV only). None = auto (1024 when paged); 0 = off.
    engine_prefill_chunk: Optional[int] = None
    # int8 KV cache ("int8" or None): panels stored int8 with symmetric
    # per-token-per-head scales (ops/kvcache.py:quantize_kv). Doubles
    # resident context per HBM GB everywhere; the decode-bandwidth win
    # (int8-sized cache reads) is realized on the paged-Pallas path,
    # where dequant happens in-VMEM — XLA paths may materialize
    # dequantized panels once per chunk. ~1e-3 relative attention error;
    # composes with paged KV, speculation and prefix caching.
    engine_kv_quantize: Optional[str] = None
    # Persistent XLA compilation cache (utils/compile_cache.py): None =
    # enabled at the default dir (PILOTTAI_COMPILE_CACHE env or
    # ~/.cache/pilottai_tpu/xla); "off" disables; else the directory.
    # Warm restarts (FaultTolerance respawns, worker redeploys) reuse
    # compiled programs instead of paying minutes of recompilation.
    engine_compile_cache: Optional[str] = None
    # Disaggregated prefill/decode serving (distributed/cell.py, ISSUE
    # 19): per-tier replica counts as "<P>p<D>d" (e.g. "1p2d" = one
    # prefill-tier replica, two decode-tier replicas; replicas past
    # P+D stay "mixed"). A ServingCell built over handlers with this
    # config splits its replicas into tiers and moves freshly prefilled
    # requests to the decode tier via the KV handoff path. None (the
    # default) keeps every replica "mixed" — the colocated topology, an
    # exact no-op on routing and output.
    cell_disagg: Optional[str] = None

    @field_validator("cell_disagg")
    @classmethod
    def _valid_cell_disagg(cls, v: Optional[str]) -> Optional[str]:
        if v is None:
            return v
        import re

        spec = v.strip().lower()
        m = re.fullmatch(r"(\d+)p\+?(\d+)d", spec)
        if not m or int(m.group(1)) + int(m.group(2)) < 1:
            raise ValueError(
                "cell_disagg must be '<P>p<D>d' (e.g. '1p2d'); "
                f"got {v!r}"
            )
        return spec
    seed: int = 0                                    # param init seed when no checkpoint
    # Deadlines, shedding, breaker (reliability/): defaults keep the seed
    # behavior except the breaker, which only changes anything once the
    # backend fails 5 times in a row.
    reliability: ReliabilityConfig = Field(default_factory=ReliabilityConfig)


class LogConfig(BaseModel):
    """Logging configuration (reference: ``pilott/core/config.py:80-100``)."""

    level: str = "INFO"
    log_to_file: bool = False
    log_dir: str = "logs"
    json_format: bool = True
    rotate_max_bytes: int = 10 * 1024 * 1024
    rotate_backups: int = 5

    @field_validator("level")
    @classmethod
    def _valid_level(cls, v: str) -> str:
        allowed = {"DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL"}
        v = v.upper()
        if v not in allowed:
            raise ValueError(f"log level must be one of {sorted(allowed)}")
        return v


class AgentConfig(BaseModel):
    """The single, unified agent configuration.

    Union of the fields read anywhere in the reference: identity/prompting
    (``core/config.py:103-125``), feature flags (``:127-134``), resource
    limits (``:137-151``), plus the minimal class's fields
    (``core/agent.py:19-29``).
    """

    role: str = "worker"
    role_type: AgentRole = AgentRole.WORKER
    goal: str = "complete assigned tasks accurately"
    description: str = ""
    backstory: str = ""

    knowledge_sources: List[str] = Field(default_factory=list)
    tools: List[str] = Field(default_factory=list)
    required_capabilities: List[str] = Field(default_factory=list)
    specializations: List[str] = Field(default_factory=list)

    # Reasoning loop bounds (reference: max_iterations=20 ``core/config.py:128``)
    max_iterations: int = Field(default=20, ge=1)
    max_rpm: Optional[int] = None
    retry_limit: int = Field(default=2, ge=0)
    code_execution_mode: Literal["safe", "restricted", "unrestricted"] = "safe"

    # Feature flags (reference ``core/config.py:130-134``)
    memory_enabled: bool = True
    delegation_enabled: bool = False
    caching_enabled: bool = True
    code_execution_enabled: bool = False
    verbose: bool = False

    # Resource limits (reference ``core/config.py:137-151``)
    max_child_agents: int = Field(default=10, ge=0)
    max_queue_size: int = Field(default=100, ge=1)
    max_task_complexity: int = Field(default=5, ge=1, le=10)
    delegation_threshold: float = Field(default=0.7, ge=0.0, le=1.0)
    max_concurrent_tasks: int = Field(default=5, ge=1)
    task_timeout: float = Field(default=300.0, gt=0)

    llm: Optional[LLMConfig] = None
    log: LogConfig = Field(default_factory=LogConfig)

    # ---------------- persistence (reference ``core/config.py:198-249``) --- #

    SENSITIVE_KEYS: ClassVar[tuple] = ("api_key", "secret", "password", "token")

    def has_sensitive_data(self) -> bool:
        def scan(obj: Any) -> bool:
            if isinstance(obj, dict):
                return any(
                    any(s in str(k).lower() for s in self.SENSITIVE_KEYS) and v
                    or scan(v)
                    for k, v in obj.items()
                )
            if isinstance(obj, list):
                return any(scan(x) for x in obj)
            return False

        return scan(self.model_dump())

    def save(self, path: str | Path) -> None:
        """Atomic JSON save with backup-and-restore semantics.

        SecretStr fields are revealed on disk (pydantic would otherwise
        serialize the mask ``**********`` and destroy the key on round-trip);
        callers holding secrets should prefer env vars or ``SecureConfig``.
        """
        path = Path(path)
        data = self.model_dump(mode="json")
        if self.llm is not None and self.llm.api_key is not None:
            data["llm"]["api_key"] = self.llm.api_key.get_secret_value()
        backup = path.with_suffix(path.suffix + ".bak")
        if path.exists():
            shutil.copy2(path, backup)
        tmp = path.with_suffix(path.suffix + f".tmp{os.getpid()}")
        try:
            tmp.write_text(json.dumps(data, indent=2))
            tmp.replace(path)
        except Exception:
            if backup.exists():
                shutil.copy2(backup, path)
            raise
        finally:
            tmp.unlink(missing_ok=True)

    @classmethod
    def load(cls, path: str | Path) -> "AgentConfig":
        return cls(**json.loads(Path(path).read_text()))


class ServeConfig(BaseModel):
    """Orchestrator configuration (reference: ``pilott/pilott.py:17-27``)."""

    name: str = "pilott-tpu"
    max_concurrent_tasks: int = Field(default=5, ge=1)
    task_timeout: float = Field(default=300.0, gt=0)
    max_queue_size: int = Field(default=1000, ge=1)
    cleanup_interval: float = Field(default=3600.0, gt=0)
    task_retention: float = Field(default=86400.0, gt=0)
    max_retry_attempts: int = Field(default=3, ge=0)
    decomposition_enabled: bool = True
    evaluation_enabled: bool = True
    # Integrated side services (the reference never wires these into
    # Serve.start(), SURVEY.md §3.1 — here they are part of one lifecycle).
    load_balancing_enabled: bool = False
    dynamic_scaling_enabled: bool = False
    fault_tolerance_enabled: bool = False
    # Manager-side delegation (delegation/delegator.py): when enabled and a
    # manager agent with children is attached, tasks route through
    # TaskDelegator.evaluate_delegation BEFORE the router (reference
    # ``delegation/task_delegator.py:41-111`` — never wired there).
    delegation_enabled: bool = False
    # Durable task journal (checkpoint/journal.py; SURVEY.md §5.4 — the
    # reference loses all queue state on crash/preemption).
    journal_path: Optional[str] = None
    journal_fsync: bool = False
    journal_recover: bool = True  # replay the journal on start()


class RouterConfig(BaseModel):
    """Task router configuration (reference: ``pilott/core/router.py:15-20``)."""

    load_check_interval: float = Field(default=5.0, ge=0)  # score cache TTL (0 = no caching)
    load_threshold: float = Field(default=0.8, ge=0.0, le=1.0)
    route_timeout: float = Field(default=30.0, gt=0)
    route_attempts: int = Field(default=3, ge=1)
    retry_backoff: float = Field(default=1.0, ge=0)


class LoadBalancerConfig(BaseModel):
    """Reference: ``pilott/orchestration/load_balancer.py:22-30``."""

    check_interval: float = Field(default=30.0, gt=0)
    overload_threshold: float = Field(default=0.8, ge=0.0, le=1.0)
    underload_threshold: float = Field(default=0.2, ge=0.0, le=1.0)
    max_tasks_per_cycle: int = Field(default=3, ge=1)
    task_move_timeout: float = Field(default=30.0, gt=0)
    trend_window: int = Field(default=5, ge=1)


class ScalingConfig(BaseModel):
    """Reference: ``pilott/orchestration/orchestration.py:19-28``."""

    check_interval: float = Field(default=60.0, gt=0)
    scale_up_threshold: float = Field(default=0.8, ge=0.0, le=1.0)
    scale_down_threshold: float = Field(default=0.3, ge=0.0, le=1.0)
    min_agents: int = Field(default=2, ge=0)
    max_agents: int = Field(default=10, ge=1)
    cooldown: float = Field(default=300.0, ge=0)
    trend_window: int = Field(default=5, ge=1)
    # Normalizer for the engine admission-queue signal when the engine
    # runs without a shed limit (engine.max_queue_depth gauge absent):
    # this many queued-not-admitted requests read as 100% queue pressure.
    queue_depth_ref: int = Field(default=64, ge=1)
    # Predictive autoscaling (obs/forecast.py): when the seasonal
    # arrival forecaster has a full period of history, the load signal
    # is boosted by forecast(now + forecast_lead_s) / current rate —
    # capacity moves BEFORE the predicted ramp arrives instead of after
    # burn rate crosses 1. Boost-only (a predicted lull never shrinks
    # early) and capped at forecast_boost_cap so a cold forecaster or a
    # spiky trace can't slam the pool to max. No-op until the forecaster
    # is ready, so enabling it is safe on day one.
    forecast_enabled: bool = True
    forecast_lead_s: float = Field(default=120.0, ge=0)
    forecast_boost_cap: float = Field(default=2.0, ge=1.0)


class FaultToleranceConfig(BaseModel):
    """Reference: ``pilott/orchestration/scaling.py:49-58``."""

    check_interval: float = Field(default=30.0, gt=0)
    heartbeat_timeout: float = Field(default=60.0, gt=0)
    max_recovery_attempts: int = Field(default=3, ge=0)
    recovery_cooldown: float = Field(default=300.0, ge=0)
    resource_threshold: float = Field(default=0.9, ge=0.0, le=1.0)
    stuck_task_timeout: float = Field(default=1800.0, gt=0)
    error_threshold: int = Field(default=5, ge=1)


def utcnow() -> float:
    return time.time()
