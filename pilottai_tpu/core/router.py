"""TaskRouter: score-based task→agent routing.

Reference parity: ``pilott/core/router.py`` — ``route_task`` with lock,
timeout, attempts + backoff (``:34-62``); cached per-agent scores
(``:64-88``: 0.4·suitability + 0.3·(1−load) + 0.2·specialization +
0.1·success_rate, cache TTL = load_check_interval); load penalty weights
(``:103``); static ``get_task_priority`` (``:135-145``). The vestigial
second TaskDelegator in the reference's router (``:148-193``, §2.12-f) has
exactly one home here: ``pilottai_tpu/delegation``.
"""

from __future__ import annotations

import asyncio
import time
from typing import Dict, List, Optional, Tuple

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import RouterConfig
from pilottai_tpu.core.task import Task, TaskPriority
from pilottai_tpu.utils.logging import get_logger


class TaskRouter:
    """Routes tasks to the best available agent by composite score."""

    def __init__(self, config: Optional[RouterConfig] = None) -> None:
        self.config = config or RouterConfig()
        self._score_cache: Dict[Tuple[str, str], Tuple[float, float]] = {}
        self._lock = asyncio.Lock()
        self._log = get_logger("router")

    # ------------------------------------------------------------------ #

    def _load_penalty(self, agent: BaseAgent) -> float:
        """0-1 penalty: queue-dominated composite (reference ``:103`` mixes
        queue 0.5 / cpu 0.3 / mem 0.2; engine queue metrics replace host
        probes here)."""
        return min(1.0, 0.7 * agent.queue_utilization + 0.3 * agent.load)

    def _score(self, agent: BaseAgent, task: Task) -> float:
        cache_key = (agent.id, task.type)
        now = time.monotonic()
        hit = self._score_cache.get(cache_key)
        if hit is not None and now - hit[1] < self.config.load_check_interval:
            return hit[0]
        suitability = agent.evaluate_task_suitability(task)
        specialization = 1.0 if task.type in agent.config.specializations else 0.0
        score = (
            0.4 * suitability
            + 0.3 * (1.0 - self._load_penalty(agent))
            + 0.2 * specialization
            + 0.1 * agent.success_rate
        )
        self._score_cache[cache_key] = (score, now)
        return score

    def invalidate(self, agent_id: Optional[str] = None) -> None:
        if agent_id is None:
            self._score_cache.clear()
        else:
            self._score_cache = {
                k: v for k, v in self._score_cache.items() if k[0] != agent_id
            }

    # ------------------------------------------------------------------ #

    async def route_task(
        self, task: Task, agents: List[BaseAgent]
    ) -> Optional[BaseAgent]:
        """Pick the best agent; retries with backoff when none available."""
        for attempt in range(self.config.route_attempts):
            async with self._lock:
                available = [
                    a for a in agents
                    if a.status.is_available
                    and a.queue_utilization < self.config.load_threshold
                ]
                if available:
                    best = max(available, key=lambda a: self._score(a, task))
                    # Drop the winner's cached score: its load just
                    # changed by this very dispatch, and serving it from
                    # the TTL cache piles whole bursts onto one agent
                    # while its peers idle.
                    self._score_cache.pop((best.id, task.type), None)
                    self._log.debug(
                        "routed task %s -> agent %s", task.id[:8], best.id[:8]
                    )
                    return best
            if attempt < self.config.route_attempts - 1:
                await asyncio.sleep(self.config.retry_backoff * (attempt + 1))
        return None

    # ------------------------------------------------------------------ #

    @staticmethod
    def get_task_priority(task: Task) -> TaskPriority:
        """Urgency heuristic (reference ``:135-145``): deadline pressure,
        complexity and fan-in raise priority."""
        score = 0
        if task.deadline is not None and task.deadline - time.time() < 300:
            score += 2
        if task.complexity >= 7:
            score += 1
        if len(task.dependencies) >= 3:
            score += 1
        if score >= 3:
            return TaskPriority.CRITICAL
        if score == 2:
            return TaskPriority.HIGH
        if score == 1:
            return TaskPriority.NORMAL
        return task.priority
