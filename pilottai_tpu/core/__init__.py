"""Core runtime: tasks, configs, agents, factory, router, short-term memory.

Reference parity: ``pilott/core/__init__.py:1-21`` re-exports the same
surface. Unlike the reference there is exactly ONE ``AgentConfig``
(the reference ships two incompatible ones, SURVEY.md §2.12-c).
"""

from pilottai_tpu.core.task import Task, TaskPriority, TaskResult, TaskStatus
from pilottai_tpu.core.status import AgentRole, AgentStatus
from pilottai_tpu.core.config import (
    AgentConfig,
    LLMConfig,
    LogConfig,
    RouterConfig,
    ServeConfig,
)

__all__ = [
    "Task",
    "TaskPriority",
    "TaskResult",
    "TaskStatus",
    "AgentRole",
    "AgentStatus",
    "AgentConfig",
    "LLMConfig",
    "LogConfig",
    "RouterConfig",
    "ServeConfig",
]
