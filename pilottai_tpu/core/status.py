"""Agent status and role enums.

Reference parity: ``pilott/core/status.py`` / ``pilott/core/role.py``
(AgentStatus used at ``pilott/core/agent.py:435-444``; AgentRole used by
the factory and router).
"""

from __future__ import annotations

import enum


class AgentStatus(str, enum.Enum):
    """Lifecycle status of an agent."""

    CREATED = "created"
    STARTING = "starting"
    IDLE = "idle"
    BUSY = "busy"
    PAUSED = "paused"
    RECOVERING = "recovering"
    STOPPING = "stopping"
    STOPPED = "stopped"
    ERROR = "error"

    @property
    def is_available(self) -> bool:
        """Whether the agent can accept new tasks in this state."""
        return self in (AgentStatus.IDLE, AgentStatus.BUSY)

    @property
    def is_terminal(self) -> bool:
        return self in (AgentStatus.STOPPED, AgentStatus.ERROR)


class HealthStatus(str, enum.Enum):
    """4-level agent health classification used by fault tolerance.

    Reference parity: ``pilott/orchestration/scaling.py:209-228``.
    """

    HEALTHY = "healthy"
    DEGRADED = "degraded"
    UNHEALTHY = "unhealthy"
    CRITICAL = "critical"


class AgentRole(str, enum.Enum):
    """Role of an agent in the hierarchy."""

    WORKER = "worker"
    MANAGER = "manager"
    ORCHESTRATOR = "orchestrator"
    RESEARCHER = "researcher"
    PROCESSOR = "processor"
    EVALUATOR = "evaluator"
    GENERATOR = "generator"
    EXTRACTOR = "extractor"

    @property
    def is_manager(self) -> bool:
        return self in (AgentRole.MANAGER, AgentRole.ORCHESTRATOR)
