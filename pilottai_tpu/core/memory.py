"""Short-term memory: a bounded, tag- and time-indexed record store.

Reference parity: ``pilott/core/memory.py`` (133 LoC) — ``MemoryEntry``
(:9-13), bounded deque store (:23), tag index + bisect timestamp index
(:26-27,51), ``store``/``retrieve``/``retrieve_by_timerange`` (:34-88),
bounded context/pattern dicts (:90-107). Used by Serve for task-execution
records (``pilott/pilott.py:96,653-666``).

Fix over the reference (SURVEY.md §2.12-h): the reference's ``tag_index``
stores positional indices into a bounded deque, so indices drift after
eviction. Here entries carry stable ids and indexes map tag → id set, with
eviction removing ids from every index.
"""

from __future__ import annotations

import asyncio
import bisect
import itertools
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set

from pilottai_tpu.obs.dag import global_dag


@dataclass
class MemoryEntry:
    """One record (reference: ``core/memory.py:9-13``)."""

    data: Any
    tags: Set[str] = field(default_factory=set)
    priority: int = 0
    timestamp: float = field(default_factory=time.time)
    entry_id: int = 0

    def to_dict(self) -> Dict[str, Any]:
        return {
            "id": self.entry_id,
            "data": self.data,
            "tags": sorted(self.tags),
            "priority": self.priority,
            "timestamp": self.timestamp,
        }


class Memory:
    """Bounded short-term memory with tag and time-range retrieval."""

    def __init__(self, max_entries: int = 1000, max_context: int = 100, max_patterns: int = 50) -> None:
        self.max_entries = max_entries
        self._entries: "OrderedDict[int, MemoryEntry]" = OrderedDict()
        self._tag_index: Dict[str, Set[int]] = {}
        self._time_index: List[tuple] = []  # sorted [(timestamp, id)]
        self._ids = itertools.count()
        self._lock = asyncio.Lock()
        # Bounded auxiliary stores (reference ``core/memory.py:90-107``).
        self.context: "OrderedDict[str, Any]" = OrderedDict()
        self.patterns: "OrderedDict[str, Any]" = OrderedDict()
        self.max_context = max_context
        self.max_patterns = max_patterns

    # ------------------------------------------------------------------ #

    async def store(
        self,
        data: Any,
        tags: Optional[Set[str]] = None,
        priority: int = 0,
        timestamp: Optional[float] = None,
    ) -> int:
        """Store a record; returns its stable entry id."""
        # Memory-op node in the ambient task's DAG (no-op outside one):
        # store/lookup latency becomes task.memory_s.
        with global_dag.recorded("memory", "store"):
            return await self._store_inner(data, tags, priority, timestamp)

    async def _store_inner(
        self,
        data: Any,
        tags: Optional[Set[str]] = None,
        priority: int = 0,
        timestamp: Optional[float] = None,
    ) -> int:
        async with self._lock:
            entry = MemoryEntry(
                data=data,
                tags=set(tags or ()),
                priority=priority,
                timestamp=timestamp if timestamp is not None else time.time(),
                entry_id=next(self._ids),
            )
            self._entries[entry.entry_id] = entry
            for tag in entry.tags:
                self._tag_index.setdefault(tag, set()).add(entry.entry_id)
            bisect.insort(self._time_index, (entry.timestamp, entry.entry_id))
            while len(self._entries) > self.max_entries:
                self._evict_oldest()
            return entry.entry_id

    def _evict_oldest(self) -> None:
        old_id, old = self._entries.popitem(last=False)
        for tag in old.tags:
            ids = self._tag_index.get(tag)
            if ids:
                ids.discard(old_id)
                if not ids:
                    del self._tag_index[tag]
        idx = bisect.bisect_left(self._time_index, (old.timestamp, old_id))
        if idx < len(self._time_index) and self._time_index[idx] == (old.timestamp, old_id):
            del self._time_index[idx]

    # ------------------------------------------------------------------ #

    async def retrieve(
        self,
        tags: Optional[Set[str]] = None,
        min_priority: Optional[int] = None,
        limit: int = 50,
        predicate: Optional[Any] = None,
    ) -> List[MemoryEntry]:
        """Filter-match retrieval, newest first (reference ``:53-76``)."""
        with global_dag.recorded("memory", "retrieve"):
            return await self._retrieve_inner(
                tags, min_priority, limit, predicate
            )

    async def _retrieve_inner(
        self,
        tags: Optional[Set[str]] = None,
        min_priority: Optional[int] = None,
        limit: int = 50,
        predicate: Optional[Any] = None,
    ) -> List[MemoryEntry]:
        async with self._lock:
            if tags:
                id_sets = [self._tag_index.get(t, set()) for t in tags]
                candidate_ids: Set[int] = set.intersection(*id_sets) if id_sets else set()
                candidates = [self._entries[i] for i in candidate_ids if i in self._entries]
            else:
                candidates = list(self._entries.values())
            if min_priority is not None:
                candidates = [e for e in candidates if e.priority >= min_priority]
            if predicate is not None:
                candidates = [e for e in candidates if predicate(e)]
            candidates.sort(key=lambda e: e.timestamp, reverse=True)
            return candidates[:limit]

    async def retrieve_by_timerange(self, start: float, end: float) -> List[MemoryEntry]:
        """Binary-search range query (reference ``:78-88``)."""
        async with self._lock:
            lo = bisect.bisect_left(self._time_index, (start, -1))
            hi = bisect.bisect_right(self._time_index, (end, float("inf")))
            return [
                self._entries[eid]
                for _, eid in self._time_index[lo:hi]
                if eid in self._entries
            ]

    # ------------------------------------------------------------------ #

    def set_context(self, key: str, value: Any) -> None:
        self.context[key] = value
        self.context.move_to_end(key)
        while len(self.context) > self.max_context:
            self.context.popitem(last=False)

    def set_pattern(self, key: str, value: Any) -> None:
        self.patterns[key] = value
        self.patterns.move_to_end(key)
        while len(self.patterns) > self.max_patterns:
            self.patterns.popitem(last=False)

    async def cleanup(self, max_age: Optional[float] = None) -> int:
        """Drop entries older than ``max_age`` seconds; returns count dropped."""
        if max_age is None:
            return 0
        cutoff = time.time() - max_age
        async with self._lock:
            stale = [eid for eid, e in self._entries.items() if e.timestamp < cutoff]
            for eid in stale:
                entry = self._entries.pop(eid)
                for tag in entry.tags:
                    ids = self._tag_index.get(tag)
                    if ids:
                        ids.discard(eid)
                        if not ids:
                            del self._tag_index[tag]
            self._time_index = [(t, i) for (t, i) in self._time_index if i in self._entries]
            return len(stale)

    def __len__(self) -> int:
        return len(self._entries)

    def get_metrics(self) -> Dict[str, Any]:
        return {
            "entries": len(self._entries),
            "tags": len(self._tag_index),
            "context_keys": len(self.context),
            "patterns": len(self.patterns),
        }
