"""BaseAgent: the LLM-driven agent runtime.

Reference parity: ``pilott/core/agent.py`` (627 LoC) — reasoning loop
``execute_task`` → validate deps → analyze (LLM) → select tools (LLM) →
sorted tool-lock acquisition → bounded plan/act step loop (LLM per step) →
evaluate (LLM) (``:131-371``); health/metrics/suitability surface
(``:217-229,535-575``); manager hooks (``:592-628``); system prompt from
role/goal/backstory (``:373-387``).

Deliberate fixes over the reference:
  * parent/child hierarchy is REAL — ``child_agents``/``add_child_agent``
    are implied everywhere in the reference and defined nowhere
    (SURVEY.md §2.12-b);
  * ``send_heartbeat`` exists (called but undefined at
    ``orchestration/scaling.py:232``, §2.12-h);
  * one tolerant JSON parser for all LLM responses (the reference's agent
    used strict ``json.loads``, §3.4);
  * load/utilization come from queue depth and engine metrics, not a
    blocking ``psutil.cpu_percent(interval=1)`` (§2.12-h).
"""

from __future__ import annotations

import asyncio
import time
import uuid
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.status import AgentStatus
from pilottai_tpu.core.task import Task, TaskPriority, TaskResult, TaskStatus
from pilottai_tpu.obs.dag import global_dag, global_occupancy
from pilottai_tpu.prompts.manager import PromptManager
from pilottai_tpu.prompts.schemas import schema_for
from pilottai_tpu.sched import global_scheduler
from pilottai_tpu.tools.tool import Tool, ToolRegistry
from pilottai_tpu.utils.json_utils import coerce_bool, extract_json
from pilottai_tpu.utils.logging import get_logger
from pilottai_tpu.utils.metrics import global_metrics
from pilottai_tpu.utils.tracing import global_tracer

StepCallback = Callable[[str, Dict[str, Any]], Any]


class AgentTaskQueue:
    """Bounded task queue supporting O(1) removal without ghost slots.

    ``asyncio.Queue`` can't remove items, so a detached (rebalanced) task
    would keep occupying a slot and distort capacity checks. Here capacity
    counts LIVE tasks only: the deque holds ids, the dict holds the truth,
    and consumers skip ids whose task was removed.
    """

    def __init__(self, maxsize: int) -> None:
        self.maxsize = maxsize
        self._order: deque = deque()
        self._live: Dict[str, Task] = {}
        self._event = asyncio.Event()

    def qsize(self) -> int:
        return len(self._live)

    def empty(self) -> bool:
        return not self._live

    def put_nowait(self, task: Task) -> None:
        if len(self._live) >= self.maxsize:
            raise asyncio.QueueFull(f"agent queue at capacity {self.maxsize}")
        self._live[task.id] = task
        self._order.append(task.id)
        self._event.set()

    def remove(self, task_id: str) -> Optional[Task]:
        """Detach a queued task; its id in the deque becomes a skipped ghost
        but no longer counts toward capacity."""
        return self._live.pop(task_id, None)

    def get_nowait(self) -> Task:
        while self._order:
            task = self._live.pop(self._order.popleft(), None)
            if task is not None:
                return task
        raise asyncio.QueueEmpty()

    async def get(self, timeout: Optional[float] = None) -> Optional[Task]:
        while True:
            try:
                return self.get_nowait()
            except asyncio.QueueEmpty:
                pass
            self._event.clear()
            try:
                await asyncio.wait_for(self._event.wait(), timeout=timeout)
            except asyncio.TimeoutError:
                return None

    def values(self) -> List[Task]:
        return list(self._live.values())


class BaseAgent:
    """An autonomous agent executing tasks through an LLM reasoning loop."""

    def __init__(
        self,
        config: Optional[AgentConfig] = None,
        llm: Optional[Any] = None,  # LLMHandler
        tools: Optional[ToolRegistry | List[Tool]] = None,
        memory: Optional[Any] = None,          # EnhancedMemory (optional)
        knowledge: Optional[Any] = None,       # KnowledgeManager (optional)
        agent_id: Optional[str] = None,
        prompt_manager: Optional[PromptManager] = None,
        step_callback: Optional[StepCallback] = None,
        dependency_resolver: Optional[Callable[[str], Optional[Task]]] = None,
    ) -> None:
        self.config = config or AgentConfig()
        if llm is None:
            raise ValueError(
                "BaseAgent requires an llm handle (LLMHandler); use "
                "LLMConfig(provider='mock') for tests"
            )  # reference enforces the same at core/agent.py:77
        if isinstance(llm, (LLMConfig, dict)):
            from pilottai_tpu.engine.handler import LLMHandler

            llm = LLMHandler(llm)
        self.llm = llm
        self.id = agent_id or str(uuid.uuid4())
        self.role = self.config.role
        self.status = AgentStatus.CREATED
        self.tools = (
            tools if isinstance(tools, ToolRegistry) else ToolRegistry(tools or [])
        )
        self._own_registry = not isinstance(tools, ToolRegistry)
        self.memory = memory
        self.knowledge = knowledge
        # Framework-level grounding (VERDICT r4 #5): attached stores are
        # usable without hand-built tools — the reasoning loop gets
        # memory_search/knowledge_query tools and retrieved context.
        self._register_grounding_tools()
        self.prompts = prompt_manager or PromptManager("agent")
        self.step_callback = step_callback
        self.dependency_resolver = dependency_resolver

        # Hierarchy (fix for SURVEY §2.12-b).
        self.parent: Optional["BaseAgent"] = None
        self.child_agents: Dict[str, "BaseAgent"] = {}

        # Queues / history / metrics.
        self.task_queue = AgentTaskQueue(self.config.max_queue_size)
        self.current_tasks: Dict[str, Task] = {}
        self.conversation_history: deque = deque(maxlen=100)
        self.task_history: deque = deque(maxlen=1000)
        self.task_metrics: Dict[str, int] = {
            "completed": 0, "failed": 0, "retried": 0,
        }
        self._execution_locks: Dict[str, asyncio.Lock] = {}
        self._total_exec_time = 0.0
        self._last_heartbeat = time.time()
        self._error_count = 0
        self._worker_task: Optional[asyncio.Task] = None
        self._log = get_logger("agent", agent_id=self.id[:8], role=self.role)

    # ------------------------------------------------------------------ #
    # Grounding (VERDICT r4 #5: memory/knowledge were dead parameters —
    # stored but never consulted by the loop; the reference's were too)
    # ------------------------------------------------------------------ #

    def _register_grounding_tools(self) -> None:
        """Auto-register ``memory_search``/``knowledge_query`` tools for
        attached stores (same shape the document-pipeline example used to
        hand-build). A user tool with the same name wins — this never
        overwrites. A caller-SUPPLIED registry is never mutated: two
        agents sharing one registry must not end up with a tool closure
        bound to whichever agent constructed first — the registry is
        copied per-agent before any grounding tool is added (the Tool
        objects themselves stay shared)."""
        wants_memory = (
            self.memory is not None
            and self.config.memory_enabled
            and hasattr(self.memory, "semantic_search")
            and "memory_search" not in self.tools
        )
        wants_knowledge = (
            self.knowledge is not None
            and hasattr(self.knowledge, "query_knowledge")
            and "knowledge_query" not in self.tools
        )
        if (wants_memory or wants_knowledge) and not self._own_registry:
            self.tools = ToolRegistry(self.tools.subset(self.tools.names()))
            self._own_registry = True
        if (
            self.memory is not None
            and self.config.memory_enabled
            and hasattr(self.memory, "semantic_search")
            and "memory_search" not in self.tools
        ):
            async def memory_search(
                query: Optional[str] = None, k: int = 3
            ) -> List[str]:
                items = await self.memory.semantic_search(
                    query or "", limit=int(k)
                )
                return [str(i.get("text", "")) for i in items]

            self.tools.register(Tool(
                name="memory_search",
                function=memory_search,
                description="Search the agent's semantic memory",
                parameters={"properties": {
                    "query": {"type": "string"}, "k": {"type": "integer"},
                }},
            ))
        if (
            self.knowledge is not None
            and hasattr(self.knowledge, "query_knowledge")
            and "knowledge_query" not in self.tools
        ):
            async def knowledge_query(
                query: Optional[str] = None, k: int = 3
            ) -> List[Any]:
                rows = await self.knowledge.query_knowledge(query or "")
                return list(rows)[: int(k)]

            self.tools.register(Tool(
                name="knowledge_query",
                function=knowledge_query,
                description="Query the attached knowledge sources",
                parameters={"properties": {
                    "query": {"type": "string"}, "k": {"type": "integer"},
                }},
            ))

    async def _grounding_context(self, task: Task) -> List[str]:
        """Top-k memory context for step planning (best-effort)."""
        if (
            self.memory is None
            or not self.config.memory_enabled
            or not hasattr(self.memory, "semantic_search")
        ):
            return []
        try:
            items = await self.memory.semantic_search(task.description, limit=3)
        except Exception:  # noqa: BLE001 — grounding must never fail a task
            return []
        return [
            str(i.get("text", ""))[:160] for i in items if i.get("text")
        ]

    # ------------------------------------------------------------------ #
    # Hierarchy (reference: implied at scaling.py:149, load_balancer.py:223,
    # delegation/task_delegator.py:311 — never implemented there)
    # ------------------------------------------------------------------ #

    def add_child_agent(self, agent: "BaseAgent") -> None:
        if len(self.child_agents) >= self.config.max_child_agents:
            raise RuntimeError(
                f"agent {self.id[:8]} at max_child_agents="
                f"{self.config.max_child_agents}"
            )
        if agent.id in self.child_agents:
            raise ValueError(f"agent {agent.id} is already a child")
        if agent is self or self._is_ancestor(agent):
            raise ValueError("hierarchy cycles are not allowed")
        agent.parent = self
        self.child_agents[agent.id] = agent

    def remove_child_agent(self, agent_id: str) -> Optional["BaseAgent"]:
        agent = self.child_agents.pop(agent_id, None)
        if agent is not None:
            agent.parent = None
        return agent

    def _is_ancestor(self, candidate: "BaseAgent") -> bool:
        node = self.parent
        while node is not None:
            if node is candidate:
                return True
            node = node.parent
        return False

    def descendants(self) -> List["BaseAgent"]:
        out: List[BaseAgent] = []
        stack = list(self.child_agents.values())
        while stack:
            node = stack.pop()
            out.append(node)
            stack.extend(node.child_agents.values())
        return out

    # ------------------------------------------------------------------ #
    # Lifecycle (reference ``core/agent.py:435-444,577-590``)
    # ------------------------------------------------------------------ #

    async def start(self) -> None:
        # Role occupancy gauges (agent.<role>.busy_frac / .queue_depth)
        # exist from the first start — registration is idempotent and
        # restart-safe (fault-tolerance recovery stop→start cycles).
        global_occupancy.register(self.role, self.id)
        if self.status.is_available:
            return
        self.status = AgentStatus.STARTING
        if self.llm is not None and hasattr(self.llm, "start"):
            await self.llm.start()
        self.status = AgentStatus.IDLE
        self._last_heartbeat = time.time()
        self._log.info("agent started")

    async def stop(self) -> None:
        self.status = AgentStatus.STOPPING
        if self._worker_task is not None:
            self._worker_task.cancel()
            self._worker_task = None
        self.status = AgentStatus.STOPPED
        # Leave the role's occupancy denominator (start() re-registers
        # on a recovery restart) — a retired agent counted forever would
        # bias agent.<role>.busy_frac low after every replacement.
        global_occupancy.unregister(self.role, self.id)
        self._log.info("agent stopped")

    async def reset(self) -> None:
        """Drop queued work and error state; keep history (reference ``:577``)."""
        while not self.task_queue.empty():
            try:
                task = self.task_queue.get_nowait()
                task.mark_cancelled()
            except asyncio.QueueEmpty:
                break
        self.current_tasks.clear()
        self._error_count = 0
        self.status = AgentStatus.IDLE
        self._last_heartbeat = time.time()

    async def pause(self) -> None:
        self.status = AgentStatus.PAUSED

    async def resume(self) -> None:
        if self.status == AgentStatus.PAUSED:
            self.status = AgentStatus.IDLE

    def send_heartbeat(self) -> float:
        """Liveness signal for FaultTolerance (defined here; the reference
        calls it but never defines it — SURVEY §2.12-h)."""
        self._last_heartbeat = time.time()
        return self._last_heartbeat

    # ------------------------------------------------------------------ #
    # Queue surface (used by router / balancer / scaler)
    # ------------------------------------------------------------------ #

    def _report_queue_depth(self) -> None:
        global_occupancy.set_queue_depth(
            self.role, self.task_queue.qsize() + len(self.current_tasks)
        )

    async def add_task(self, task: Task) -> None:
        """Non-blocking enqueue: raises asyncio.QueueFull when at capacity
        (callers — router, balancer, fault tolerance — must handle refusal,
        never hang on a saturated agent)."""
        if self.status == AgentStatus.STOPPED:
            raise RuntimeError(f"agent {self.id[:8]} is stopped")
        self.task_queue.put_nowait(task)
        task.mark_queued()
        task.agent_id = self.id
        self._report_queue_depth()

    def remove_task(self, task_id: str) -> Optional[Task]:
        """Detach a queued (not yet running) task — used for rebalancing.
        The freed slot is immediately reusable (no ghost capacity)."""
        task = self.task_queue.remove(task_id)
        if task is None:
            return None
        task.status = TaskStatus.PENDING
        task.agent_id = None
        self._report_queue_depth()
        return task

    def queued_tasks(self) -> List[Task]:
        return self.task_queue.values()

    async def run_queue_worker(self) -> None:
        """Drain the agent's own queue (hierarchical/manager workloads)."""
        while self.status not in (AgentStatus.STOPPED, AgentStatus.STOPPING):
            task = await self.task_queue.get(timeout=0.5)
            if task is None:
                continue
            await self.execute_task(task)

    def start_queue_worker(self) -> None:
        if self._worker_task is None or self._worker_task.done():
            self._worker_task = asyncio.create_task(self.run_queue_worker())

    # ------------------------------------------------------------------ #
    # Execution (reference ``core/agent.py:131-371``; call stack §3.4)
    # ------------------------------------------------------------------ #

    async def execute_task(self, task: Task) -> TaskResult:
        """Run one task through the full reasoning loop, with per-task lock
        and overall timeout."""
        lock = self._execution_locks.setdefault(task.id, asyncio.Lock())
        start = time.perf_counter()
        async with lock:
            self.send_heartbeat()
            self.status = AgentStatus.BUSY
            self.current_tasks[task.id] = task
            task.mark_started(agent_id=self.id)
            global_occupancy.step_started(self.role, (self.id, task.id))
            self._report_queue_depth()
            try:
                # trace_id: the orchestrator stamps the task's trace in
                # metadata, so retry attempts and fault-recovery re-runs
                # land in the SAME tree even when no ambient span is
                # live; the dag node nests tools/memory/engine flights
                # under this agent execution.
                with global_tracer.span(
                    "agent.execute_task", task_id=task.id,
                    trace_id=task.metadata.get("trace_id"),
                    attempt=task.retry_count,
                ), global_dag.span(
                    task.id, "agent", self.role, trace=False,
                    agent_id=self.id[:8], attempt=task.retry_count,
                ):
                    result = await asyncio.wait_for(
                        self._execute_task_internal(task),
                        timeout=min(task.timeout, self.config.task_timeout),
                    )
            except asyncio.TimeoutError:
                result = TaskResult(
                    success=False,
                    error=f"task timed out after {task.timeout}s",
                    execution_time=time.perf_counter() - start,
                )
            except Exception as exc:  # noqa: BLE001 - task boundary
                self._error_count += 1
                self._log.error("task %s failed: %s", task.id[:8], exc)
                result = TaskResult(
                    success=False,
                    error=str(exc),
                    execution_time=time.perf_counter() - start,
                )
            finally:
                self.current_tasks.pop(task.id, None)
                self._execution_locks.pop(task.id, None)
                if not self.current_tasks:
                    self.status = AgentStatus.IDLE
                global_occupancy.step_finished(self.role, (self.id, task.id))
                self._report_queue_depth()
                self.send_heartbeat()

        result.execution_time = time.perf_counter() - start
        self._record_result(task, result)
        return result

    def _record_result(self, task: Task, result: TaskResult) -> None:
        if result.success:
            task.mark_completed(result)
            self.task_metrics["completed"] += 1
        else:
            task.mark_failed(result.error or "unknown error", result)
            self.task_metrics["failed"] += 1
        self._total_exec_time += result.execution_time
        self.task_history.append(
            {
                "task_id": task.id,
                "type": task.type,
                "success": result.success,
                "execution_time": result.execution_time,
                "ts": time.time(),
            }
        )
        global_metrics.inc("agent.steps")
        global_metrics.observe("agent.step_latency", result.execution_time)

    async def _execute_task_internal(self, task: Task) -> TaskResult:
        self._validate_task(task)
        analysis = await self._analyze_task(task)
        selected = await self._select_tools(task)
        # Sorted lock acquisition avoids deadlock when two agents share
        # tools (reference ``core/agent.py:181-185``). Acquisition happens
        # INSIDE the try so a CancelledError mid-acquisition (task timeout)
        # releases exactly the locks already held.
        locks = [t.lock for t in sorted(selected, key=lambda t: t.name)]
        acquired: List[asyncio.Lock] = []
        try:
            for lock in locks:
                await lock.acquire()
                acquired.append(lock)
            output, steps = await self._execute_steps(task, analysis, selected)
        finally:
            for lock in reversed(acquired):
                lock.release()
        evaluation = await self._evaluate_result(task, output)
        success = coerce_bool(evaluation.get("success", True))
        return TaskResult(
            success=success,
            output=output,
            error=None if success else "; ".join(
                str(i) for i in evaluation.get("issues", [])
            ) or "evaluation failed",
            metadata={
                "analysis": analysis,
                "evaluation": evaluation,
                "steps": steps,
                "tools_used": [t.name for t in selected],
            },
        )

    def _validate_task(self, task: Task) -> None:
        """Dependencies must be COMPLETED (reference ``:231-246``)."""
        if not task.description:
            raise ValueError("task has no description")
        for dep_id in task.dependencies:
            dep = (
                self.dependency_resolver(dep_id)
                if self.dependency_resolver
                else None
            )
            if dep is None:
                # Unresolvable = already evicted by retention (completed long
                # ago) or tracked elsewhere; consistent with the
                # orchestrator's _deps_state, which skips missing deps.
                continue
            if dep.status != TaskStatus.COMPLETED:
                raise ValueError(
                    f"dependency {dep_id} is {dep.status.value}, not completed"
                )

    # ----------------------- LLM steps -------------------------------- #

    def system_prompt(self) -> str:
        return self.prompts.format_prompt(
            "system.base",
            role=self.config.role,
            goal=self.config.goal,
            backstory=self.config.backstory or "none",
        )

    @staticmethod
    def _slo_class_for(task: Optional[Task]) -> str:
        """Map the task kind onto an SLO service class (obs/slo.py):
        LOW-priority work is fan-out/backlog traffic nobody is watching
        stream — batch objectives; everything else (NORMAL and up, and
        taskless control calls) serves a caller who is waiting."""
        if task is not None and task.priority <= TaskPriority.LOW:
            return "batch"
        return "interactive"

    async def _ask(
        self,
        prompt: str,
        tools: Optional[List[Dict[str, Any]]] = None,
        schema: Optional[Dict[str, Any]] = None,
        task: Optional[Task] = None,
        stage: Optional[str] = None,
    ) -> Dict[str, Any]:
        sys_prompt = self.system_prompt()
        # DAG-aware scheduling hints (pilottai_tpu/sched/): the task's
        # full priority rung — boosted when its live remaining critical
        # path dominates the active set — plus the gang tag for
        # first-stage fan-out siblings. note_stage side effects learn
        # this role's stage order and pre-warm the predicted NEXT
        # stage's prompt prefix through the engine's KV cache tier.
        # Structured form: the engine re-renders tool-preamble + system
        # + user through the same framing as the real request
        # (native._sched_prewarm mirrors _build_request per path), so
        # the pre-warmed token prefix byte-matches the admission that
        # follows. Built only when the scheduler can consume it
        # (policy "dag" AND an engine attached) — otherwise rendering
        # the tool preamble and merging 4 KB prefixes per call would be
        # pure hot-path waste.
        prefix: Optional[Dict[str, Any]] = None
        if global_scheduler.wants_prefix:
            prefix = {"system": sys_prompt, "user": prompt}
            if tools:
                from pilottai_tpu.engine.base import tool_preamble
                from pilottai_tpu.engine.types import ToolSpec

                prefix = {
                    "tools": tool_preamble([
                        t if isinstance(t, ToolSpec) else ToolSpec(**t)
                        for t in tools
                    ]),
                    **prefix,
                }
        hints = global_scheduler.request_hints(
            task, stage, role=self.role, prompt=prefix,
        )
        # Every rules.yaml prompt demands strict JSON: constrained decoding
        # makes the reply well-formed by construction on in-tree engines —
        # and SCHEMA-constrained where the template's shape is expressible
        # (prompts/schemas.py), so the wire fields are exact, not hoped for.
        response = await self.llm.generate_response(
            [
                {"role": "system", "content": sys_prompt},
                {"role": "user", "content": prompt},
            ],
            tools=tools,
            json_mode=True,
            json_schema=schema,
            slo_class=self._slo_class_for(task),
            priority=hints.get("priority"),
            gang_id=hints.get("gang_id"),
            gang_size=hints.get("gang_size", 0),
        )
        self.conversation_history.append(
            {"prompt_tail": prompt[-200:], "response": response.content[:500]}
        )
        data = extract_json(response.content) or {}
        # Function-calling parity (reference ``core/agent.py:331-338``):
        # a structured tool_call from the engine becomes the step's action
        # when the reply JSON didn't already name one.
        if tools and response.tool_calls and "action" not in data:
            tc = response.tool_calls[0]
            data = {**data, "action": tc.name, "arguments": tc.arguments}
        return data

    async def _analyze_task(self, task: Task) -> Dict[str, Any]:
        prompt = self.prompts.format_prompt("task_analysis", task=task.to_prompt())
        return await self._ask(
            prompt, schema=schema_for("agent", "task_analysis"), task=task,
            stage="analyze",
        )

    async def _select_tools(self, task: Task) -> List[Tool]:
        candidates = (
            self.tools.subset(task.tools) if task.tools
            else self.tools.subset(self.tools.names())
        )
        if not candidates:
            return []
        prompt = self.prompts.format_prompt(
            "tool_selection",
            task=task.to_prompt(),
            tools="\n".join(f"{t.name}: {t.description}" for t in candidates),
        )
        data = await self._ask(
            prompt, tools=[t.to_spec() for t in candidates],
            schema=schema_for("agent", "tool_selection"), task=task,
            stage="tools",
        )
        names = data.get("selected_tools", [])
        if not names and data.get("action"):
            # The engine surfaced a structured tool_call instead of the
            # selection form: treat invoking a tool as selecting it.
            names = [data["action"]]
        chosen = [t for t in candidates if t.name in names]
        return chosen

    async def _execute_steps(
        self, task: Task, analysis: Dict[str, Any], tools: List[Tool]
    ) -> tuple:
        """Bounded plan/act loop (reference ``:270-349``)."""
        history: List[Dict[str, Any]] = []
        output: Any = None
        tool_map = {t.name: t for t in tools}
        # Retrieved-memory grounding rides at the head of the progress
        # block (the protocol model trains on this framing too,
        # train/protocol.py).
        grounding = await self._grounding_context(task)
        mem_block = (
            "relevant memory:\n"
            + "\n".join(f"- {g}" for g in grounding) + "\n"
            if grounding else ""
        )
        for iteration in range(self.config.max_iterations):
            prompt = self.prompts.format_prompt(
                "step_planning",
                task=task.to_prompt(),
                history=mem_block + ("\n".join(
                    f"step {i}: {h['action']} -> {str(h['result'])[:200]}"
                    for i, h in enumerate(history)
                ) or "none yet"),
            )
            plan = await self._ask(
                prompt, tools=[t.to_spec() for t in tools] or None,
                task=task, stage="step",
            )
            action = plan.get("action", "respond")
            complete = coerce_bool(plan.get("task_complete", False))
            if complete:
                output = plan.get("output", output)
                history.append({"action": "complete", "result": output})
                if self.step_callback:
                    maybe = self.step_callback(
                        task.id,
                        {"iteration": iteration, "action": "complete"},
                    )
                    if asyncio.iscoroutine(maybe):
                        await maybe
                break
            if action in tool_map:
                try:
                    result = await tool_map[action].execute(
                        plan.get("arguments", {}) or {}
                    )
                except Exception as exc:  # noqa: BLE001 - step boundary
                    result = f"tool error: {exc}"
                history.append({"action": action, "result": result})
                output = result
            else:
                output = plan.get("output", "")
                history.append({"action": "respond", "result": output})
            if self.step_callback:
                maybe = self.step_callback(
                    task.id, {"iteration": iteration, "action": action}
                )
                if asyncio.iscoroutine(maybe):
                    await maybe
        return output, history

    async def _evaluate_result(self, task: Task, output: Any) -> Dict[str, Any]:
        prompt = self.prompts.format_prompt(
            "result_evaluation", task=task.to_prompt(), result=str(output)[:2000]
        )
        return await self._ask(
            prompt, schema=schema_for("agent", "result_evaluation"),
            task=task, stage="evaluate",
        )

    # ------------------------------------------------------------------ #
    # Ops surface (reference ``:217-229,535-575``)
    # ------------------------------------------------------------------ #

    @property
    def queue_utilization(self) -> float:
        return (
            (self.task_queue.qsize() + len(self.current_tasks))
            / max(self.config.max_queue_size, 1)
        )

    @property
    def load(self) -> float:
        """0-1 composite load from queue depth and in-flight tasks (no
        blocking host probes — reference bug §2.12-h)."""
        inflight = len(self.current_tasks) / max(self.config.max_concurrent_tasks, 1)
        return min(1.0, 0.6 * self.queue_utilization + 0.4 * min(inflight, 1.0))

    @property
    def success_rate(self) -> float:
        total = self.task_metrics["completed"] + self.task_metrics["failed"]
        return self.task_metrics["completed"] / total if total else 1.0

    def get_health(self) -> Dict[str, Any]:
        return {
            "agent_id": self.id,
            "status": self.status.value,
            "error_count": self._error_count,
            "last_heartbeat": self._last_heartbeat,
            "queue_utilization": self.queue_utilization,
            "current_tasks": len(self.current_tasks),
        }

    def get_metrics(self) -> Dict[str, Any]:
        total = self.task_metrics["completed"] + self.task_metrics["failed"]
        return {
            "agent_id": self.id,
            "role": self.role,
            "status": self.status.value,
            "queue_size": self.task_queue.qsize(),
            "queue_utilization": self.queue_utilization,
            "load": self.load,
            "total_tasks": total,
            "completed_tasks": self.task_metrics["completed"],
            "failed_tasks": self.task_metrics["failed"],
            "success_rate": self.success_rate,
            "avg_execution_time": self._total_exec_time / total if total else 0.0,
            "error_count": self._error_count,
            "children": len(self.child_agents),
        }

    def evaluate_task_suitability(self, task: Task) -> float:
        """0-1 score: base 0.7 + specialization bonus − load penalty
        (reference ``core/agent.py:549-575``)."""
        if not self.status.is_available:
            return 0.0
        score = 0.7
        if task.type in self.config.specializations:
            score += 0.2
        caps = set(self.config.required_capabilities)
        needed = set(task.required_capabilities)
        if needed:
            if not needed.issubset(caps | set(self.tools.names())):
                return 0.1
            score += 0.1
        score -= 0.3 * self.load
        return max(0.0, min(1.0, score))

    # ------------------------------------------------------------------ #
    # Manager hooks (reference ``core/agent.py:592-628``)
    # ------------------------------------------------------------------ #

    async def determine_strategy(self, tasks: List[Task], state: Dict[str, Any]) -> Dict[str, Any]:
        pm = PromptManager("orchestrator")
        prompt = pm.format_prompt(
            "execution_strategy",
            tasks="\n".join(t.to_prompt() for t in tasks[:10]),
            state=str(state),
        )
        data = extract_json(
            (await self.llm.generate_response(
                [{"role": "user", "content": prompt}], json_mode=True,
                json_schema=schema_for("orchestrator", "execution_strategy"),
            )).content
        ) or {}
        return {
            "strategy": data.get("strategy", "parallel"),
            "max_parallel": int(data.get("max_parallel", 4) or 4),
        }

    async def select_agent(self, task: Task, candidates: List["BaseAgent"]) -> Optional["BaseAgent"]:
        pool = candidates or list(self.child_agents.values())
        if not pool:
            return None
        pm = PromptManager("orchestrator")
        prompt = pm.format_prompt(
            "agent_selection",
            task=task.to_prompt(),
            agents="\n".join(
                f"{a.id}: {a.role}, load={a.load:.2f}, success={a.success_rate:.2f}"
                for a in pool
            ),
        )
        data = extract_json(
            (await self.llm.generate_response(
                [{"role": "user", "content": prompt}], json_mode=True,
                json_schema=schema_for("orchestrator", "agent_selection"),
            )).content
        ) or {}
        chosen = data.get("agent_id", "")
        for agent in pool:
            if agent.id == chosen:
                return agent
        return max(pool, key=lambda a: a.evaluate_task_suitability(task))

    def __repr__(self) -> str:
        return f"<BaseAgent {self.id[:8]} role={self.role} status={self.status.value}>"
