"""AgentFactory: type registry + managed agent creation/cleanup.

Reference parity: ``pilott/core/factory.py`` — class-level registries under
locks (``:15-19``), ``register_agent_type`` validation (``:22-33``),
``create_agent`` with default-config synthesis and creation timeout
(``:57-104``), ``cleanup_agent``/``cleanup_all_agents`` (``:106-134``).
The reference's broken sync-``@contextmanager``-around-async-generator
(``:37-54``, SURVEY §2.12-g) is replaced with a real
``@asynccontextmanager``.
"""

from __future__ import annotations

import asyncio
import threading
from contextlib import asynccontextmanager
from typing import Any, Dict, List, Optional, Type

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig
from pilottai_tpu.utils.logging import get_logger


class AgentFactory:
    """Registry of agent types and tracker of live agents."""

    _agent_types: Dict[str, Type[BaseAgent]] = {}
    _active_agents: Dict[str, BaseAgent] = {}
    _registry_lock = threading.Lock()
    _log = get_logger("factory")
    creation_timeout: float = 30.0

    # ------------------------------------------------------------------ #

    @classmethod
    def register_agent_type(cls, name: str, agent_class: Type[BaseAgent]) -> None:
        if not (isinstance(agent_class, type) and issubclass(agent_class, BaseAgent)):
            raise TypeError(f"{agent_class!r} is not a BaseAgent subclass")
        with cls._registry_lock:
            if name in cls._agent_types:
                raise ValueError(f"agent type {name!r} already registered")
            cls._agent_types[name] = agent_class

    @classmethod
    def unregister_agent_type(cls, name: str) -> None:
        with cls._registry_lock:
            cls._agent_types.pop(name, None)

    @classmethod
    def list_agent_types(cls) -> List[str]:
        with cls._registry_lock:
            return sorted(cls._agent_types)

    # ------------------------------------------------------------------ #

    @classmethod
    def _validate_config(cls, config: AgentConfig) -> None:
        if config.max_queue_size < config.max_concurrent_tasks:
            raise ValueError(
                "max_queue_size must be >= max_concurrent_tasks"
            )

    @classmethod
    async def create_agent(
        cls,
        agent_type: str,
        config: Optional[AgentConfig | Dict[str, Any]] = None,
        start: bool = True,
        **kwargs: Any,
    ) -> BaseAgent:
        """Instantiate + (optionally) start a registered agent type.

        Default-config synthesis mirrors the reference (``factory.py:57-84``):
        a missing config becomes an AgentConfig with role = agent_type.
        """
        with cls._registry_lock:
            if agent_type not in cls._agent_types:
                raise KeyError(
                    f"unknown agent type {agent_type!r}; registered: "
                    f"{sorted(cls._agent_types)}"
                )
            agent_class = cls._agent_types[agent_type]
        if config is None:
            config = AgentConfig(role=agent_type)
        elif isinstance(config, dict):
            config = AgentConfig(**{"role": agent_type, **config})
        cls._validate_config(config)

        agent = agent_class(config=config, **kwargs)
        if start:
            try:
                await asyncio.wait_for(agent.start(), timeout=cls.creation_timeout)
            except asyncio.TimeoutError:
                raise RuntimeError(
                    f"agent {agent_type!r} failed to start within "
                    f"{cls.creation_timeout}s"
                ) from None
        with cls._registry_lock:
            cls._active_agents[agent.id] = agent
        cls._log.info("created agent %s type=%s", agent.id[:8], agent_type)
        return agent

    @classmethod
    async def cleanup_agent(cls, agent_id: str) -> bool:
        """Stop + deregister; idempotent (reference ``:106-120``)."""
        with cls._registry_lock:
            agent = cls._active_agents.pop(agent_id, None)
        if agent is None:
            return False
        try:
            await agent.stop()
        except Exception as exc:  # noqa: BLE001 - cleanup boundary
            cls._log.warning("error stopping agent %s: %s", agent_id[:8], exc)
        return True

    @classmethod
    async def cleanup_all_agents(cls) -> int:
        with cls._registry_lock:
            ids = list(cls._active_agents)
        count = 0
        for agent_id in ids:
            if await cls.cleanup_agent(agent_id):
                count += 1
        return count

    @classmethod
    def active_agents(cls) -> Dict[str, BaseAgent]:
        with cls._registry_lock:
            return dict(cls._active_agents)

    # ------------------------------------------------------------------ #

    @classmethod
    @asynccontextmanager
    async def managed_agent(
        cls, agent_type: str, config: Optional[AgentConfig] = None, **kwargs: Any
    ):
        """Async context manager: create on enter, cleanup on exit (the
        capability the reference's broken ``create_managed_agent`` intended,
        SURVEY §2.12-g)."""
        agent = await cls.create_agent(agent_type, config, **kwargs)
        try:
            yield agent
        finally:
            await cls.cleanup_agent(agent.id)


AgentFactory.register_agent_type("worker", BaseAgent)
