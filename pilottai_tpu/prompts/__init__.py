from pilottai_tpu.prompts.manager import PromptManager

__all__ = ["PromptManager"]
