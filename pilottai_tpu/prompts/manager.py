"""Prompt template loading, placeholder validation and formatting.

Reference parity: ``PromptManager`` (``pilott/core/agent.py:32-56``) and
``OrchestratorPromptManager`` (``pilott/pilott.py:29-66``) — both load
``pilott/source/rules.yaml``, regex-extract ``{param}`` placeholders and
validate kwargs before formatting. Here one class serves both namespaces.
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Any, Dict, Optional, Set

try:
    import yaml
except ImportError:  # pragma: no cover - pyyaml ships with jax stacks
    yaml = None

_DEFAULT_RULES = Path(__file__).with_name("rules.yaml")

# A placeholder is {name}; literal braces are doubled ({{ }}), matching
# str.format semantics (the JSON examples in the templates use {{ }}).
_PLACEHOLDER_RE = re.compile(r"(?<!\{)\{([a-zA-Z_][a-zA-Z0-9_]*)\}(?!\})")
# Single-pass substitution token: doubled brace OR placeholder. One regex
# pass over the template only, so placeholder-like text *inside substituted
# values* is never re-scanned (no cross-kwarg injection).
_SUBST_RE = re.compile(r"\{\{|\}\}|(?<!\{)\{([a-zA-Z_][a-zA-Z0-9_]*)\}(?!\})")


class PromptError(Exception):
    pass


class PromptManager:
    """Loads a namespace ("agent" or "orchestrator") of prompt templates."""

    def __init__(
        self,
        namespace: str = "agent",
        rules_path: Optional[str | Path] = None,
        overrides: Optional[Dict[str, str]] = None,
    ) -> None:
        self.namespace = namespace
        path = Path(rules_path) if rules_path else _DEFAULT_RULES
        if yaml is None:
            raise PromptError("pyyaml is required to load prompt rules")
        rules = yaml.safe_load(path.read_text())
        if namespace not in rules:
            raise PromptError(f"namespace {namespace!r} not found in {path}")
        self._templates: Dict[str, Any] = rules[namespace]
        if overrides:
            self._templates.update(overrides)

    def _lookup(self, prompt_type: str) -> str:
        node: Any = self._templates
        for part in prompt_type.split("."):
            if not isinstance(node, dict) or part not in node:
                raise PromptError(
                    f"unknown prompt {prompt_type!r} in namespace {self.namespace!r}"
                )
            node = node[part]
        if not isinstance(node, str):
            raise PromptError(f"prompt {prompt_type!r} is not a template leaf")
        return node

    @staticmethod
    def placeholders(template: str) -> Set[str]:
        return set(_PLACEHOLDER_RE.findall(template))

    def format_prompt(self, prompt_type: str, **kwargs: Any) -> str:
        """Validate kwargs against the template's placeholders, then format.

        Reference: ``pilott/pilott.py:41-66`` raises on missing params;
        extra params are ignored there and here.
        """
        template = self._lookup(prompt_type)
        needed = self.placeholders(template)
        missing = needed - set(kwargs)
        if missing:
            raise PromptError(
                f"prompt {prompt_type!r} missing parameters: {sorted(missing)}"
            )

        def _sub(match: "re.Match[str]") -> str:
            token = match.group(0)
            if token == "{{":
                return "{"
            if token == "}}":
                return "}"
            return str(kwargs[match.group(1)])

        return _SUBST_RE.sub(_sub, template)

    def available(self) -> Dict[str, Any]:
        return dict(self._templates)
