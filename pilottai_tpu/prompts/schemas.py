"""JSON Schemas for the rules.yaml wire protocol.

Each template in ``prompts/rules.yaml`` demands a specific JSON shape;
these schemas state those shapes formally so in-tree engines can enforce
them with schema-constrained decoding (``engine/json_schema.py``) — the
LLM↔runtime protocol becomes valid **by construction**, not by
retry-parse (the reference's approach, ``pilott/pilott.py:603-639``).

``step_planning`` is deliberately absent: its ``arguments`` field is a
free-form object (tool arguments), which the compiled-DFA subset cannot
express — that call keeps the generic JSON grammar.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

_STR = {"type": "string"}
_STR_LIST = {"type": "array", "items": {"type": "string"}}

PROTOCOL_SCHEMAS: Dict[str, Dict[str, Any]] = {
    "agent.task_analysis": {
        "type": "object",
        "properties": {
            "understanding": _STR,
            "approach": _STR,
            "estimated_steps": {"type": "integer"},
            "risks": _STR_LIST,
        },
        "required": ["understanding", "approach", "estimated_steps", "risks"],
    },
    "agent.tool_selection": {
        "type": "object",
        "properties": {
            "selected_tools": _STR_LIST,
            "reasoning": _STR,
        },
        "required": ["selected_tools", "reasoning"],
    },
    "agent.result_evaluation": {
        "type": "object",
        "properties": {
            "success": {"type": "boolean"},
            "quality": {"type": "number"},
            "issues": _STR_LIST,
            "suggestions": _STR_LIST,
        },
        "required": ["success", "quality", "issues", "suggestions"],
    },
    "orchestrator.task_analysis": {
        "type": "object",
        "properties": {
            "requires_decomposition": {"type": "boolean"},
            "complexity": {"type": "integer"},
            "estimated_resources": {
                "type": "object",
                "properties": {
                    "agents": {"type": "integer"},
                    "llm_calls": {"type": "integer"},
                },
                "required": ["agents", "llm_calls"],
            },
            "reasoning": _STR,
        },
        "required": [
            "requires_decomposition", "complexity",
            "estimated_resources", "reasoning",
        ],
    },
    "orchestrator.task_decomposition": {
        "type": "object",
        "properties": {
            "subtasks": {
                "type": "array",
                "items": {
                    "type": "object",
                    "properties": {
                        "description": _STR,
                        "type": _STR,
                        "priority": {
                            "enum": ["low", "normal", "high", "critical"]
                        },
                        "depends_on": {
                            "type": "array",
                            "items": {"type": "integer"},
                        },
                    },
                    "required": [
                        "description", "type", "priority", "depends_on",
                    ],
                },
            },
        },
        "required": ["subtasks"],
    },
    "orchestrator.agent_selection": {
        "type": "object",
        "properties": {"agent_id": _STR, "reasoning": _STR},
        "required": ["agent_id", "reasoning"],
    },
    "orchestrator.execution_strategy": {
        "type": "object",
        "properties": {
            "strategy": {"enum": ["parallel", "sequential"]},
            "max_parallel": {"type": "integer"},
            "reasoning": _STR,
        },
        "required": ["strategy", "max_parallel", "reasoning"],
    },
    "orchestrator.result_evaluation": {
        "type": "object",
        "properties": {
            "quality": {"type": "number"},
            "requires_retry": {"type": "boolean"},
            "feedback": _STR,
        },
        "required": ["quality", "requires_retry", "feedback"],
    },
}


def schema_for(namespace: str, template: str) -> Optional[Dict[str, Any]]:
    """The wire schema for ``<namespace>.<template>``, or None when the
    shape is not expressible (step_planning's free-form arguments)."""
    return PROTOCOL_SCHEMAS.get(f"{namespace}.{template}")
