"""Headline benchmark: agent-steps/sec/chip through the in-tree engine.

An "agent step" is one LLM call inside the agent's plan/act/evaluate loop
(SURVEY.md §3.4: a simple task is ≥4 such calls; the reference pays a
remote HTTPS round-trip per step, ``pilott/engine/llm.py:59``). Here the
same step runs on local devices through the continuous batcher.

Baseline: the reference publishes no numbers (SURVEY.md §6); BASELINE.json's
north star is ≤500 ms p50 per agent step → 2.0 steps/sec/chip. vs_baseline
is measured steps/sec/chip against that 2.0.

Prints ONE JSON line.
"""

import asyncio
import json
import statistics
import time

import jax


CONCURRENCY = 32       # concurrent agent steps in flight
STEPS = 96             # total timed steps
MAX_NEW_TOKENS = 48    # JSON-ish agent-step reply length
BASELINE_STEPS_PER_SEC = 2.0


def pick_config():
    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    from pilottai_tpu.core.config import LLMConfig

    return on_accel, LLMConfig(
        model_name="llama3-1b-byte" if on_accel else "llama-tiny",
        provider="tpu" if on_accel else "cpu",
        engine_slots=min(CONCURRENCY, 32),
        engine_max_seq=512,
        dtype="bfloat16" if on_accel else "float32",
    )


PROMPT = (
    "Analyze the task and respond with JSON: "
    '{"requires_decomposition": false, "complexity": 3, '
    '"estimated_resources": {"agents": 1}}. Task: summarize the quarterly '
    "report into three bullet points for the executive team."
)


async def run_bench():
    on_accel, cfg = pick_config()
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    handler = LLMHandler(cfg)
    params = GenerationParams(max_new_tokens=MAX_NEW_TOKENS, temperature=0.0)

    async def one_step():
        resp = await handler.apredict(PROMPT, params=params)
        return resp

    # Warmup: compile prefill bucket + decode, fill the pipeline.
    await asyncio.gather(*[one_step() for _ in range(min(8, CONCURRENCY))])

    latencies = []
    done = 0
    t0 = time.perf_counter()

    async def worker():
        nonlocal done
        while done < STEPS:
            done += 1
            s = time.perf_counter()
            await one_step()
            latencies.append(time.perf_counter() - s)

    await asyncio.gather(*[worker() for _ in range(CONCURRENCY)])
    wall = time.perf_counter() - t0
    await handler.stop()

    n_chips = max(len(jax.devices()), 1) if on_accel else 1
    steps_per_sec_chip = len(latencies) / wall / n_chips
    p50_ms = statistics.median(latencies) * 1000.0
    print(
        json.dumps(
            {
                "metric": "agent_steps_per_sec_per_chip",
                "value": round(steps_per_sec_chip, 3),
                "unit": "steps/s/chip",
                "vs_baseline": round(steps_per_sec_chip / BASELINE_STEPS_PER_SEC, 3),
                "p50_step_ms": round(p50_ms, 1),
                "model": cfg.model_name,
                "provider": cfg.provider,
                "n_chips": n_chips,
                "concurrency": CONCURRENCY,
                "steps": len(latencies),
            }
        )
    )


if __name__ == "__main__":
    asyncio.run(run_bench())
