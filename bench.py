"""Headline benchmark: agent-steps/sec/chip through the in-tree engine,
plus orchestrator-level numbers through ``Serve`` itself.

An "agent step" is one LLM call inside the agent's plan/act/evaluate loop
(SURVEY.md §3.4: a simple task is ≥4 such calls; the reference pays a
remote HTTPS round-trip per step, ``pilott/engine/llm.py:59``). Here the
same step runs on local devices through the continuous batcher.

Five sections on accelerator (VERDICT r3 next-steps 1, 2, 6, 9):

* ``llama3-1b-byte`` — 32-way concurrency throughput section;
* ``llama3-8b-byte`` — the BASELINE.md north-star model, int8
  weight-only + speculative decoding (D=6 verify blocks, early-exit
  chunks), 8-way, over COLD prompts (every request's task suffix is
  unique — prefix caching may share the page-aligned/LCP preamble, the
  way real agent traffic shares the rules preamble, but no request is
  an exact repeat); its p50 vs the ≤500 ms target is the headline
  (``vs_baseline`` = 500 / p50_8b);
* ``llama3-8b-byte @ 4K paged`` — the long-context serving path: paged
  KV + int8 KV cache + speculation + block-granular prefix caching
  composed (round 3 silently lost all three under paging);
* ``pipeline`` — BASELINE config #3 end-to-end: Serve + manager + 3
  specialist workers running the document pipeline on the real 1B
  engine, task-completion p50 *through* ``Serve.execute``;
* ``swarm`` — BASELINE config #4: 32 agents on one Serve sharing the
  1B engine, agent LLM steps/s through the orchestrator.

The TPU is reached through a shared tunnel whose latency oscillates
between ~100 ms and multi-second stalls (see .claude/skills/verify
gotchas); a single epoch can land in a bad window and misreport the
engine by 5x. Engine sections therefore run several epochs and report
the best one (peak sustained throughput) PLUS the median epoch and every
epoch's rate, so the flattering statistic never stands alone.

Perf note (round 4, measured on one v5e through the tunnel): the 8B
decode device time sits near its bandwidth floor — ~14 ms per verify
block (jax.profiler: 181 ms chunk + 27 ms admission per 8-way wave at
acceptance ~3.7) — so wave latency ≈ device time + ~100-130 ms of
tunnel round trips that co-located hardware would not pay.

Prints ONE JSON line.
"""

import asyncio
import gc
import json
import os
import statistics
import sys
import time

# Persistent compilation cache: the driver re-runs this benchmark every
# round in a fresh process; warm boots cut 8B engine-up from ~140 s to
# ~30 s (utils/compile_cache.py).
os.environ.setdefault(
    "PILOTTAI_COMPILE_CACHE",
    os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache"),
)

import jax

MAX_NEW_TOKENS = 48    # JSON-ish agent-step reply length
TARGET_P50_MS = 500.0  # BASELINE.md north star for llama3-8b

PREAMBLE = (
    "Analyze the task and respond with JSON: "
    '{"requires_decomposition": false, "complexity": 3, '
    '"estimated_resources": {"agents": 1}}. Task: '
)


def _prompt(uid: int, pad_to: int = 0) -> str:
    """Agent-step prompt with a UNIQUE task suffix (cold request). The
    shared preamble mirrors real traffic (rules.yaml is byte-identical
    across calls); ``pad_to`` repeats it to reach long-context sizes."""
    pre = PREAMBLE
    while pad_to and len(pre) < pad_to:
        pre += PREAMBLE
    return pre + f"summarize document {uid} for the executive team"


async def bench_model(cfg, concurrency, steps, epochs, n_chips=1,
                      pad_to=0):
    """Run one engine section; returns the result dict."""
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.obs import peak_flops_per_chip

    handler = LLMHandler(cfg)
    on_accel = cfg.provider != "cpu"
    peak_flops = peak_flops_per_chip("tpu" if on_accel else "cpu")
    # Section-pure phase percentiles: drop the previous section's
    # request-phase samples so the `phases` block below describes ONLY
    # this section's traffic (counts and windows included).
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    _gm.reset_histograms("request.")
    _gm.reset_histograms("engine.prefill_latency")
    # Host-gap histogram is section-pure too: each section's
    # host_gap_p50_ms must describe ONLY its own dispatches, or a slow
    # warmup section poisons every later section's number.
    _gm.reset_histograms("engine.host_gap_ms")
    params = GenerationParams(max_new_tokens=MAX_NEW_TOKENS, temperature=0.0)
    uid = [0]

    async def one_step():
        uid[0] += 1
        return await handler.apredict(
            _prompt(uid[0], pad_to), params=params
        )

    # Warmup: two full waves — the first compiles prefill buckets +
    # decode, the second the PREFIX-HIT admission variants and settles
    # the speculative acceptance EMA (with only one wave those compiles
    # land inside timed epoch 1 and drag the reported median).
    for _ in range(2):
        await asyncio.gather(*[one_step() for _ in range(concurrency)])

    # Chunk-utilization counters are cumulative — snapshot AFTER the
    # warmup waves (the engine's lazy boot runs its bucket compile
    # sweep inside the first one, and those 2-token probes would bias
    # the section's useful/dispatched ratio far below steady state).
    blocks0 = (
        _gm.get("engine.blocks_dispatched"),
        _gm.get("engine.blocks_useful"),
        _gm.get("engine.chunk_folds"),
    )
    # Attribution counters for the section's LIVE MFU: prefill tokens +
    # ACCEPTED decode tokens (folded validity — obs/attribution.py feeds
    # both), achieved FLOPs via ModelConfig.flops_per_token(). Same
    # formula as the live engine.mfu gauge, measured as a delta over the
    # timed epochs. (The old number used decode tokens only with an
    # inline 2*n_params guess — prefill and speculative acceptance were
    # invisible to it.)
    attr0 = (
        _gm.get("engine.prefill_tokens"),
        _gm.get("engine.generated_tokens_device"),
        _gm.get("engine.achieved_flops"),
    )
    t_meas0 = time.perf_counter()

    async def epoch():
        latencies = []
        done = 0
        t0 = time.perf_counter()

        async def worker():
            nonlocal done
            while done < steps:
                done += 1
                s = time.perf_counter()
                await one_step()
                latencies.append(time.perf_counter() - s)

        await asyncio.gather(*[worker() for _ in range(concurrency)])
        return latencies, time.perf_counter() - t0

    runs = [await epoch() for _ in range(epochs)]
    wall_meas = time.perf_counter() - t_meas0
    attr1 = (
        _gm.get("engine.prefill_tokens"),
        _gm.get("engine.generated_tokens_device"),
        _gm.get("engine.achieved_flops"),
    )

    # Transport-independent truth (VERDICT r4 weak #2, methodology fixed
    # per VERDICT r5 next-step 2): a STEADY-STATE window under
    # jax.profiler — the device's own busy time per step can't be
    # confused with tunnel weather. One un-traced settle wave first (so
    # first-wave admission, compile stragglers and the acceptance EMA
    # never pollute the trace — r5's single isolated wave reported an
    # internally impossible 104.9 device-only vs 146.3 wall), then the
    # trace starts mid-epoch and spans ≥3 consecutive waves.
    # steps_per_sec_device_only is what co-located hardware would
    # sustain if the device were the only bottleneck; busy_frac shows
    # how much of the window the tunnel ate.
    PROFILE_WAVES = 3
    device = None
    if cfg.provider != "cpu":
        from pilottai_tpu.utils.device_profile import DeviceWindow

        try:
            await asyncio.gather(  # settle wave — excluded from trace
                *[one_step() for _ in range(concurrency)]
            )
            flops_w0 = _gm.get("engine.achieved_flops")
            win = DeviceWindow().start()
            t0 = time.perf_counter()
            try:
                for _ in range(PROFILE_WAVES):
                    await asyncio.gather(
                        *[one_step() for _ in range(concurrency)]
                    )
            finally:
                # The profiler trace is process-global: leaving it
                # running after a failed wave breaks every later
                # section's profiling.
                window_wall = time.perf_counter() - t0
                prof = win.stop()
            profiled = PROFILE_WAVES * concurrency
            flops_w = _gm.get("engine.achieved_flops") - flops_w0
            if prof["device_busy_s"] > 0:
                device = {
                    "device_ms_per_step": round(
                        prof["device_busy_s"] * 1000.0 / profiled, 2
                    ),
                    "steps_per_sec_device_only": round(
                        profiled / prof["device_busy_s"] / n_chips, 3
                    ),
                    "device_busy_frac": round(prof["busy_frac"], 3),
                    "profiled_steps": profiled,
                    "profiled_waves": PROFILE_WAVES,
                    "profiled_window_steps_per_sec": round(
                        profiled / window_wall / n_chips, 3
                    ),
                    # MFU over the PROFILER-measured window: achieved
                    # FLOPs (attribution counters) over the profiled
                    # wall, and over the device's own busy time — the
                    # reconciliation pair for the section-level live
                    # `mfu` below (slow-marker test pins the same pair
                    # on the CPU engine; tests/test_attribution.py).
                    "mfu_profiled_window": round(
                        flops_w / (window_wall * peak_flops * n_chips), 4
                    ),
                    "mfu_device_busy": round(
                        flops_w
                        / (prof["device_busy_s"] * peak_flops * n_chips),
                        4,
                    ),
                }
        except Exception as exc:  # noqa: BLE001 — profiling is best-effort
            _note("device profile FAILED", {"error": str(exc)})

    # Per-phase breakdown (queue wait / prefill / TTFT / TPOT / ITL
    # percentiles) from the flight-recorder histograms, captured while
    # this section's samples are still the recent window — future perf
    # PRs get a phase-attributed trajectory, not just aggregate rates.
    from pilottai_tpu.obs import phase_summary

    phases = phase_summary()
    blocks_disp = _gm.get("engine.blocks_dispatched") - blocks0[0]
    blocks_used = _gm.get("engine.blocks_useful") - blocks0[1]
    n_folds = _gm.get("engine.chunk_folds") - blocks0[2]
    # Host-gap percentiles for THIS section (histogram reset above):
    # the device-idle bubble between fold-complete and next dispatch.
    # p50 ≈ 0 means the overlapped pipeline kept the device fed; a
    # regression here is attributable before device_busy_frac moves.
    gap = _gm.snapshot()["histograms"].get("engine.host_gap_ms") or {}
    host_gap_p50 = gap.get("p50")
    host_gap_p90 = gap.get("p90")

    await handler.stop()
    del handler
    gc.collect()

    epoch_rates = [round(len(l) / w / n_chips, 3) for l, w in runs]
    latencies, wall = max(runs, key=lambda e: len(e[0]) / e[1])
    steps_per_sec = len(latencies) / wall / n_chips
    p50_ms = statistics.median(latencies) * 1000.0

    # LIVE section MFU: achieved-FLOPs delta over the timed epochs
    # (prefill tokens + accepted speculative/decode tokens from folded
    # validity x ModelConfig.flops_per_token() — exactly the live
    # engine.mfu gauge's accounting, measured per chip over the
    # measurement wall).
    prefill_toks = attr1[0] - attr0[0]
    accepted_toks = attr1[1] - attr0[1]
    flops_meas = attr1[2] - attr0[2]
    mfu_live = (
        flops_meas / (wall_meas * peak_flops * n_chips)
        if wall_meas > 0 else 0.0
    )

    # Internal-consistency check BEFORE the number is emitted (VERDICT
    # r5 next-step 2): (a) the device can't be slower than the wall that
    # includes transport — steps_per_sec_device_only ≥ the wall rate;
    # (b) busy_frac × device-only rate must reproduce the profiled
    # window's own wall rate within tolerance (they are the same window
    # measured two ways). A violation means the profiled window was not
    # steady-state — the r5 failure mode this check exists to catch.
    if device is not None:
        dev_rate = device["steps_per_sec_device_only"]
        window_rate = device["profiled_window_steps_per_sec"]
        product = device["device_busy_frac"] * dev_rate
        rel_err = abs(product - window_rate) / max(window_rate, 1e-9)
        # Live-vs-profiler MFU reconciliation (acceptance bar: within
        # 15% on the 1B dense section): the section's live MFU against
        # the same accounting over the profiler-measured window. Drift
        # here means the attribution counters disagree with the
        # profiler's clock — the silent-drift failure the slow-marker
        # test (tests/test_attribution.py) pins on CPU.
        mfu_rel_err = (
            abs(device["mfu_profiled_window"] - mfu_live)
            / max(mfu_live, 1e-9)
        )
        device["device_consistency"] = {
            "device_only_ge_wall": bool(dev_rate >= steps_per_sec * 0.98),
            "busy_x_device_vs_window_rel_err": round(rel_err, 3),
            "mfu_live_vs_profiled_rel_err": round(mfu_rel_err, 3),
            "mfu_ok": bool(mfu_rel_err <= 0.15),
            "ok": bool(dev_rate >= steps_per_sec * 0.98 and rel_err <= 0.25),
        }
        _note(f"device consistency [{cfg.model_name}]", {
            "steps_per_sec_device_only": dev_rate,
            "steps_per_sec_per_chip": round(steps_per_sec, 3),
            "busy_frac_x_device_only": round(product, 3),
            "profiled_window_steps_per_sec": window_rate,
            "mfu_live": round(mfu_live, 4),
            "mfu_profiled_window": device["mfu_profiled_window"],
            **device["device_consistency"],
        })
    decode_tok_s = len(latencies) * MAX_NEW_TOKENS / wall / n_chips
    return {
        "model": cfg.model_name,
        "steps_per_sec_per_chip": round(steps_per_sec, 3),
        "median_epoch_steps_per_sec": round(
            statistics.median(epoch_rates), 3
        ),
        "p50_step_ms": round(p50_ms, 1),
        "decode_tokens_per_sec_per_chip": round(decode_tok_s, 1),
        # Live MFU (see attr0/attr1 above): prefill + accepted tokens,
        # ModelConfig.flops_per_token(), per chip, over the measurement
        # wall — the same formula as the live engine.mfu gauge.
        "mfu": round(mfu_live, 4),
        "mfu_prefill_tokens": int(prefill_toks),
        "mfu_accepted_tokens": int(accepted_toks),
        "concurrency": concurrency,
        "steps": len(latencies),
        "speculate": cfg.engine_speculate,
        "quantize": cfg.quantize,
        "paged": bool(cfg.engine_paged_kv),
        "kv_quantize": cfg.engine_kv_quantize,
        "epoch_steps_per_sec": epoch_rates,
        # Section-pure: the request-phase histograms were reset at this
        # section's start, so counts and percentiles cover only it.
        "phases": phases,
        # Adaptive-chunk scheduling outcome for this section: useful
        # decode blocks ÷ dispatched blocks, and the mean per-dispatch
        # chunk size the policy actually picked.
        "chunk_policy": cfg.engine_chunk_policy,
        "chunk_utilization": (
            round(blocks_used / blocks_disp, 4) if blocks_disp else None
        ),
        "chunk_blocks_dispatched": int(blocks_disp),
        "chunk_blocks_mean": (
            round(blocks_disp / n_folds, 2) if n_folds else None
        ),
        # Device-feed health: host-side gap percentiles (ms) and the
        # profiled busy fraction. device_busy_frac is None on CPU runs
        # (no device profile); the device dict overrides it on accel.
        "host_gap_p50_ms": (
            round(host_gap_p50, 3) if host_gap_p50 is not None else None
        ),
        "host_gap_p90_ms": (
            round(host_gap_p90, 3) if host_gap_p90 is not None else None
        ),
        "device_busy_frac": None,
        **(device or {}),
    }


async def bench_slo(cfg, rate_rps, duration_s=30.0, n_chips=1, seed=7,
                    burst_factor=2.0, derate=False):
    """Open-loop SLO section (ROADMAP item 5): Poisson arrivals at
    ``rate_rps`` over a multi-tenant mix with a 2x burst through the
    middle fifth of the run. The mix carries the three first-class
    workload shapes the cost model covers (ISSUE 18) next to plain
    chat: **multi-turn sessions** (a persistent ``session_id`` whose
    transcript grows turn over turn — the PR 9 kvcache tier's prefix
    path), **long-context RAG** (a fat padded context ahead of a short
    question, batch class) and **schema-constrained tool loops** (two
    chained grammar-constrained calls per arrival). Open-loop means
    arrivals do NOT wait for completions (closed-loop fixed concurrency
    self-throttles and can never show queueing collapse); the headline
    is per-class SLO attainment and p99s from obs/slo.py, not
    throughput.
    """
    import random as _random

    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.obs import global_slo
    from pilottai_tpu.reliability import EngineOverloaded
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    TOOL_SCHEMA = {
        "type": "object",
        "properties": {
            "action": {"type": "string"},
            "count": {"type": "integer"},
        },
        "required": ["action", "count"],
    }
    # (name, weight, slo_class, max_new_tokens, pad_to, json_schema)
    # Tenant behavior beyond the tuple (session transcripts, tool-loop
    # chaining) keys off the name in one().
    tenants = [
        ("chat", 0.35, "interactive", 32, 0, None),
        ("sessions", 0.25, "interactive", 24, 0, None),
        ("rag", 0.2, "batch", 48, 1200, None),
        ("toolloop", 0.2, "interactive", 24, 0, TOOL_SCHEMA),
    ]
    handler = LLMHandler(cfg)
    rng = _random.Random(seed)
    uid = [0]
    # Multi-turn session state: a small pool of persistent sessions
    # whose transcripts grow — successive turns share an ever-longer
    # prefix under one session_id, the exact shape the kvcache tier
    # (and its knobs) exist for.
    n_session_pool = 6
    session_log: dict = {}

    async def one(tenant, warm=False):
        name, _, slo_class, max_new, pad_to, schema = tenant
        uid[0] += 1
        # Per-request RNG keyed by arrival index: in-task draws must not
        # interleave with the arrival loop's shared rng, or two runs of
        # the same seed would see different workloads (the AUTOCONF
        # section compares knob vectors on the SAME recorded workload).
        req_rng = _random.Random((seed << 20) ^ uid[0])
        params = GenerationParams(
            max_new_tokens=max_new, temperature=0.0,
            slo_class=slo_class, json_schema=schema,
            json_mode=schema is not None,
        )
        try:
            if name == "sessions":
                sid = f"slo-sess-{req_rng.randrange(n_session_pool)}"
                log = session_log.setdefault(sid, [])
                log.append(f"turn {len(log)}: question {uid[0]}")
                if len(log) > 8:  # bound transcript growth
                    del log[:-8]
                params = params.model_copy(update={"session_id": sid})
                await handler.apredict("\n".join(log), params=params)
            elif name == "toolloop":
                # Tool loop: two chained schema-constrained calls — the
                # second consumes the first's (fixed-shape) output, the
                # agentic pattern the scheduler sees as a short chain.
                out = await handler.apredict(
                    _prompt(uid[0], pad_to), params=params
                )
                await handler.apredict(
                    f"given {str(out)[:120]}, next call {uid[0]}",
                    params=params,
                )
            else:
                await handler.apredict(
                    _prompt(uid[0], pad_to), params=params
                )
            return "ok"
        except EngineOverloaded:
            return "shed"
        except Exception as exc:  # noqa: BLE001 — harness keeps running
            if not warm:
                _note("slo request FAILED", {"tenant": name,
                                             "error": str(exc)[:200]})
            return "error"

    # Warm every tenant shape (prefill buckets + schema DFA + the
    # acceptance EMA) so compiles never land inside the measured run.
    for tenant in tenants:
        await asyncio.gather(*[one(tenant, warm=True) for _ in range(2)])

    names = [t[0] for t in tenants]
    weights = [t[1] for t in tenants]
    # Headline honesty (ISSUE 19 satellite): BENCH_r07 printed
    # slo_attainment_interactive 0.0 because the offered rate was set
    # from the agent-step rate, which overstates what one engine absorbs
    # on this heavier mix (every arrival decodes 24-48 tokens; RAG pads
    # 1200 chars) — the section measured unbounded queue growth, not SLO
    # behavior. With ``derate`` on, a short closed-loop burst over the
    # same weighted mix measures the mix's own capacity and the offered
    # rate clamps to 80% of it (the requested rate is still reported as
    # ``target_rps``). Off by default: AUTOCONF replays this harness per
    # knob candidate and must offer every candidate the SAME load.
    measured_capacity_rps = None
    effective_rate = rate_rps
    if derate:
        calib_rng = _random.Random(seed ^ 0x5CA1AB1E)
        calib_t0 = time.perf_counter()
        calib_reqs = 0
        for _ in range(3):
            wave = calib_rng.choices(tenants, weights=weights, k=4)
            await asyncio.gather(*[one(t, warm=True) for t in wave])
            calib_reqs += len(wave)
        calib_wall = max(time.perf_counter() - calib_t0, 1e-6)
        measured_capacity_rps = round(calib_reqs / calib_wall, 2)
        effective_rate = min(
            rate_rps, max(round(0.8 * measured_capacity_rps, 2), 0.5)
        )

    # Section-pure SLO windows: the warmup's compile-wall misses must
    # not burn this section's budget. requests/missed are cumulative
    # process counters (earlier bench sections feed the same global
    # tracker), so the section reports DELTAS from here.
    global_slo.reset()
    _gm.reset_histograms("request.")
    count0 = {
        cls: (_gm.get(f"slo.{cls}.requests"), _gm.get(f"slo.{cls}.missed"))
        for cls in global_slo.classes
    }

    t_start = time.perf_counter()
    burst_lo = t_start + 0.4 * duration_s
    burst_hi = t_start + 0.6 * duration_s
    inflight: list = []
    offered = {n: 0 for n in names}
    while True:
        now = time.perf_counter()
        if now >= t_start + duration_s:
            break
        rate = effective_rate * (
            burst_factor if burst_lo <= now < burst_hi else 1.0
        )
        await asyncio.sleep(rng.expovariate(max(rate, 1e-3)))
        tenant = rng.choices(tenants, weights=weights, k=1)[0]
        offered[tenant[0]] += 1
        inflight.append(asyncio.create_task(one(tenant)))
    # Offered load is defined by the ARRIVAL window — stamp it before
    # draining in-flight work, or saturation (queued requests completing
    # long after arrivals stop) would dilute offered_rps exactly when
    # the open-loop harness is demonstrating queueing collapse.
    arrival_wall = time.perf_counter() - t_start
    outcomes = await asyncio.gather(*inflight)
    drain_wall = time.perf_counter() - t_start - arrival_wall
    snap = global_slo.snapshot()
    await handler.stop()
    gc.collect()

    per_class = {}
    for cls, entry in snap.items():
        req0, miss0 = count0.get(cls, (0.0, 0.0))
        requests = entry["requests"] - req0
        if not requests:
            continue
        per_class[cls] = {
            "ttft_p99_s": entry["ttft_p99_s"],
            "tpot_p99_s": entry["tpot_p99_s"],
            "e2e_p99_s": entry["e2e_p99_s"],
            "attainment": entry["attainment"],
            "burn_rate": entry["burn_rate"],
            "requests": int(requests),
            "missed": int(entry["missed"] - miss0),
            "targets": entry["targets"],
        }
    completed = outcomes.count("ok")
    offered_rps = sum(offered.values()) / arrival_wall
    # Saturation stamp: if completions couldn't keep pace with arrivals
    # (or the post-arrival drain dwarfs the run), the percentiles above
    # describe queueing collapse and the attainment headline must be
    # read with that caveat.
    saturated = bool(
        completed / arrival_wall < 0.8 * offered_rps
        or drain_wall > 0.5 * arrival_wall
    )
    return {
        "offered_rps": round(offered_rps, 2),
        "target_rps": rate_rps,
        "derated_rps": effective_rate if derate else None,
        "measured_capacity_rps": measured_capacity_rps,
        "saturated": saturated,
        "burst_factor": burst_factor,
        "duration_s": round(arrival_wall, 1),
        "drain_s": round(drain_wall, 1),
        "offered": offered,
        "completed": completed,
        "shed": outcomes.count("shed"),
        "errors": outcomes.count("error"),
        "classes": per_class,
        "model": cfg.model_name,
        "n_chips": n_chips,
    }


async def bench_autoconf(model_name, common, rate_rps, duration_s=10.0,
                         n_chips=1, seed=11):
    """AUTOCONF section (ISSUE 18): close the measurement→configuration
    loop end to end, twice over.

    **Knob half** — run the (widened) ``bench_slo`` workload under a
    small candidate-knob sweep with the SAME seed (same recorded
    arrival trace), capture the workload profiler's fingerprint during
    the default run, fit the cost model over the per-class sample
    points and ask it for a recommendation weighted by the measured
    class mix. The recommended and default sub-blocks are the measured
    runs for those two knob vectors — recommended must meet or beat
    default on the workload it was fitted to.

    **Forecast half** — a scripted burst trace (recurring 5× burst in a
    short synthetic 'day') replayed through ``ArrivalForecast`` with an
    injected clock, driving a real ``DynamicScaling`` over a simulated
    agent pool: with ``forecast_enabled`` capacity must move BEFORE the
    interactive burn rate crosses 1.0; with it off the scaler only
    reacts after. Pure simulation — no engine, so the result isolates
    the predictive term rather than CPU-bound decode noise.
    """
    from pilottai_tpu.core.config import (
        LLMConfig,
        ReliabilityConfig,
        ScalingConfig,
    )
    from pilottai_tpu.obs import global_profile
    from pilottai_tpu.obs.costmodel import CostModel
    from pilottai_tpu.obs.forecast import ArrivalForecast
    from pilottai_tpu.orchestration.scaling import DynamicScaling
    from pilottai_tpu.utils.compile_cache import load_profile, store_profile
    from pilottai_tpu.utils.metrics import MetricsRegistry

    # ------------------------------------------------------------------ #
    # Knob half: candidate sweep → samples + fingerprint → recommend.
    # ------------------------------------------------------------------ #
    # "default" is LLMConfig's field defaults for the modeled knobs (the
    # do-nothing config scripts/recommend.py diffs against); the other
    # two bracket it (more batching + a host KV tier vs a lean/small
    # vector) so the model has a real choice on both score axes.
    candidates = {
        "default": dict(engine_slots=8, engine_chunk=16, engine_speculate=0,
                        engine_prefix_cache=4, engine_kvcache_host_mb=0),
        "batchy": dict(engine_slots=16, engine_chunk=24, engine_speculate=0,
                       engine_prefix_cache=4, engine_kvcache_host_mb=64),
        "lean": dict(engine_slots=4, engine_chunk=8, engine_speculate=0,
                     engine_prefix_cache=2, engine_kvcache_host_mb=0),
    }
    runs = {}
    samples = []
    fingerprint = None
    for name, knobs in candidates.items():
        if name == "default":
            # Fingerprint the DEFAULT run: the profile describes the
            # workload as the un-tuned deployment sees it.
            global_profile.reset()
        run = await bench_slo(
            LLMConfig(
                model_name=model_name,
                reliability=ReliabilityConfig(max_queue_depth=256),
                **knobs, **common,
            ),
            rate_rps=rate_rps, duration_s=duration_s,
            n_chips=n_chips, seed=seed,
        )
        if name == "default":
            fingerprint = global_profile.fingerprint()
        steps_per_s = round(
            run["completed"] / max(run["duration_s"], 1e-9), 3
        )
        for cls, entry in (run.get("classes") or {}).items():
            samples.append({
                "knobs": knobs,
                "workload": cls,
                "metrics": {
                    "attainment": entry["attainment"],
                    "ttft_p99_s": entry["ttft_p99_s"],
                    "tpot_p99_s": entry["tpot_p99_s"],
                    "burn_rate": entry["burn_rate"],
                    "steps_per_s": steps_per_s,
                },
            })
        runs[name] = {
            "knobs": knobs,
            "steps_per_s": steps_per_s,
            "completed": run["completed"],
            "shed": run["shed"],
            "errors": run["errors"],
            "classes": run["classes"],
        }

    model = CostModel(samples=samples)
    rec = model.recommend(
        profile=fingerprint, default_knobs=candidates["default"]
    )
    rec_name = next(
        (n for n, k in candidates.items() if k == rec["knobs"]), None
    )
    # Persist fingerprint + recommendation into the profile store (next
    # to autotune.json) — the engine's boot check and recommend.py
    # --deployment both read from here.
    try:
        blob = load_profile(model_name) or {}
        blob["fingerprint"] = fingerprint
        blob["recommendation"] = {
            "knobs": rec["knobs"], "score": rec["score"],
            "predicted": rec["predicted"],
        }
        store_profile(model_name, blob)
    except Exception:  # noqa: BLE001 — the store is best-effort
        pass

    # ------------------------------------------------------------------ #
    # Forecast half: scripted recurring burst, forecast on vs off.
    # ------------------------------------------------------------------ #
    BUCKET_S, N_PHASES = 20.0, 30
    BASE_RPS, BURST_RPS = 4.0, 20.0
    BURST_PHASES = (18, 19, 20, 21)
    CAP_RPS_PER_AGENT = 4.0

    def _trace_rps(phase):
        return BURST_RPS if phase in BURST_PHASES else BASE_RPS

    async def _burst_sim(forecast_on):
        sim_now = [0.0]
        fc = ArrivalForecast(
            bucket_s=BUCKET_S, period_s=BUCKET_S * N_PHASES,
            alpha=0.5, gamma=0.5, clock=lambda: sim_now[0],
        )
        # Two synthetic 'days' of history teach the seasonal curve the
        # recurring burst; level settles at ~1.
        for b in range(2 * N_PHASES):
            sim_now[0] = b * BUCKET_S
            fc.ingest_bucket(
                int(_trace_rps(b % N_PHASES) * BUCKET_S), at=sim_now[0]
            )

        class _SimAgent:
            def __init__(self, util):
                self.queue_utilization = util
                self.current_tasks = []
                self.success_rate = 1.0
                self.status = "busy"  # never IDLE: sim never drains

                class _Q:
                    @staticmethod
                    def qsize():
                        return 1

                self.task_queue = _Q()

        class _SimOrch:
            def __init__(self, n):
                self.agents = {f"a{i}": object() for i in range(n)}
                self.task_queue = []
                self.running_tasks = {}
                self.config = type(
                    "C", (), {"max_queue_size": 100,
                              "max_concurrent_tasks": 16},
                )()
                self.util = 0.0

            def agent_list(self):
                return [_SimAgent(self.util) for _ in self.agents]

            async def create_agent(self, agent_type):
                aid = f"a{len(self.agents)}"
                self.agents[aid] = object()
                return type("A", (), {"id": aid})()

            async def remove_agent(self, aid):
                self.agents.pop(aid, None)

        orch = _SimOrch(2)
        reg = MetricsRegistry()
        scaler = DynamicScaling(
            orch,
            ScalingConfig(
                min_agents=2, max_agents=10, cooldown=0.0,
                forecast_enabled=forecast_on,
                # 3 buckets of lead: the scaler sees the learned burst
                # while the trace is still at base rate. Cap 4 ≈ the
                # burst/base ratio (the boost a 5x recurring burst
                # actually warrants) so the pre-scale can finish before
                # the burst instead of stalling one agent short.
                forecast_lead_s=3 * BUCKET_S,
                forecast_boost_cap=4.0,
            ),
            registry=reg, forecast=fc,
        )
        backlog = 0.0
        first_up = None
        burn_cross = None
        agents_at_burst = None
        peak_burn = 0.0
        # Day 3: tick per bucket. Demand beyond pool capacity queues;
        # queued interactive work past one tick is an SLO miss, and the
        # miss fraction over the 1% budget is the burn rate.
        for b in range(2 * N_PHASES, 3 * N_PHASES):
            phase = b % N_PHASES
            sim_now[0] = b * BUCKET_S
            if phase == BURST_PHASES[0] and agents_at_burst is None:
                agents_at_burst = len(orch.agents)
            demand = _trace_rps(phase) * BUCKET_S
            fc.observe(at=sim_now[0], n=int(demand))
            capacity = len(orch.agents) * CAP_RPS_PER_AGENT * BUCKET_S
            served = min(backlog + demand, capacity)
            backlog = backlog + demand - served
            miss_frac = backlog / max(demand, 1.0)
            burn = min(miss_frac / 0.01, 50.0)
            peak_burn = max(peak_burn, burn)
            reg.set_gauge("slo.interactive.burn_rate", burn)
            orch.util = min((backlog + demand) / max(capacity, 1.0), 1.0)
            decision = await scaler.scale_once()
            if decision == "up" and first_up is None:
                first_up = phase
            if burn > 1.0 and burn_cross is None:
                burn_cross = phase
        return {
            "forecast_enabled": forecast_on,
            "first_scale_up_phase": first_up,
            "burn_exceeds_1_phase": burn_cross,
            "burst_start_phase": BURST_PHASES[0],
            "scaled_before_burn": (
                first_up is not None
                and (burn_cross is None or first_up < burn_cross)
            ),
            "agents_at_burst_start": agents_at_burst,
            "peak_burn": round(peak_burn, 2),
            "final_agents": len(orch.agents),
            "forecast_lead_s": 3 * BUCKET_S,
            "bucket_s": BUCKET_S,
        }

    fc_on = await _burst_sim(True)
    fc_off = await _burst_sim(False)
    # Measured lead: how many seconds before the burst the forecast-on
    # run moved capacity (None if it never scaled).
    lead = (
        (fc_on["burst_start_phase"] - fc_on["first_scale_up_phase"])
        * BUCKET_S
        if fc_on["first_scale_up_phase"] is not None else None
    )

    return {
        "workload": {
            "rate_rps": rate_rps, "duration_s": duration_s, "seed": seed,
            "model": model_name, "n_chips": n_chips,
            "tenants": ["chat", "sessions", "rag", "toolloop"],
        },
        "candidates": runs,
        "samples": samples,
        "profile": fingerprint,
        "recommendation": rec,
        "recommended": {"name": rec_name, **(runs.get(rec_name) or {})},
        "default": runs["default"],
        "forecast": {"on": fc_on, "off": fc_off},
        "forecast_lead_s": lead,
        "caveats": [
            "CPU runs: absolute steps/s and percentiles are not TPU "
            "numbers; the section's claims are relative (recommended vs "
            "default on the same recorded workload, forecast on vs off "
            "on the same scripted trace).",
            "recommended/default sub-blocks are the measured candidate "
            "runs (same seed = same arrival trace), not a re-run.",
        ] if common.get("provider") != "tpu" else [
            "recommended/default sub-blocks are the measured candidate "
            "runs (same seed = same arrival trace), not a re-run.",
        ],
    }


async def bench_recovery(cfg, n_requests=6, max_new_tokens=48):
    """RECOVERY section (ISSUE 9): scripted single-fault soak. A wave of
    greedy requests decodes concurrently; one injected ``engine.step``
    failure lands mid-decode (``skip=1`` lets the first dispatch through
    so real tokens have folded); every in-flight request must complete
    through the engine's in-flight recovery with output byte-identical
    to an uninjected reference wave. Reports ``recovered_frac`` (1.0 =
    every requeued request completed), ``recovery_ms`` p50/p99
    (fault-snapshot → re-admission wall) and ``tokens_replayed`` (tokens
    re-prefilled over prompt+generated)."""
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.reliability import global_injector
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    handler = LLMHandler(cfg)
    await handler.start()
    try:
        prompts = [_prompt(9000 + i) for i in range(n_requests)]

        async def wave():
            return await asyncio.gather(*[
                handler.apredict(
                    p,
                    params=GenerationParams(
                        max_new_tokens=max_new_tokens, temperature=0.0,
                    ),
                )
                for p in prompts
            ], return_exceptions=True)

        base = await wave()
        counters = (
            "engine.recovery_requeued", "engine.recovered_requests",
            "engine.recovery_failed", "engine.tokens_replayed",
            "engine.rebuilds",
        )
        before = {k: _gm.get(k) for k in counters}
        _gm.reset_histograms("engine.recovery_ms")
        global_injector.arm(
            "engine.step", RuntimeError("bench-injected device fault"),
            times=1, skip=1,
        )
        t0 = time.perf_counter()
        injected = await wave()
        wall = time.perf_counter() - t0
        delta = {k: _gm.get(k) - before[k] for k in counters}
        errors = sum(isinstance(o, Exception) for o in injected)
        identical = sum(
            1 for a, b in zip(base, injected)
            if not isinstance(b, Exception) and a == b
        )
        hist = (_gm.snapshot()["histograms"].get("engine.recovery_ms")
                or {})
        requeued = delta["engine.recovery_requeued"]
        return {
            # 1.0 ⇔ every request the fault interrupted completed anyway.
            "recovered_frac": (
                round(delta["engine.recovered_requests"] / requeued, 4)
                if requeued else (1.0 if errors == 0 else 0.0)
            ),
            "outputs_identical": identical == n_requests,
            "client_errors": errors,
            "requests": n_requests,
            "requeued": int(requeued),
            "recovery_failed": int(delta["engine.recovery_failed"]),
            "recovery_ms_p50": hist.get("p50"),
            "recovery_ms_p99": hist.get("p99"),
            "tokens_replayed": int(delta["engine.tokens_replayed"]),
            "rebuilds": int(delta["engine.rebuilds"]),
            "fault_fired": global_injector.fired("engine.step") > 0,
            "wall_s": round(wall, 2),
            "model": cfg.model_name,
        }
    finally:
        global_injector.disarm("engine.step")
        await handler.stop()
        gc.collect()


async def bench_kvcache(cfg, n_sessions=6, turns=3, max_new_tokens=24):
    """KVCACHE section (ISSUE 10): multi-turn session workload against
    the global KV cache tier. ``n_sessions`` conversations interleave
    round-robin, each turn re-sending the session's full transcript
    (the multi-turn agent shape). The device-resident store is
    deliberately tiny (``engine_prefix_cache`` in cfg), so by the time
    a session's next turn arrives its entry has been evicted — and with
    the host tier enabled the eviction SPILLED instead of discarding,
    so the resume restores from host RAM and prefills only the new
    tail. Headlines: ``prefix_hit_rate`` (hits ÷ lookups; > 0 on resume
    after eviction is the acceptance bar), ``prefill_tokens_saved`` and
    restore p50/p99 (host-side staging wall). Greedy parity tier on/off
    is pinned by tests/test_kvcache.py, not re-measured here."""
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    handler = LLMHandler(cfg)
    await handler.start()
    try:
        # Per-session preambles diverge immediately (distinct lineages:
        # cross-session LCP entries must not mask the cold tier) and
        # clear the store's 64-token entry floor on their own.
        def preamble(s):
            return (
                f"Session {s:03d} memory: persona agent-{s}; "
                f"goals g{s * 7}, g{s * 11}; constraints c{s * 13}. "
                + PREAMBLE
            )

        history = {s: "" for s in range(n_sessions)}
        counters = (
            "lookups", "hits", "host_hits", "spills", "restores",
            "prefill_tokens_saved",
        )
        before = {
            k: _gm.get(f"engine.kvcache.{k}") for k in counters
        }
        _gm.reset_histograms("engine.kvcache.restore_ms")
        t0 = time.perf_counter()
        for turn in range(turns):
            for s in range(n_sessions):
                prompt = (
                    preamble(s) + history[s]
                    + f"\nuser: next step for item {turn}?\nassistant:"
                )
                params = GenerationParams(
                    max_new_tokens=max_new_tokens, temperature=0.0,
                    session_id=f"bench-sess-{s}",
                )
                reply = await handler.apredict(prompt, params=params)
                history[s] += (
                    f"\nuser: next step for item {turn}?"
                    f"\nassistant: {reply}"
                )
        wall = time.perf_counter() - t0
        delta = {
            k: _gm.get(f"engine.kvcache.{k}") - before[k] for k in counters
        }
        hist = (
            _gm.snapshot()["histograms"].get("engine.kvcache.restore_ms")
            or {}
        )
        return {
            "prefix_hit_rate": round(
                delta["lookups"] and delta["hits"] / delta["lookups"], 4
            ),
            "prefill_tokens_saved": int(delta["prefill_tokens_saved"]),
            "host_hits": int(delta["host_hits"]),
            "spills": int(delta["spills"]),
            "restores": int(delta["restores"]),
            "restore_ms_p50": hist.get("p50"),
            "restore_ms_p99": hist.get("p99"),
            "host_bytes": int(_gm.get("engine.kvcache.host_bytes")),
            "sessions": n_sessions,
            "turns": turns,
            "requests": n_sessions * turns,
            "wall_s": round(wall, 2),
            "model": cfg.model_name,
        }
    finally:
        await handler.stop()
        gc.collect()


async def bench_cell(cfg, n_replicas=3, rate_rps=8.0, duration_s=12.0,
                     single_rps=None, n_sessions=6, seed=11, n_chips=1):
    """CELL section (ISSUE 11): an N-replica serving cell under the
    ``bench_slo`` open-loop harness at a deliberate overload — the
    offered rate is ≥10× what ONE engine absorbs, so the section shows
    the cell doing its actual job: KV-affinity routing (sessionful
    tenants pin to their replica; ``affinity_hit_rate``), per-class
    SLO-aware shedding at the cell boundary (``classes.*.shed`` — batch
    sheds first, interactive is defended), a scripted mid-soak session
    migration and a scripted replica drain with session KV moving in
    the host tier's transfer format. Headline: interactive attainment
    at the overload, affinity hit rate, per-class shed counts."""
    import random as _random

    from pilottai_tpu.distributed import ServingCell
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.reliability import EngineOverloaded
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    cell = ServingCell([LLMHandler(cfg) for _ in range(n_replicas)])
    await cell.start()
    rng = _random.Random(seed)
    uid = [0]

    def session_prompt(k):
        # Stable per-session transcript head: the routing table's
        # affinity primitive (same bytes → same radix path) and the
        # engine tier's lineage in one.
        return (
            f"Session cell-{k:02d} memory: persona agent-{k}; "
            + PREAMBLE + f"continue thread {k}"
        )

    # (name, weight, slo_class, max_new_tokens, session_k)
    tenants = [
        ("chat", 0.4, "interactive", 24, None),
        ("session", 0.4, "interactive", 24, "cycle"),
        ("batch", 0.2, "batch", 32, None),
    ]

    async def one(tenant, warm=False):
        name, _, slo_class, max_new, kind = tenant
        uid[0] += 1
        sid = None
        if kind == "cycle":
            k = uid[0] % n_sessions
            prompt = session_prompt(k)
            sid = f"cellbench-{k}"
        else:
            prompt = _prompt(uid[0])
        params = GenerationParams(
            max_new_tokens=max_new, temperature=0.0, slo_class=slo_class,
            session_id=sid,
        )
        try:
            await cell.apredict(prompt, params=params)
            return "ok"
        except EngineOverloaded:
            return "shed"
        except Exception as exc:  # noqa: BLE001 — harness keeps running
            if not warm:
                _note("cell request FAILED", {"tenant": name,
                                              "error": str(exc)[:200]})
            return "error"

    # Warm every replica (compiles + one session turn each).
    for tenant in tenants:
        await asyncio.gather(*[one(tenant, warm=True) for _ in range(
            n_replicas)])

    counters = (
        "cell.routed.interactive", "cell.routed.batch",
        "cell.shed.interactive", "cell.shed.batch",
        "cell.affinity_lookups", "cell.affinity_hits",
        "cell.migrations", "cell.migrated_tokens", "cell.rerouted",
    )
    before = {k: _gm.get(k) for k in counters}
    _gm.reset_histograms("cell.migration_ms")
    _gm.reset_histograms("cell.drain_s")
    for rep in cell.replicas.values():
        rep.slo.reset()
    # reset() clears the rolling windows (attainment/burn are
    # section-pure from here) but requests/missed are cumulative
    # registry counters — report section DELTAS, same discipline as
    # bench_slo.
    slo0 = cell.slo_snapshot()["classes"]

    names = [t[0] for t in tenants]
    weights = [t[1] for t in tenants]
    t_start = time.perf_counter()
    t_end = t_start + duration_s
    inflight: list = []
    offered = {n: 0 for n in names}
    migrated = None
    drained = None
    drain_task = None
    next_at = t_start
    while True:
        now = time.perf_counter()
        frac = (now - t_start) / duration_s
        if now >= t_end:
            break
        if migrated is None and frac >= 0.4 and cell.sessions:
            # Scripted rebalance: move one hot session's KV lineage.
            sid = sorted(cell.sessions)[0]
            try:
                migrated = await cell.migrate_session(sid)
            except Exception as exc:  # noqa: BLE001 — report, keep going
                migrated = {"error": str(exc)}
        if drained is None and frac >= 0.6:
            # Scripted zero-downtime drain of one replica mid-soak; its
            # sessions migrate, its in-flight work re-admits elsewhere.
            rid = next(iter(cell.replicas))
            drained = rid
            drain_task = asyncio.create_task(cell.drain(rid, grace_s=1.0))
        # Catch-up arrivals: spawn every arrival whose Poisson time has
        # come. Open-loop means arrivals wait for NOTHING — not for
        # completions, and not for the event loop's sleep granularity
        # (a per-arrival sleep silently caps the offered rate at the
        # loop's wakeup resolution, diluting the overload the section
        # exists to demonstrate).
        while next_at <= now and next_at < t_end:
            tenant = rng.choices(tenants, weights=weights, k=1)[0]
            offered[tenant[0]] += 1
            inflight.append(asyncio.create_task(one(tenant)))
            next_at += rng.expovariate(max(rate_rps, 1e-3))
        await asyncio.sleep(min(max(next_at - now, 0.0), 0.02))
    arrival_wall = time.perf_counter() - t_start
    outcomes = await asyncio.gather(*inflight)
    if drain_task is not None:
        drain_report = await drain_task
    else:
        drain_report = None
    drain_wall = time.perf_counter() - t_start - arrival_wall
    slo = cell.slo_snapshot()
    delta = {k: _gm.get(k) - before[k] for k in counters}
    mig_hist = (_gm.snapshot()["histograms"].get("cell.migration_ms")
                or {})
    await cell.stop()
    gc.collect()

    classes = {}
    for cls, entry in (slo.get("classes") or {}).items():
        base = slo0.get(cls) or {}
        requests = int(entry["requests"] - base.get("requests", 0))
        if not requests:
            continue
        classes[cls] = {
            "attainment": entry["attainment"],
            "burn_rate": entry["burn_rate"],
            "requests": requests,
            "missed": int(entry["missed"] - base.get("missed", 0)),
            "e2e_p99_s": entry.get("e2e_p99_s"),
            "routed": int(delta.get(f"cell.routed.{cls}", 0)),
            "shed": int(delta.get(f"cell.shed.{cls}", 0)),
        }
    lookups = delta["cell.affinity_lookups"]
    offered_rps = sum(offered.values()) / arrival_wall
    return {
        "replicas": n_replicas,
        "offered_rps": round(offered_rps, 2),
        "target_rps": rate_rps,
        "duration_s": round(arrival_wall, 1),
        "drain_wall_s": round(drain_wall, 1),
        # The overload multiple: offered load vs what ONE engine
        # sustains closed-loop (the 1B/tiny section's measured rate).
        "single_engine_rps": single_rps,
        "load_multiple": (
            round(offered_rps / single_rps, 1) if single_rps else None
        ),
        "offered": offered,
        "completed": outcomes.count("ok"),
        "shed": outcomes.count("shed"),
        "errors": outcomes.count("error"),
        "affinity_hit_rate": round(
            delta["cell.affinity_hits"] / lookups, 4
        ) if lookups else None,
        "rerouted": int(delta["cell.rerouted"]),
        "migrations": int(delta["cell.migrations"]),
        "migrated_tokens": int(delta["cell.migrated_tokens"]),
        "migration_ms_p50": mig_hist.get("p50"),
        "migration_ms_p99": mig_hist.get("p99"),
        "drained_replica": drained,
        "drain_s": (drain_report or {}).get("drain_s"),
        "drain_readmitted": (drain_report or {}).get("readmitted"),
        "drain_migrated_sessions": (
            (drain_report or {}).get("migrated_sessions")
        ),
        "classes": classes,
        "model": cfg.model_name,
        "n_chips": n_chips,
    }


async def bench_disagg(cfg, rate_rps, prefill_rps, duration_s=6.0,
                       n_sessions=4, seed=13, n_chips=1):
    """DISAGG section (ISSUE 19): the same mixed workload — sticky
    interactive sessions (decode-heavy) plus a stream of long cold RAG
    prefills — against a 2-replica cell COLOCATED (both mixed) and then
    DISAGGREGATED (``1p1d``). Each run measures two phases: decode
    traffic alone (baseline TPOT), then decode traffic with the long
    prefills running concurrently. The headline is the interference
    ratio — mixed-phase interactive TPOT p99 over baseline — which
    disaggregation must hold closer to 1.0 than colocation: the prefill
    tier absorbs the chunked prefill work, the decode tier restores the
    handed-off KV and only decodes. Handoff health rides along:
    ``handoff_success`` ((handoffs - fallbacks) / handoffs) and the
    ``cell.handoff_ms`` p50/p99.

    Caveat (stamped as ``host_cores`` / ``isolation_measurable``):
    in-process replicas share the host's cores, so on a single-core
    CPU host the prefill work steals the decode tier's cycles through
    the OS scheduler no matter which replica runs it — the interference
    ratios then read as parity and the measurable claims are handoff
    health + tier routing; the TPOT separation needs per-replica
    silicon (accelerator hosts, or a multi-core CPU host).

    TPOT percentiles come from the SLO tracker's flight listener. The
    1-token prefill legs of handoffs contribute no TPOT sample (TPOT
    needs a second token), so the interference axis is clean; their
    TTFT samples do land in the interactive pool, so the disagg run's
    TTFT p99 reads as the p99 over client requests AND prefill legs —
    a mild downward dilution, called out here rather than filtered."""
    import random as _random

    from pilottai_tpu.distributed import ServingCell
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.obs import global_slo
    from pilottai_tpu.reliability import EngineOverloaded
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    counters = (
        "cell.handoffs", "cell.handoff_fallbacks", "cell.handoff_rejected",
        "cell.handoff_tokens", "cell.tier.prefill_routed",
        "cell.tier.decode_routed", "cell.tier.bypass",
    )

    async def _run(disagg):
        cell = ServingCell(
            [LLMHandler(cfg) for _ in range(2)],
            cell_disagg="1p1d" if disagg else None,
        )
        await cell.start()
        rng = _random.Random(seed)
        uid = [0]
        session_log: dict = {}

        async def decode_turn(k):
            uid[0] += 1
            log = session_log.setdefault(k, [
                f"Session disagg-{k:02d} memory: persona agent-{k}; "
                + f"context: thread {k} telemetry baseline; " * 3
            ])
            log.append(f"turn {len(log)}: user question {uid[0]}")
            if len(log) > 6:
                # Bound transcript growth but keep the head line — it
                # carries the session's routing-table identity.
                del log[1:len(log) - 5]
            params = GenerationParams(
                max_new_tokens=16, temperature=0.0,
                slo_class="interactive", session_id=f"disagg-sess-{k}",
            )
            try:
                await cell.apredict("\n".join(log), params=params)
                return "ok"
            except EngineOverloaded:
                return "shed"
            except Exception as exc:  # noqa: BLE001 — harness runs on
                _note("disagg decode FAILED", {"error": str(exc)[:200]})
                return "error"

        async def rag_one():
            uid[0] += 1
            # Unique per-request body: a shared preamble would go
            # prefix-hot after the first arrival and bypass the prefill
            # tier — the section exists to measure the handoff path.
            seg = f"retrieved shard {uid[0]}: fleet telemetry chunk; "
            # 420 + suffix + chat-template overhead stays under the
            # handoff keep-window (engine_max_seq - 1 - max_new_tokens):
            # a longer body is non-migratable and serves colocated.
            body = (seg * 12)[:420] + f" summarize incident {uid[0]}."
            params = GenerationParams(
                max_new_tokens=8, temperature=0.0, slo_class="batch",
            )
            try:
                await cell.apredict(body, params=params)
                return "ok"
            except EngineOverloaded:
                return "shed"
            except Exception as exc:  # noqa: BLE001 — harness runs on
                _note("disagg rag FAILED", {"error": str(exc)[:200]})
                return "error"

        # Warm: establish every session's pin (first turns hand off on
        # the disagg run) and compile the decode + RAG prefill shapes.
        # Seven rounds, not one — transcripts grow until the 6-line
        # bound and walk through new prefill buckets on the way; a
        # compile landing inside the baseline phase would dominate its
        # TPOT p99 (the first topology run pays all compiles for both
        # otherwise).
        for _ in range(7):
            await asyncio.gather(*[decode_turn(k) for k in range(n_sessions)])
            await rag_one()

        before = {k: _gm.get(k) for k in counters}
        _gm.reset_histograms("cell.handoff_ms")

        async def phase(with_prefills):
            global_slo.reset()
            _gm.reset_histograms("request.")
            rag_offered = [0]
            t0 = time.perf_counter()
            t_end = t0 + duration_s
            inflight: list = []
            next_dec = t0
            next_rag = t0
            while True:
                now = time.perf_counter()
                if now >= t_end:
                    break
                while next_dec <= now and next_dec < t_end:
                    inflight.append(asyncio.create_task(
                        decode_turn(rng.randrange(n_sessions))
                    ))
                    next_dec += rng.expovariate(max(rate_rps, 1e-3))
                while with_prefills and next_rag <= now and next_rag < t_end:
                    rag_offered[0] += 1
                    inflight.append(asyncio.create_task(rag_one()))
                    next_rag += rng.expovariate(max(prefill_rps, 1e-3))
                nxt = min(next_dec, next_rag) if with_prefills else next_dec
                await asyncio.sleep(min(max(nxt - now, 0.0), 0.02))
            outcomes = await asyncio.gather(*inflight)
            inter = (global_slo.snapshot() or {}).get("interactive") or {}
            return {
                "offered": len(outcomes),
                "rag_offered": rag_offered[0],
                "completed": outcomes.count("ok"),
                "shed": outcomes.count("shed"),
                "errors": outcomes.count("error"),
                "ttft_p99_s": inter.get("ttft_p99_s"),
                "tpot_p50_s": inter.get("tpot_p50_s"),
                "tpot_p99_s": inter.get("tpot_p99_s"),
                "e2e_p99_s": inter.get("e2e_p99_s"),
                "attainment": inter.get("attainment"),
            }

        base = await phase(False)
        mixed = await phase(True)
        delta = {k: _gm.get(k) - before[k] for k in counters}
        hand_hist = (
            _gm.snapshot()["histograms"].get("cell.handoff_ms") or {}
        )
        await cell.stop()
        gc.collect()

        tp_base = base.get("tpot_p99_s")
        tp_mixed = mixed.get("tpot_p99_s")
        tp50_base = base.get("tpot_p50_s")
        tp50_mixed = mixed.get("tpot_p50_s")
        handoffs = int(delta["cell.handoffs"])
        fallbacks = int(delta["cell.handoff_fallbacks"])
        return {
            "topology": "1p1d" if disagg else "colocated",
            "baseline": base,
            "mixed": mixed,
            "tpot_interference": (
                round(tp_mixed / tp_base, 3)
                if tp_base and tp_mixed else None
            ),
            # p50-based secondary: far fewer samples land in a short
            # phase's p99 (it degenerates toward the max), so the p50
            # ratio is the stabler read on a noisy host.
            "tpot_interference_p50": (
                round(tp50_mixed / tp50_base, 3)
                if tp50_base and tp50_mixed else None
            ),
            "handoffs": handoffs,
            "handoff_fallbacks": fallbacks,
            "handoff_rejected": int(delta["cell.handoff_rejected"]),
            "handoff_tokens": int(delta["cell.handoff_tokens"]),
            "handoff_success": (
                round((handoffs - fallbacks) / handoffs, 4)
                if handoffs else None
            ),
            "handoff_ms_p50": hand_hist.get("p50"),
            "handoff_ms_p99": hand_hist.get("p99"),
            "prefill_routed": int(delta["cell.tier.prefill_routed"]),
            "decode_routed": int(delta["cell.tier.decode_routed"]),
            "prefix_bypass": int(delta["cell.tier.bypass"]),
        }

    colocated = await _run(False)
    disagg = await _run(True)
    import os as _os

    host_cores = len(_os.sched_getaffinity(0)) if hasattr(
        _os, "sched_getaffinity") else (_os.cpu_count() or 1)
    return {
        "colocated": colocated,
        "disagg": disagg,
        "rate_rps": rate_rps,
        "prefill_rps": prefill_rps,
        "duration_s": duration_s,
        # Honesty stamp: in-process replicas timeshare the host's
        # cores. On a single-core host the compute-isolation half of
        # disaggregation is physically invisible (both topologies burn
        # the same core) and the interference ratios read as parity —
        # the split shows up in handoff health, tier routing and slot
        # separation; the TPOT win needs per-replica silicon.
        "host_cores": host_cores,
        "isolation_measurable": host_cores > 1,
        "model": cfg.model_name,
        "n_chips": n_chips,
    }


async def bench_pipeline(provider: str, rounds: int = 4):
    """BASELINE config #3 through the orchestrator: Serve + manager + 3
    specialists on the document pipeline, real engine, measured at
    ``Serve.execute`` granularity (routing, evaluation, retry and
    journaling included)."""
    from examples.document_pipeline.pipeline import (
        SAMPLE_DOC,
        build_pipeline,
        stage_tasks,
    )

    serve, _memory = build_pipeline(provider=provider)
    # The trained protocol model completes a stage in one tool step +
    # one completion step; two iterations is that realistic shape (and
    # keeps a missing-checkpoint fallback from measuring the
    # max_iterations=20 cap instead of the orchestrator).
    for a in serve.agents.values():
        a.config.max_iterations = 2
    _reset_task_attribution()
    await serve.start()
    try:
        waves = []
        task_lat = []
        ok = total = 0
        for r in range(rounds + 1):  # round 0 is warmup/compile
            tasks = stage_tasks(
                str(SAMPLE_DOC), f"What are the key findings? (round {r})"
            )
            t0 = time.perf_counter()
            results = await serve.execute(list(tasks))
            wall = time.perf_counter() - t0
            if r == 0:
                # Warmup-pure attribution: round 0's compile-inflated
                # task times must not land in the section fractions.
                _reset_task_attribution()
            if r > 0:
                waves.append(wall)
                ok += sum(1 for res in results if res.success)
                total += len(results)
                task_lat += [
                    res.execution_time for res in results
                    if res.execution_time
                ]
        # Capture while the agents are still registered — stop()
        # retires each role from the occupancy tracker.
        attribution = _task_attribution("pipeline")
    finally:
        await serve.stop()
    gc.collect()
    from pilottai_tpu.train.protocol import has_checkpoint

    return {
        "pipeline_p50_ms": round(statistics.median(task_lat) * 1000.0, 1),
        "pipeline_wall_s": round(statistics.median(waves), 2),
        "pipeline_success": f"{ok}/{total}",
        "rounds": rounds,
        "stages_per_round": len(tasks),
        "pipeline_model": "protocol-s" if provider != "mock" else "mock",
        "pipeline_trained_checkpoint": has_checkpoint(),
        # Orchestrator-cost curve (obs/dag.py): how much of summed task
        # e2e the orchestration layer itself ate, and how busy each
        # specialist actually was — tracked alongside steps/s and MFU.
        **attribution,
    }


async def bench_swarm(model: str, provider: str, n_agents: int = 32,
                      n_tasks: int = 96):
    """BASELINE config #4 through the orchestrator: a swarm of agents on
    one Serve sharing a single engine. Reports LLM agent-steps/s (the
    analyze/evaluate/step calls Serve's task flow actually makes) and
    task-completion p50 through ``Serve.execute_task``."""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, LLMConfig, ServeConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.serve import Serve
    from pilottai_tpu.utils.metrics import global_metrics

    from pilottai_tpu.core.config import SamplingConfig
    from pilottai_tpu.train.protocol import (
        DEFAULT_CHECKPOINT,
        SERVE_MAX_NEW,
        SERVE_MAX_SEQ,
        has_checkpoint,
    )

    has_ckpt = has_checkpoint()
    llm = LLMHandler(LLMConfig(
        model_name=model, provider=provider,
        # The in-tree-trained protocol checkpoint: agents make their
        # decisions from real decoded tokens and tasks SUCCEED
        # (train/protocol.py; random weights without it — reported).
        checkpoint_path=str(DEFAULT_CHECKPOINT) if has_ckpt else None,
        # Swarm traffic trickles in (each task's calls are sequential),
        # so admission groups stay small — admit_batch at n_agents would
        # pad every 1-4 arrivals to 32 prefill rows.
        engine_slots=n_agents, engine_admit_batch=8,
        engine_max_seq=SERVE_MAX_SEQ, engine_chunk=16,
        dtype="bfloat16" if provider == "tpu" else "float32",
        engine_speculate=4,
        sampling=SamplingConfig(temperature=0.0, max_new_tokens=SERVE_MAX_NEW),
    ))
    agents = [
        BaseAgent(
            config=AgentConfig(
                role=f"worker{i}", specializations=["generic"],
                max_iterations=2,  # see bench_pipeline's note
            ),
            llm=llm,
        )
        for i in range(n_agents)
    ]
    serve = Serve(
        name="swarm-bench", agents=agents, manager_llm=llm,
        config=ServeConfig(
            decomposition_enabled=False, max_concurrent_tasks=n_agents,
        ),
    )
    await serve.start()
    try:
        # Warmup wave (compiles + acceptance EMA).
        await asyncio.gather(*[
            serve.execute_task(f"warm task {i}") for i in range(n_agents)
        ])
        # Task attribution is section-pure AND warmup-pure: the compile
        # wave's inflated task times must not land in the overhead or
        # busy_frac fractions.
        _reset_task_attribution()
        c0 = global_metrics.get("engine.completed")
        t0 = time.perf_counter()
        results = await asyncio.gather(*[
            serve.execute_task(f"swarm task {i}: check inventory {i}")
            for i in range(n_tasks)
        ])
        wall = time.perf_counter() - t0
        llm_steps = global_metrics.get("engine.completed") - c0
        lat = [r.execution_time for r in results if r.execution_time]
        ok = sum(1 for r in results if r.success)
        attribution = _task_attribution("swarm")  # before stop() retires roles
    finally:
        await serve.stop()
    gc.collect()
    return {
        "swarm_steps_per_sec": round(llm_steps / wall, 2),
        "swarm_task_p50_ms": round(statistics.median(lat) * 1000.0, 1),
        "swarm_tasks_per_sec": round(n_tasks / wall, 2),
        "swarm_success": f"{ok}/{n_tasks}",
        "agents": n_agents,
        "swarm_model": model,
        "swarm_trained_checkpoint": has_ckpt,
        **attribution,
    }


async def bench_sched(model, provider, n_waves=4, gang=3, n_bg=6,
                      max_iterations=1):
    """SCHED section (ISSUE 12 / ROADMAP item 4): the DAG-aware
    scheduler's on-vs-off comparison on ONE workload — fan-out waves of
    ``gang`` HIGH-priority sibling tasks (gang-tagged, rolled up under
    a synthetic parent dag so PR 7's straggler/critical-path
    attribution applies) contending with LOW-priority background
    traffic on a deliberately saturated engine (2 slots), run twice:
    ``engine_sched_policy="fifo"`` + scheduler policy off, then
    ``"dag"`` + policy on.

    Reported per mode, in PR 7's field shapes:

    * ``swarm_straggler_frac`` — Σ parent ``straggler_s`` ÷ Σ task
      ``e2e_s`` (the task.* histograms, section-pure): the price of
      each join waiting on its slowest branch. Gang admission +
      critical-path priority attack exactly this.
    * ``swarm_critical_path_frac`` — Σ parent ``critical_path_s`` ÷ Σ
      task ``e2e_s``: the PARENT's wall (its critical path ≈ the
      fan-out's makespan) as a fraction of all task time spent. More
      parallel efficiency → smaller numerator on the same work.

    The acceptance bar (ISSUE 12): both lower with the scheduler on,
    greedy outputs byte-identical on/off (pinned by
    tests/test_sched.py, not re-measured here), and scheduler-on task
    success ≥ scheduler-off (tests/test_mini_swarm.py CI lane)."""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import (
        AgentConfig,
        LLMConfig,
        SamplingConfig,
        ServeConfig,
    )
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.obs.dag import global_dag
    from pilottai_tpu.sched import global_scheduler
    from pilottai_tpu.serve import Serve
    from pilottai_tpu.train.protocol import (
        DEFAULT_CHECKPOINT,
        SERVE_MAX_NEW,
        SERVE_MAX_SEQ,
        has_checkpoint,
    )
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    has_ckpt = has_checkpoint()
    counters = (
        "sched.gang_admits", "sched.gang_partial", "sched.priority_aged",
        "sched.priority_boosts", "sched.prewarms", "sched.prewarm_hits",
    )
    out = {
        "waves": n_waves, "gang": gang, "background_per_wave": n_bg,
        "model": model if has_ckpt or provider == "tpu" else "untrained",
    }
    try:
        for mode in ("off", "on"):
            global_scheduler.configure(policy="dag" if mode == "on" else "off")
            global_scheduler.reset()
            global_dag.reset()
            from pilottai_tpu.engine.handler import LLMHandler

            llm = LLMHandler(LLMConfig(
                model_name=model, provider=provider,
                checkpoint_path=str(DEFAULT_CHECKPOINT) if has_ckpt else None,
                # Small on purpose: the scheduler only matters when an
                # engine backlog exists — two slots against gang +
                # background concurrency keeps a backlog standing for
                # the whole wave, so admission ORDER (the thing under
                # test) is what decides who progresses.
                engine_slots=2, engine_admit_batch=2,
                engine_max_seq=SERVE_MAX_SEQ, engine_chunk=16,
                dtype="bfloat16" if provider == "tpu" else "float32",
                engine_sched_policy="dag" if mode == "on" else "fifo",
                # The aging floor must scale with service time: at the
                # default 2 s a LOW background call ages to CRITICAL
                # within ONE slow-engine LLM call and neutralizes the
                # priority signal this section exists to measure. 30 s
                # still guarantees no starvation across the run.
                engine_priority_aging_s=30.0,
                # Gang wait sized to the fan-out's emission spread (the
                # siblings below arrive ~0.3 s apart, as a real
                # decomposition emits them): the gang holds until its
                # siblings are present — or this bound — then admits
                # together ahead of the background.
                engine_gang_wait_ms=1500.0,
                # Pre-warm needs the KV cache tier; tiny hot store so
                # the cold tier actually serves.
                engine_prefix_cache=2, engine_kvcache_host_mb=64,
                sampling=SamplingConfig(
                    temperature=0.0, max_new_tokens=SERVE_MAX_NEW
                ),
            ))
            agents = [
                BaseAgent(
                    config=AgentConfig(
                        role=f"worker{i}", specializations=["generic"],
                        max_iterations=max_iterations,
                    ),
                    llm=llm,
                )
                for i in range(gang + n_bg)
            ]
            serve = Serve(
                name=f"sched-bench-{mode}", agents=agents, manager_llm=llm,
                config=ServeConfig(
                    decomposition_enabled=False,
                    max_concurrent_tasks=gang + n_bg,
                ),
            )
            await serve.start()
            try:
                # Warmup: compiles + the scheduler's stage model (two
                # tasks per role teach the stage transitions and
                # converge the pre-warm prefixes).
                await asyncio.gather(*[
                    serve.execute_task(f"warm task {i}")
                    for i in range(gang + n_bg)
                ])
                _reset_task_attribution()
                before = {k: _gm.get(k) for k in counters}
                steps0 = _gm.get("engine.completed")
                parent_bd = []
                wave_walls = []
                ok = total = 0
                t0 = time.perf_counter()
                for w in range(n_waves):
                    parent_id = f"sched-{mode}-wave-{w}"
                    gang_id = f"bench-gang-{mode}-{w}"
                    global_dag.start(parent_id, type="fanout")
                    # The straggler shape (ISSUE 12: "a task's slowest
                    # branch stops straggling behind unrelated
                    # traffic"): siblings are emitted ~0.3 s apart, the
                    # way a real decomposition streams its subtasks
                    # out, and an unrelated LOW-priority BURST lands
                    # between the second-to-last and LAST sibling.
                    # Under FIFO exactly that one branch queues behind
                    # the whole burst while its siblings already ran —
                    # slowest − median spikes by the burst's drain
                    # time. (Uniform background can't show this: FIFO
                    # fairness delays every branch EQUALLY, and
                    # straggler_s measures imbalance, not delay.) With
                    # the scheduler on, the late sibling's HIGH
                    # priority + the gang sort it ahead of the burst.
                    def _bg(i):
                        return asyncio.create_task(serve.execute_task(
                            Task(
                                description=(
                                    f"background {w}-{i}: tally ledger "
                                    f"{w * 10 + i}"
                                ),
                                priority="low",
                            )
                        ))

                    def _sib(i):
                        return asyncio.create_task(serve.execute_task(
                            Task(
                                description=(
                                    f"branch {w}-{i}: check inventory "
                                    f"{w * 10 + i}"
                                ),
                                priority="high",
                                parent_task_id=parent_id,
                                metadata={
                                    "gang_id": gang_id,
                                    "gang_size": gang,
                                },
                            )
                        ))

                    background = [_bg(0)]
                    await asyncio.sleep(0.2)
                    tw = time.perf_counter()
                    sib_handles = []
                    for i in range(gang - 1):
                        sib_handles.append(_sib(i))
                        await asyncio.sleep(0.3)
                    background += [_bg(i) for i in range(1, n_bg)]
                    await asyncio.sleep(0.3)
                    sib_handles.append(_sib(gang - 1))  # the straggler
                    sibs = await asyncio.gather(*sib_handles)
                    wave_walls.append(time.perf_counter() - tw)
                    summary = global_dag.finish(parent_id, "ok")
                    parent_bd.append((summary or {}).get("breakdown") or {})
                    bg = await asyncio.gather(*background)
                    ok += sum(1 for r in list(sibs) + list(bg) if r.success)
                    total += gang + len(bg)
                wall = time.perf_counter() - t0
                llm_steps = _gm.get("engine.completed") - steps0
                hists = _gm.snapshot()["histograms"]

                def _total(name):
                    h = hists.get(name) or {}
                    return (h.get("count") or 0) * (h.get("mean") or 0.0)

                e2e_total = _total("task.e2e_s")
                parent_cp = sum(
                    float(bd.get("critical_path_s") or 0.0)
                    for bd in parent_bd
                )
                delta = {
                    k.split(".", 1)[1]: int(_gm.get(k) - before[k])
                    for k in counters
                }
                out[mode] = {
                    "swarm_straggler_frac": (
                        round(_total("task.straggler_s") / e2e_total, 4)
                        if e2e_total else None
                    ),
                    "swarm_critical_path_frac": (
                        round(parent_cp / e2e_total, 4) if e2e_total else None
                    ),
                    "wave_p50_ms": round(
                        statistics.median(wave_walls) * 1000.0, 1
                    ),
                    "steps_per_sec": round(llm_steps / wall, 2),
                    "success": f"{ok}/{total}",
                    **delta,
                }
            finally:
                await serve.stop()
                await llm.stop()
            gc.collect()
    finally:
        # The process default: policy on (engine_sched_policy defaults
        # to "dag" too) — later sections must not inherit "off".
        global_scheduler.configure(policy="dag")
    on, off = out.get("on") or {}, out.get("off") or {}

    def _lower(key):
        a, b = on.get(key), off.get(key)
        return bool(a is not None and b is not None and a < b)

    out["straggler_frac_improved"] = _lower("swarm_straggler_frac")
    out["critical_path_frac_improved"] = _lower("swarm_critical_path_frac")
    return out


async def bench_multichip(
    model_name: str,
    provider: str,
    mesh_shape,
    concurrency: int = 8,
    steps: int = 24,
    epochs: int = 2,
):
    """MULTICHIP section (ISSUE 13): a REAL tensor-parallel serving soak
    — not the 32-token dryrun MULTICHIP_r01–r05 recorded. The engine
    boots on ``mesh_shape`` with the paged KV pool sharded over the
    ``model`` axis and admission replicated over ``data``, runs the same
    closed-loop agent-step workload as the single-chip sections, and
    reports per-chip steps/s, MFU, and the per-axis collective-time
    split (``engine.collective_frac.model`` / ``.data``,
    parallel/collectives.py) next to a single-device run of the SAME
    config for parallel efficiency. Runnable on CPU via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=8`` (the
    subprocess path ``python bench.py --multichip`` sets that up
    itself); greedy output parity sharded-vs-single is pinned by
    tests/test_multichip.py, so this section only measures."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.models.registry import get_model_config
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
    from pilottai_tpu.parallel.sharding import validate_serving_mesh
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    mesh_cfg = MeshConfig.from_dict(mesh_shape)
    n_chips = mesh_cfg.n_devices
    on_accel = provider != "cpu"
    model_cfg = get_model_config(model_name)
    report = validate_serving_mesh(
        create_mesh(mesh_cfg), model_cfg, concurrency
    )

    def _cfg(mesh):
        return LLMConfig(
            model_name=model_name,
            provider=provider,
            mesh_shape=mesh,
            engine_slots=concurrency,
            engine_admit_batch=concurrency,
            engine_chunk=8,
            engine_speculate=4,
            # The flagship sharded combo: paged pool + int8 KV — the
            # shapes ISSUE 13's byte-identity matrix pins.
            engine_paged_kv=True,
            engine_page_size=32,
            engine_kv_quantize="int8",
            engine_max_seq=512,
            dtype="bfloat16" if on_accel else "float32",
            quantize="int8" if on_accel else None,
            timeout=600.0,
        )

    from pilottai_tpu.obs.attribution import PHASES

    def _attr():
        out = {
            phase: _gm.get(f"engine.attributed_{phase}_s")
            for phase in PHASES
        }
        for axis in ("model", "data"):
            out[f"collective.{axis}"] = _gm.get(
                f"engine.attributed_collective_s.{axis}"
            )
        return out

    attr0 = _attr()
    sec = await bench_model(
        _cfg(dict(mesh_shape)), concurrency, steps, epochs, n_chips=n_chips
    )
    attr1 = _attr()
    d_attr = {k: attr1[k] - attr0[k] for k in attr1}
    # Section-exact fractions from the cumulative counters (the rolling
    # gauges sample a 60 s window; deltas cover exactly this soak).
    attributed = sum(d_attr[p] for p in PHASES)
    coll_frac = d_attr["collective"] / attributed if attributed > 0 else 0.0
    coll_model = (
        d_attr["collective.model"] / attributed if attributed > 0 else 0.0
    )
    coll_data = (
        d_attr["collective.data"] / attributed if attributed > 0 else 0.0
    )
    n_steps = max(sec.get("steps") or steps, 1)

    # Single-device reference: the SAME engine config on one chip — the
    # denominator for parallel efficiency (and the parity partner the
    # test matrix pins byte-identical).
    single = await bench_model(
        _cfg({"data": 1}), concurrency, max(steps // 2, 8), 1, n_chips=1
    )

    sharded_rate = sec["steps_per_sec_per_chip"] * n_chips
    single_rate = max(single["steps_per_sec_per_chip"], 1e-9)
    out = {
        "mesh": {k: int(v) for k, v in mesh_shape.items()},
        "n_chips": n_chips,
        "model": model_name,
        "kv_heads_sharded": bool(report["kv_heads_sharded"]),
        "data_groups": int(report["data_groups"]),
        "steps_per_sec_per_chip": sec["steps_per_sec_per_chip"],
        "p50_step_ms": sec["p50_step_ms"],
        "decode_tokens_per_sec_per_chip": sec[
            "decode_tokens_per_sec_per_chip"
        ],
        "mfu": sec["mfu"],
        "paged": True,
        "kv_quantize": "int8",
        "speculate": 4,
        "steps": sec["steps"],
        # Collective attribution (parallel/collectives.py estimates
        # carved out of measured dispatch walls — see PERF_NOTES round
        # 10 for the methodology and its error bars).
        "collective_frac": round(coll_frac, 4),
        "collective_frac_model": round(coll_model, 4),
        "collective_frac_data": round(coll_data, 4),
        "collective_ms_per_step": round(
            d_attr["collective"] * 1000.0 / n_steps, 3
        ),
        "single_chip": {
            "steps_per_sec_per_chip": single["steps_per_sec_per_chip"],
            "p50_step_ms": single["p50_step_ms"],
            "mfu": single["mfu"],
        },
        # Sharded per-chip rate over the single-device rate: 1.0 = ideal
        # scaling. On the virtual CPU mesh the 8 "devices" share the
        # same cores, so this reads as partitioning overhead only;
        # accelerator rounds give the real number.
        "per_chip_efficiency": round(
            sec["steps_per_sec_per_chip"] / single_rate, 4
        ),
        "total_speedup": round(sharded_rate / single_rate, 4),
    }
    return out


def _multichip_subprocess(timeout_s: float = 2400.0):
    """Run the MULTICHIP section in a child process with a forced
    8-device host platform. The parent bench process initialized jax
    long ago (1 CPU device); device topology is fixed at first import,
    so the virtual mesh must be a fresh process — exactly how the CI
    multichip lane and tests/conftest.py get theirs."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--multichip"],
        capture_output=True, text=True, timeout=timeout_s, env=env,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"multichip subprocess rc={proc.returncode}: "
            f"{(proc.stderr or '')[-400:]}"
        )
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError("multichip subprocess produced no JSON")


def _chaos_subprocess(timeout_s: float = 900.0, seed: int = 16):
    """Run the CHAOS section (scripts/chaos_soak.py) in a child process
    with a forced 8-device host platform — the soak's survivor-ladder
    meshes need a virtual multichip topology, which is fixed at jax's
    first import (same constraint as _multichip_subprocess). A
    violation exit still yields the summary: the section records the
    red soak instead of erasing it."""
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    script = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "scripts",
        "chaos_soak.py",
    )
    proc = subprocess.run(
        [sys.executable, script, "--seed", str(seed),
         "--budget-s", str(timeout_s * 0.8)],
        capture_output=True, text=True, timeout=timeout_s, env=env,
    )
    for line in reversed((proc.stdout or "").strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"chaos subprocess rc={proc.returncode} produced no JSON: "
        f"{(proc.stderr or '')[-400:]}"
    )


async def run_multichip_cli():
    """``python bench.py --multichip``: the MULTICHIP section alone,
    one JSON line on stdout (the parent bench embeds it; the committed
    MULTICHIP_r*.json artifact wraps it)."""
    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    n = len(jax.devices())
    if n < 8:
        print(json.dumps({
            "skipped": True,
            "reason": f"{n} device(s); the multichip soak needs 8 "
                      f"(set XLA_FLAGS=--xla_force_host_platform_"
                      f"device_count=8 on CPU)",
        }))
        return
    sec = await bench_multichip(
        model_name="llama3-8b-byte" if on_accel else "protocol-s",
        provider="tpu" if on_accel else "cpu",
        mesh_shape={"model": 4, "data": 2},
        concurrency=8,
        steps=24 if on_accel else 16,
        epochs=2,
    )
    _note("multichip", sec)
    print(json.dumps(sec))


async def bench_quant(on_accel, n_chips=1):
    """QUANT section (ISSUE 14 / ROADMAP item 3): the same serving shape
    per weight-quantization mode, so the decode-roofline claim is a
    measured series — bytes/token read (the ``engine.weight_bytes*``
    gauges set at engine boot), steps/s and MFU per mode, and the
    int4-vs-int8 bytes ratio as the headline cost axis.

    On an accelerator the modes are int8/int4 on the 8B north-star
    model (the dense bf16 tree does not fit a 16 GB chip — that is the
    point of the series). On CPU the section is plumbing proof on the
    protocol-s shape: none/int8/int4, honest tiny-model caveat — its
    tied fp32 embed is a far larger share of bytes/token than at 8B,
    so the CPU ratio understates the 8B win (the layer-stream ratio is
    pinned ≤ 0.55 by tests/test_quant_parity.py either way)."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    if on_accel:
        model, modes = "llama3-8b-byte", ("int8", "int4")
        shape = dict(
            engine_slots=8, engine_chunk=16, engine_speculate=6,
        )
        load = dict(concurrency=8, steps=24, epochs=2)
    else:
        model, modes = "protocol-s", ("none", "int8", "int4")
        shape = dict(engine_slots=4, engine_chunk=8, engine_speculate=0)
        load = dict(concurrency=4, steps=12, epochs=1)
    group = 128
    out = {"model": model, "quant_group": group, "modes": {}}
    for mode in modes:
        cfg = LLMConfig(
            model_name=model,
            provider="tpu" if on_accel else "cpu",
            engine_max_seq=512,
            dtype="bfloat16" if on_accel else "float32",
            engine_quant=mode,
            engine_quant_group=group,
            timeout=600.0,
            **shape,
        )
        sec = await bench_model(cfg, n_chips=n_chips, **load)
        out["modes"][mode] = {
            "steps_per_sec_per_chip": sec["steps_per_sec_per_chip"],
            "p50_step_ms": sec["p50_step_ms"],
            "decode_tokens_per_sec_per_chip": sec[
                "decode_tokens_per_sec_per_chip"
            ],
            "mfu": sec["mfu"],
            # Gauges set by THIS engine's boot (sections run serially,
            # last writer is this mode's batcher).
            "weight_bytes": int(_gm.get("engine.weight_bytes")),
            "weight_bytes_per_token": int(
                _gm.get("engine.weight_bytes_per_token")
            ),
            **(
                {"device_ms_per_step": sec.get("device_ms_per_step"),
                 "device_busy_frac": sec.get("device_busy_frac")}
                if sec.get("device_ms_per_step") is not None else {}
            ),
        }
        _note(f"quant[{mode}]", out["modes"][mode])
    if "int8" in out["modes"] and "int4" in out["modes"]:
        out["bytes_per_token_int4_vs_int8"] = round(
            out["modes"]["int4"]["weight_bytes_per_token"]
            / max(out["modes"]["int8"]["weight_bytes_per_token"], 1),
            4,
        )
    return out


def _note(tag, payload):
    """Section progress to stderr — a crash in a later section must not
    lose the numbers already measured."""
    print(f"[bench] {tag}: {json.dumps(payload)}", file=sys.stderr, flush=True)


def _reset_task_attribution():
    """Section-pure task-DAG attribution: drop the previous section's
    ``task.*`` histograms and the occupancy windows so this section's
    overhead/critical-path fractions and busy_frac describe ONLY its own
    tasks (same discipline as the ``request.`` resets above)."""
    from pilottai_tpu.obs import global_occupancy
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    _gm.reset_histograms("task.")
    global_occupancy.reset()


def _task_attribution(prefix):
    """Orchestrator-cost fields for a Serve-driven section (obs/dag.py):
    orchestration overhead and critical-path time as fractions of
    summed task e2e, plus per-agent-role busy fractions. Histogram
    count×mean = sum because the section reset the ``task.`` histograms
    at its start."""
    from pilottai_tpu.obs import global_occupancy
    from pilottai_tpu.utils.metrics import global_metrics as _gm

    hists = _gm.snapshot()["histograms"]

    def total(name):
        h = hists.get(name) or {}
        return (h.get("count") or 0) * (h.get("mean") or 0.0)

    e2e = total("task.e2e_s")
    fracs = global_occupancy.refresh()
    out = {
        f"{prefix}_orchestration_overhead_frac": (
            round(total("task.orchestrator_overhead_s") / e2e, 4)
            if e2e else None
        ),
        f"{prefix}_critical_path_frac": (
            round(total("task.critical_path_s") / e2e, 4) if e2e else None
        ),
        f"{prefix}_straggler_frac": (
            round(total("task.straggler_s") / e2e, 4) if e2e else None
        ),
        f"{prefix}_agent_busy_frac_mean": (
            round(statistics.mean(fracs.values()), 4) if fracs else None
        ),
        f"{prefix}_agent_busy_frac_max": (
            round(max(fracs.values()), 4) if fracs else None
        ),
    }
    # Full per-role map only when small (pipeline's 4 specialists, not
    # the swarm's 32 workers — the driver tail-captures the JSON).
    if fracs and len(fracs) <= 8:
        out[f"{prefix}_agent_busy_frac"] = {
            role: round(frac, 4) for role, frac in sorted(fracs.items())
        }
    return out


async def run_bench():
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.obs import phase_summary

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    n_chips = max(len(jax.devices()), 1) if on_accel else 1

    common = dict(
        provider="tpu" if on_accel else "cpu",
        engine_max_seq=512,
        dtype="bfloat16" if on_accel else "float32",
        quantize="int8" if on_accel else None,
        # First-wave compiles through the tunnel can exceed the default
        # 120 s; a timeout there cancels and RE-SUBMITS the whole wave
        # (measured as minutes of cascading retries in the 4K section).
        timeout=600.0,
    )

    async def _section(tag, coro):
        try:
            sec = await coro
            _note(tag, sec)
            return sec
        except Exception as exc:  # noqa: BLE001 — keep earlier sections
            _note(f"{tag} FAILED", {"error": str(exc)})
            return None

    # Section 1: 1B throughput model (byte vocab: runs without a
    # checkpoint download in the zero-egress environment).
    sec_1b = await bench_model(
        LLMConfig(
            model_name="llama3-1b-byte" if on_accel else "llama-tiny",
            engine_slots=32,
            # One fused admission per 32-slot wave; early-exit chunks
            # make a generous width free (decode stops at all-done).
            engine_admit_batch=32,
            # Early-exit makes a generous chunk free: 24 blocks covers
            # the slowest slot's 48 tokens in one dispatch even at the
            # straggler's acceptance (round-4 A/B: beat chunk 12/16 at
            # both D=4 and D=6).
            engine_chunk=24,
            engine_speculate=4,
            **common,
        ),
        concurrency=32, steps=96, epochs=3, n_chips=n_chips,
    )
    _note("1b", sec_1b)

    # Section 2: the north-star model over COLD prompts. D=6 verify
    # blocks won the round-4 sweep (D 4/6/8 x chunk): acceptance ~3.7
    # caps tokens/pass, early exit stops the chunk at all-done.
    sec_8b = None
    sec_8b_long = None
    sec_8b_8k = None
    if on_accel:
        sec_8b = await _section("8b", bench_model(
            LLMConfig(
                model_name="llama3-8b-byte", engine_slots=8,
                engine_chunk=16, engine_speculate=6,
                engine_draft_layers=2,
                **common,
            ),
            concurrency=8, steps=32, epochs=2, n_chips=n_chips,
        ))

        # Section 3: long-context serving — the paged pool with every
        # fast path composed (VERDICT r3 next-step 1 done-criterion:
        # p50 within ~1.3x of the dense section).
        sec_8b_long = await _section("8b-long", bench_model(
            LLMConfig(
                model_name="llama3-8b-byte", engine_slots=8,
                engine_chunk=16, engine_speculate=6,
                **{**common, "engine_max_seq": 4096},
                # Page 64: the block-prefix tail a cold prompt must
                # prefill is uniform(0, P) — page 128 measured ~80 ms
                # slower p50 at 4K than 64 (round-4 A/B).
                engine_paged_kv=True, engine_page_size=64,
                engine_kv_quantize="int8",
            ),
            # 3 epochs: the tunnel's stall windows hit short epochs
            # hardest and this section's pass/fail bar is a RATIO to the
            # dense section — best-of-3 keeps one bad window from
            # deciding it.
            concurrency=8, steps=24, epochs=3, n_chips=n_chips,
            pad_to=1200,  # ~1.2K-char shared preamble + unique tails
        ))
        if sec_8b_long is not None:
            sec_8b_long["model"] = "llama3-8b-byte@4k-paged"

        # Section 3b: 8K context — the capacity the paged pool was built
        # for. The ~7K shared preamble admits once via chunked prefill
        # (segments interleave with live decode, engine/batcher.py
        # _advance_segment) and is then block-shared; each request
        # prefills only its unique tail. Pool sized for 8 full-8K
        # residents (1024 usable pages ≈ 4.3 GB int8 next to 8.5 GB of
        # weights).
        sec_8b_8k = await _section("8b-8k", bench_model(
            LLMConfig(
                model_name="llama3-8b-byte", engine_slots=8,
                engine_chunk=16, engine_speculate=6,
                **{**common, "engine_max_seq": 8192},
                # Page 128 at 8K (round-5 A/B, device-only ms/step:
                # 64→268, 128→243, 256→309): decode here is the paged
                # kernel's per-grid-cell latency, so fewer/bigger pages
                # win until tail-prefill cost overtakes at 256.
                engine_paged_kv=True, engine_page_size=128,
                engine_kv_pages=513,
                engine_kv_quantize="int8",
            ),
            concurrency=8, steps=16, epochs=2, n_chips=n_chips,
            pad_to=7000,
        ))
        if sec_8b_8k is not None:
            sec_8b_8k["model"] = "llama3-8b-byte@8k-paged"

    # Sections 4-5: orchestrator-level numbers (VERDICT r3 next-step 6).
    provider = "tpu" if on_accel else "mock"
    try:
        sec_pipeline = await bench_pipeline(provider=provider)
        _note("pipeline", sec_pipeline)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("pipeline FAILED", {"error": str(exc)})
        sec_pipeline = {"pipeline_p50_ms": None, "pipeline_error": str(exc)}
    sec_swarm = None
    if on_accel:
        try:
            sec_swarm = await bench_swarm("protocol-s", "tpu")
            _note("swarm", sec_swarm)
        except Exception as exc:  # noqa: BLE001 — keep earlier sections
            _note("swarm FAILED", {"error": str(exc)})
            sec_swarm = {"swarm_steps_per_sec": None,
                         "swarm_error": str(exc)}

    # Section 6: open-loop SLO harness (ROADMAP item 5) — Poisson + 2x
    # burst arrivals over the multi-tenant mix at ~70% of the 1B
    # section's measured capacity, per-class attainment as the headline.
    sec_slo = None
    try:
        from pilottai_tpu.core.config import ReliabilityConfig

        slo_rate = max(
            1.0, min(0.7 * sec_1b["steps_per_sec_per_chip"] * n_chips, 64.0)
        )
        sec_slo = await bench_slo(
            LLMConfig(
                model_name="llama3-1b-byte" if on_accel else "llama-tiny",
                engine_slots=32, engine_admit_batch=8, engine_chunk=24,
                engine_speculate=4,
                # Shed (429) instead of unbounded queue growth when the
                # burst outruns capacity — sheds land in the SLO ledger
                # as budget burn, which is the point.
                reliability=ReliabilityConfig(max_queue_depth=256),
                **common,
            ),
            rate_rps=round(slo_rate, 1),
            duration_s=30.0 if on_accel else 12.0,
            n_chips=n_chips,
            # Clamp the offered rate to the mix's own measured capacity
            # (ISSUE 19 satellite): the r07 headline printed attainment
            # 0.0 purely from CPU saturation, which the CELL section
            # then contradicted at 0.958.
            derate=True,
        )
        _note("slo", sec_slo)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("slo FAILED", {"error": str(exc)})
        sec_slo = {"slo_error": str(exc)}

    # Section 7: scripted single-fault recovery soak (ISSUE 9) — one
    # injected mid-decode device failure against a concurrent greedy
    # wave; the engine's in-flight recovery must complete every request
    # byte-identically (recovered_frac == 1.0 is the acceptance bar).
    sec_recovery = None
    try:
        sec_recovery = await bench_recovery(
            LLMConfig(
                model_name="llama3-1b-byte" if on_accel else "llama-tiny",
                engine_slots=8, engine_chunk=16,
                **common,
            ),
            n_requests=6 if on_accel else 4,
            max_new_tokens=48,
        )
        _note("recovery", sec_recovery)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("recovery FAILED", {"error": str(exc)})
        sec_recovery = {"recovery_error": str(exc)}

    # Section 8: global KV cache tier (ISSUE 10) — multi-turn sessions
    # against a deliberately tiny device-resident store, so session
    # resumes exercise the spill→restore path: hit-rate > 0 with
    # restores > 0 means the cold tier served KV that eviction would
    # previously have thrown away.
    sec_kvcache = None
    try:
        sec_kvcache = await bench_kvcache(
            LLMConfig(
                model_name="llama3-1b-byte" if on_accel else "llama-tiny",
                engine_slots=4, engine_chunk=8,
                # Two hot entries vs six sessions: every resume lands
                # after its entry was evicted (and spilled).
                engine_prefix_cache=2,
                engine_kvcache_host_mb=256,
                **common,
            ),
            n_sessions=6 if on_accel else 4,
            turns=3 if on_accel else 2,
        )
        _note("kvcache", sec_kvcache)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("kvcache FAILED", {"error": str(exc)})
        sec_kvcache = {"kvcache_error": str(exc)}

    # Section 9: serving cell (ISSUE 11) — 3 in-process replicas behind
    # the KV-affinity router, driven open-loop at ≥10× the single-engine
    # rate measured in section 1. The point is cell behavior under
    # overload: per-class boundary shedding, session affinity, scripted
    # migration + drain.
    sec_cell = None
    try:
        from pilottai_tpu.core.config import ReliabilityConfig

        single_rps = sec_1b["steps_per_sec_per_chip"] * n_chips
        # ≥10× the single-engine rate is the acceptance bar; the cap is
        # only a task-count sanity bound for very fast engines.
        cell_rate = min(10.0 * max(single_rps, 1.0), 1500.0)
        sec_cell = await bench_cell(
            LLMConfig(
                model_name="llama3-1b-byte" if on_accel else "llama-tiny",
                engine_slots=4, engine_chunk=8,
                engine_prefix_cache=2,
                engine_kvcache_host_mb=64,
                reliability=ReliabilityConfig(max_queue_depth=32),
                **common,
            ),
            n_replicas=3,
            rate_rps=round(cell_rate, 1),
            duration_s=20.0 if on_accel else 12.0,
            single_rps=round(single_rps, 2),
            n_chips=n_chips,
        )
        _note("cell", sec_cell)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("cell FAILED", {"error": str(exc)})
        sec_cell = {"cell_error": str(exc)}

    # Section 10: DAG-aware scheduler (ISSUE 12 / ROADMAP item 4) — the
    # same fan-out-plus-background workload with the scheduler off then
    # on; straggler_frac and (parent) critical_path_frac must come DOWN
    # with it on. Runs the protocol checkpoint so agents actually
    # complete tasks; greedy on/off parity is pinned by
    # tests/test_sched.py rather than re-measured here.
    sec_sched = None
    try:
        sec_sched = await bench_sched(
            "protocol-s", "tpu" if on_accel else "cpu",
            n_waves=4 if on_accel else 3,
            gang=4 if on_accel else 3,
            n_bg=6 if on_accel else 4,
        )
        _note("sched", sec_sched)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("sched FAILED", {"error": str(exc)})
        sec_sched = {"sched_error": str(exc)}

    # Section 11: MULTICHIP (ISSUE 13 / ROADMAP item 1) — the
    # tensor-parallel serving soak on mesh={'model':4,'data':2}: paged
    # KV pool sharded over the model axis, admission replicated over
    # data, per-chip steps/s + per-axis collective attribution + MFU as
    # the FIRST multichip headline since the r01–r05 dryruns. On an
    # accelerator host with ≥8 chips it runs in-process on the real
    # mesh; on CPU it re-execs itself with a forced 8-device host
    # platform (device topology is fixed at jax's first import).
    sec_multichip = None
    try:
        if on_accel and n_chips >= 8:
            sec_multichip = await bench_multichip(
                model_name="llama3-8b-byte",
                provider="tpu",
                mesh_shape={"model": 4, "data": 2},
                concurrency=8, steps=24, epochs=2,
            )
        else:
            loop = asyncio.get_running_loop()
            sec_multichip = await loop.run_in_executor(
                None, _multichip_subprocess
            )
        _note("multichip", sec_multichip)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("multichip FAILED", {"error": str(exc)})
        sec_multichip = {"multichip_error": str(exc)}

    # Section 12: QUANT (ISSUE 14 / ROADMAP item 3) — the decode weight
    # stream per quantization mode: bytes/token (measured gauges),
    # steps/s and MFU for int8 vs int4 (plus dense on CPU), with the
    # int4/int8 bytes ratio as the cost headline. The fused greedy
    # epilogue is on per the LLMConfig default, so these numbers are
    # the composed fast path.
    sec_quant = None
    try:
        sec_quant = await bench_quant(on_accel, n_chips=n_chips)
        _note("quant", sec_quant)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("quant FAILED", {"error": str(exc)})
        sec_quant = {"quant_error": str(exc)}

    # Section 13: CHAOS (ISSUE 16) — the cross-subsystem chaos soak
    # (scripts/chaos_soak.py): a seeded randomized fault schedule
    # (shard loss + KV corruption + step/prefill faults + latency
    # blips) against a 2-replica serving cell on survivor-ladder
    # meshes. Like MULTICHIP on CPU it needs 8 virtual devices, so it
    # always runs as a fresh subprocess. Invariant headlines:
    # recovered_frac, byte_identity_ok, corruptions detected vs
    # injected, stuck_flights.
    sec_chaos = None
    try:
        loop = asyncio.get_running_loop()
        sec_chaos = await loop.run_in_executor(None, _chaos_subprocess)
        _note("chaos", sec_chaos)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("chaos FAILED", {"error": str(exc)})
        sec_chaos = {"chaos_error": str(exc)}

    # Section 14: AUTOCONF (ISSUE 18) — measurement→configuration loop.
    # Knob-candidate sweep over the widened SLO workload (same seed =
    # same recorded arrival trace) feeds the cost model, the profiler's
    # fingerprint weights the recommendation, and a scripted recurring
    # burst drives DynamicScaling forecast-on vs forecast-off. The
    # recommendation + fingerprint also land in the profile store, where
    # the engine's boot divergence check and scripts/recommend.py read
    # them.
    sec_autoconf = None
    try:
        auto_rate = max(
            1.0, min(0.7 * sec_1b["steps_per_sec_per_chip"] * n_chips, 64.0)
        )
        sec_autoconf = await bench_autoconf(
            "llama3-1b-byte" if on_accel else "llama-tiny",
            common,
            rate_rps=round(auto_rate, 1),
            duration_s=12.0 if on_accel else 8.0,
            n_chips=n_chips,
        )
        _note("autoconf", sec_autoconf)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("autoconf FAILED", {"error": str(exc)})
        sec_autoconf = {"autoconf_error": str(exc)}

    # Section 15: DISAGG (ISSUE 19) — disaggregated prefill/decode
    # serving: the same sessions+RAG mix against a colocated then a
    # 1p1d 2-replica cell; interference ratio (mixed-phase interactive
    # TPOT p99 / decode-only baseline) per topology, plus handoff
    # success rate and handoff_ms percentiles.
    sec_disagg = None
    try:
        from pilottai_tpu.core.config import ReliabilityConfig

        single_rps = sec_1b["steps_per_sec_per_chip"] * n_chips
        # Below the knee on purpose: at ~0.5x single-engine rate the
        # decode stream alone saturates a 2x2-slot cell, the prefill
        # tier's queue backs up, handoff legs get shed mid-flight and
        # handoff_ms degenerates into queue wait — measuring overload,
        # not the handoff. (The SLO/CELL sections own the saturation
        # story; this one isolates the handoff + interference axes.)
        disagg_rate = max(1.0, min(0.25 * single_rps, 12.0))
        sec_disagg = await bench_disagg(
            LLMConfig(
                model_name="llama3-1b-byte" if on_accel else "llama-tiny",
                # Scarce slots: slot occupancy is the interference axis
                # an in-process cell can demonstrate even where compute
                # isolation can't be (see bench_disagg's caveat).
                engine_slots=2, engine_chunk=8,
                engine_prefix_cache=2,
                engine_kvcache_host_mb=64,
                reliability=ReliabilityConfig(max_queue_depth=32),
                **common,
            ),
            rate_rps=round(disagg_rate, 1),
            prefill_rps=round(max(disagg_rate / 4.0, 0.5), 1),
            duration_s=10.0 if on_accel else 6.0,
            n_chips=n_chips,
        )
        _note("disagg", sec_disagg)
    except Exception as exc:  # noqa: BLE001 — keep earlier sections
        _note("disagg FAILED", {"error": str(exc)})
        sec_disagg = {"disagg_error": str(exc)}

    headline = sec_8b or sec_1b
    out = {
        "metric": "agent_steps_per_sec_per_chip",
        "value": sec_1b["steps_per_sec_per_chip"],
        "unit": "steps/s/chip",
        # ≥ 1.0 ⇔ the north-star model meets the ≤500 ms p50 target.
        "vs_baseline": round(TARGET_P50_MS / headline["p50_step_ms"], 3),
        "p50_step_ms": sec_1b["p50_step_ms"],
        "p50_step_ms_8b": sec_8b["p50_step_ms"] if sec_8b else None,
        "p50_step_ms_8b_long": (
            sec_8b_long["p50_step_ms"] if sec_8b_long else None
        ),
        "p50_step_ms_8b_8k": (
            sec_8b_8k["p50_step_ms"] if sec_8b_8k else None
        ),
        # Tunnel-independent: the device's own sustainable rate and how
        # much of the benchmark wall the device was actually busy
        # (utils/device_profile.py; per-section values under models.*).
        "steps_per_sec_device_only_1b": sec_1b.get(
            "steps_per_sec_device_only"
        ),
        "device_ms_per_step_8b": (
            (sec_8b or {}).get("device_ms_per_step")
        ),
        # Device-feed headline (BENCH_r05: 8b busy_frac 0.65 — ~30% of
        # wall the device waited on the host; r6 target ≥ 0.80):
        "device_busy_frac_8b": (sec_8b or {}).get("device_busy_frac"),
        "device_busy_frac_1b": sec_1b.get("device_busy_frac"),
        "host_gap_p50_ms_8b": (sec_8b or {}).get("host_gap_p50_ms"),
        # Live MFU headlines (ROADMAP item 3 tracks ≥ 0.15 on 8B dense;
        # per-section values + profiler reconciliation under models.*).
        "mfu_1b": sec_1b.get("mfu"),
        "mfu_8b": (sec_8b or {}).get("mfu"),
        # SLO attainment headline (ROADMAP item 5): interactive-class
        # attainment under open-loop Poisson+burst load; full per-class
        # breakdown under SLO.classes.
        "slo_attainment_interactive": (
            (sec_slo.get("classes") or {}).get("interactive", {})
            .get("attainment") if sec_slo else None
        ),
        # Honesty caveat (ISSUE 19 satellite): when the SLO section
        # saturated anyway, the attainment headline above describes
        # queueing collapse, not serving quality.
        "slo_saturated": (
            sec_slo.get("saturated") if sec_slo else None
        ),
        "SLO": sec_slo,
        # Fault-domain headline (ISSUE 9): fraction of fault-interrupted
        # requests that completed anyway (full breakdown under RECOVERY).
        "recovered_frac": (
            sec_recovery.get("recovered_frac") if sec_recovery else None
        ),
        "RECOVERY": sec_recovery,
        # KV cache tier headline (ISSUE 10): session-resume hit rate on
        # the multi-turn workload (full breakdown under KVCACHE).
        "kvcache_prefix_hit_rate": (
            sec_kvcache.get("prefix_hit_rate") if sec_kvcache else None
        ),
        "KVCACHE": sec_kvcache,
        # Serving-cell headlines (ISSUE 11): interactive attainment at
        # ≥10× single-engine offered load, and the affinity hit rate
        # (full breakdown incl. per-class shed + migration/drain under
        # CELL).
        "cell_attainment_interactive": (
            (sec_cell.get("classes") or {}).get("interactive", {})
            .get("attainment") if sec_cell else None
        ),
        "cell_affinity_hit_rate": (
            sec_cell.get("affinity_hit_rate") if sec_cell else None
        ),
        "CELL": sec_cell,
        # DAG-aware scheduler headlines (ISSUE 12): straggler fraction
        # with the scheduler on vs off on the same workload (full
        # on/off blocks under SCHED).
        "sched_straggler_frac_on": (
            (sec_sched.get("on") or {}).get("swarm_straggler_frac")
            if sec_sched else None
        ),
        "sched_straggler_frac_off": (
            (sec_sched.get("off") or {}).get("swarm_straggler_frac")
            if sec_sched else None
        ),
        "SCHED": sec_sched,
        # Multichip serving headlines (ISSUE 13): the first bench round
        # since r05 whose headline is not a single-chip number — per-chip
        # steps/s on mesh={'model':4,'data':2} with the per-axis
        # collective split (full breakdown incl. the single-device
        # reference under MULTICHIP, reordered to the tail below so the
        # driver capture keeps it).
        "multichip_steps_per_sec_per_chip": (
            sec_multichip.get("steps_per_sec_per_chip")
            if sec_multichip else None
        ),
        "multichip_mfu": (
            sec_multichip.get("mfu") if sec_multichip else None
        ),
        "multichip_collective_frac_model": (
            sec_multichip.get("collective_frac_model")
            if sec_multichip else None
        ),
        "multichip_collective_frac_data": (
            sec_multichip.get("collective_frac_data")
            if sec_multichip else None
        ),
        "MULTICHIP": sec_multichip,
        # Weight-quantization headlines (ISSUE 14): 8B int4 MFU on the
        # accel path (None on CPU runs — the CPU QUANT section is
        # plumbing proof on the protocol-s shape) and the measured
        # bytes/token ratio int4 vs int8 (the ≤ 0.55 acceptance axis at
        # 8B; CPU understates it — tiny tied embed, see bench_quant).
        "mfu_8b_quant": (
            ((sec_quant.get("modes") or {}).get("int4") or {}).get("mfu")
            if sec_quant and on_accel else None
        ),
        "quant_bytes_per_token_ratio": (
            sec_quant.get("bytes_per_token_int4_vs_int8")
            if sec_quant else None
        ),
        "QUANT": sec_quant,
        # Chaos-soak headlines (ISSUE 16): every request survived the
        # fault schedule, every probe wave stayed byte-identical, and
        # every injected corruption was detected (full schedule +
        # invariant breakdown under CHAOS).
        "chaos_recovered_frac": (
            sec_chaos.get("recovered_frac") if sec_chaos else None
        ),
        "chaos_byte_identity_ok": (
            sec_chaos.get("byte_identity_ok") if sec_chaos else None
        ),
        "CHAOS": sec_chaos,
        # Auto-configuration headlines (ISSUE 18): cost-model-recommended
        # vs default knob vector on the SAME recorded workload (weighted
        # interactive+batch attainment — the recommendation's own score
        # axis), and the measured seconds of lead the arrival forecast
        # bought before the scripted burst (full sweep + forecast on/off
        # blocks under AUTOCONF).
        "autoconf_attainment_recommended": (
            ((sec_autoconf.get("recommendation") or {}).get("score") or {})
            .get("attainment") if sec_autoconf else None
        ),
        "autoconf_attainment_default": (
            ((sec_autoconf.get("recommendation") or {})
             .get("default_score") or {})
            .get("attainment") if sec_autoconf else None
        ),
        "autoconf_forecast_lead_s": (
            sec_autoconf.get("forecast_lead_s") if sec_autoconf else None
        ),
        "AUTOCONF": sec_autoconf,
        # Disaggregated-serving headlines (ISSUE 19): the decode-tier
        # interference ratio for each topology (disagg must hold closer
        # to 1.0), handoff success and the handoff wall (full per-phase
        # breakdown under DISAGG).
        "disagg_tpot_interference": (
            (sec_disagg.get("disagg") or {}).get("tpot_interference")
            if sec_disagg else None
        ),
        "colocated_tpot_interference": (
            (sec_disagg.get("colocated") or {}).get("tpot_interference")
            if sec_disagg else None
        ),
        "disagg_handoff_success": (
            (sec_disagg.get("disagg") or {}).get("handoff_success")
            if sec_disagg else None
        ),
        "disagg_handoff_ms_p99": (
            (sec_disagg.get("disagg") or {}).get("handoff_ms_p99")
            if sec_disagg else None
        ),
        "DISAGG": sec_disagg,
        **sec_pipeline,
        **(sec_swarm or {}),
        # Orchestrator-path phase percentiles: traffic since the last
        # engine section's reset — i.e. the pipeline + swarm sections
        # (per engine-section values live under models.*.phases).
        "phases": phase_summary(),
        "provider": "tpu" if on_accel else "cpu",
        "n_chips": n_chips,
        "models": {
            sec_1b["model"]: sec_1b,
            **({sec_8b["model"]: sec_8b} if sec_8b else {}),
            **({sec_8b_long["model"]: sec_8b_long} if sec_8b_long else {}),
            **({sec_8b_8k["model"]: sec_8b_8k} if sec_8b_8k else {}),
        },
    }
    # The driver captures the LAST 2,000 bytes of output: the
    # orchestrator headline (pipeline/swarm success — or the error that
    # replaced it when a section failed) must be the final keys or the
    # big `models` dict truncates it away — the round-5 12/12 and 96/96
    # claims were unverifiable from BENCH_r05.json for exactly this
    # reason (VERDICT r5 next-step 3a).
    for key in (
        # Multichip headlines ride the tail too (ISSUE 13): the MULTICHIP
        # block is small and the driver's 2,000-byte window must keep it
        # — the whole point of the round is a non-single-chip headline.
        "MULTICHIP",
        "multichip_steps_per_sec_per_chip", "multichip_mfu",
        "multichip_collective_frac_model", "multichip_collective_frac_data",
        # QUANT headlines (ISSUE 14): the round's point is the decode
        # roofline — the per-mode block and both scalar headlines must
        # survive the driver's 2,000-byte tail window.
        "QUANT", "mfu_8b_quant", "quant_bytes_per_token_ratio",
        # AUTOCONF headlines (ISSUE 18): recommended-vs-default and the
        # forecast lead are the round's point — keep them in the tail
        # window (the big AUTOCONF block itself stays mid-payload; the
        # scalars are what the driver must see).
        "autoconf_attainment_recommended", "autoconf_attainment_default",
        "autoconf_forecast_lead_s",
        # DISAGG headlines (ISSUE 19): the round's point is the
        # interference split — both topology ratios, the handoff health
        # scalars and the (small) DISAGG block ride the tail so the
        # driver's 2,000-byte window keeps them.
        "DISAGG",
        "disagg_tpot_interference", "colocated_tpot_interference",
        "disagg_handoff_success", "disagg_handoff_ms_p99",
        "slo_saturated",
        "pipeline_error", "swarm_error", "pipeline_success", "swarm_success",
    ):
        if key in out:
            out[key] = out.pop(key)
    print(json.dumps(out))


if __name__ == "__main__":
    if "--multichip" in sys.argv[1:]:
        asyncio.run(run_multichip_cli())
    else:
        asyncio.run(run_bench())
