"""Headline benchmark: agent-steps/sec/chip through the in-tree engine.

An "agent step" is one LLM call inside the agent's plan/act/evaluate loop
(SURVEY.md §3.4: a simple task is ≥4 such calls; the reference pays a
remote HTTPS round-trip per step, ``pilott/engine/llm.py:59``). Here the
same step runs on local devices through the continuous batcher.

Baseline: the reference publishes no numbers (SURVEY.md §6); BASELINE.json's
north star is ≤500 ms p50 per agent step → 2.0 steps/sec/chip. vs_baseline
is measured steps/sec/chip against that 2.0.

The TPU is reached through a shared tunnel whose latency oscillates
between ~100 ms and multi-second stalls (see .claude/skills/verify
gotchas); a single epoch can land in a bad window and misreport the
engine by 5x. The bench therefore runs EPOCHS epochs and reports the
best one — peak sustained throughput — with every epoch's steps/s in
``epoch_steps_per_sec`` for transparency.

Prints ONE JSON line.
"""

import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax


CONCURRENCY = 32       # concurrent agent steps in flight
STEPS = 96             # total timed steps per epoch
EPOCHS = 3             # measurement epochs; best one is reported
MAX_NEW_TOKENS = 48    # JSON-ish agent-step reply length
BASELINE_STEPS_PER_SEC = 2.0


def pick_config():
    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    from pilottai_tpu.core.config import LLMConfig

    return on_accel, LLMConfig(
        model_name="llama3-1b-byte" if on_accel else "llama-tiny",
        provider="tpu" if on_accel else "cpu",
        engine_slots=min(CONCURRENCY, 32),
        engine_max_seq=512,
        # Swept on v5e (chunk ∈ {8, 12, 16, 24} × {bf16, int8}): int8
        # weight-only + chunk 12 wins (p50 430 ms, 71 steps/s measured) —
        # int8 halves the decode weight stream (models/quant.py), and 12
        # balances chunk-boundary dead time against per-chunk overhead.
        engine_chunk=12,
        quantize="int8" if on_accel else None,
        dtype="bfloat16" if on_accel else "float32",
    )


PROMPT = (
    "Analyze the task and respond with JSON: "
    '{"requires_decomposition": false, "complexity": 3, '
    '"estimated_resources": {"agents": 1}}. Task: summarize the quarterly '
    "report into three bullet points for the executive team."
)


async def run_bench():
    on_accel, cfg = pick_config()
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    handler = LLMHandler(cfg)
    params = GenerationParams(max_new_tokens=MAX_NEW_TOKENS, temperature=0.0)

    async def one_step():
        resp = await handler.apredict(PROMPT, params=params)
        return resp

    # Warmup: compile prefill bucket + decode, fill the pipeline.
    await asyncio.gather(*[one_step() for _ in range(min(8, CONCURRENCY))])

    async def epoch():
        latencies = []
        done = 0
        t0 = time.perf_counter()

        async def worker():
            nonlocal done
            while done < STEPS:
                done += 1
                s = time.perf_counter()
                await one_step()
                latencies.append(time.perf_counter() - s)

        await asyncio.gather(*[worker() for _ in range(CONCURRENCY)])
        return latencies, time.perf_counter() - t0

    epochs = [await epoch() for _ in range(EPOCHS)]
    epoch_rates = [round(len(l) / w, 3) for l, w in epochs]
    latencies, wall = max(epochs, key=lambda e: len(e[0]) / e[1])
    await handler.stop()

    n_chips = max(len(jax.devices()), 1) if on_accel else 1
    steps_per_sec_chip = len(latencies) / wall / n_chips
    p50_ms = statistics.median(latencies) * 1000.0

    # Decode throughput + MFU so the distance to hardware roofline is
    # visible in the artifact (VERDICT r1 asked for both). Every step
    # generates MAX_NEW_TOKENS (random weights never emit EOS).
    from pilottai_tpu.models.registry import get_model_config

    n_params = get_model_config(cfg.model_name).param_count()
    decode_tok_s = len(latencies) * MAX_NEW_TOKENS / wall / n_chips
    peak_flops = 197e12 if on_accel else 1e12  # v5e bf16 peak per chip
    mfu = decode_tok_s * 2.0 * n_params / peak_flops

    print(
        json.dumps(
            {
                "metric": "agent_steps_per_sec_per_chip",
                "value": round(steps_per_sec_chip, 3),
                "unit": "steps/s/chip",
                "vs_baseline": round(steps_per_sec_chip / BASELINE_STEPS_PER_SEC, 3),
                "p50_step_ms": round(p50_ms, 1),
                "decode_tokens_per_sec_per_chip": round(decode_tok_s, 1),
                "mfu": round(mfu, 4),
                "model": cfg.model_name,
                "provider": cfg.provider,
                "n_chips": n_chips,
                "concurrency": CONCURRENCY,
                "steps": len(latencies),
                "epoch_steps_per_sec": epoch_rates,
            }
        )
    )


if __name__ == "__main__":
    asyncio.run(run_bench())
