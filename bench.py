"""Headline benchmark: agent-steps/sec/chip through the in-tree engine.

An "agent step" is one LLM call inside the agent's plan/act/evaluate loop
(SURVEY.md §3.4: a simple task is ≥4 such calls; the reference pays a
remote HTTPS round-trip per step, ``pilott/engine/llm.py:59``). Here the
same step runs on local devices through the continuous batcher.

Two sections on accelerator (VERDICT r2 next-step 3):

* ``llama3-1b-byte`` — 32-way concurrency throughput section;
* ``llama3-8b`` — the BASELINE.md north-star model, int8 weight-only +
  speculative decoding, 8-way; its p50 vs the ≤500 ms target is the
  headline (``vs_baseline`` = 500 / p50_8b — ≥1.0 means target met; the
  reference publishes no numbers of its own, SURVEY.md §6).

The TPU is reached through a shared tunnel whose latency oscillates
between ~100 ms and multi-second stalls (see .claude/skills/verify
gotchas); a single epoch can land in a bad window and misreport the
engine by 5x. Each section therefore runs several epochs and reports the
best one (peak sustained throughput) PLUS the median epoch and every
epoch's rate, so the flattering statistic never stands alone.

Prints ONE JSON line.
"""

import asyncio
import gc
import json
import os
import statistics
import sys
import time

import jax

MAX_NEW_TOKENS = 48    # JSON-ish agent-step reply length
TARGET_P50_MS = 500.0  # BASELINE.md north star for llama3-8b

PROMPT = (
    "Analyze the task and respond with JSON: "
    '{"requires_decomposition": false, "complexity": 3, '
    '"estimated_resources": {"agents": 1}}. Task: summarize the quarterly '
    "report into three bullet points for the executive team."
)


async def bench_model(cfg, concurrency, steps, epochs, n_chips=1):
    """Run one engine section; returns the result dict."""
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.models.registry import get_model_config

    handler = LLMHandler(cfg)
    params = GenerationParams(max_new_tokens=MAX_NEW_TOKENS, temperature=0.0)

    async def one_step():
        return await handler.apredict(PROMPT, params=params)

    # Warmup: two full waves — the first compiles prefill buckets +
    # decode, the second the PREFIX-HIT admission variants and settles
    # the speculative acceptance EMA (with only one wave those compiles
    # land inside timed epoch 1 and drag the reported median).
    for _ in range(2):
        await asyncio.gather(*[one_step() for _ in range(concurrency)])

    async def epoch():
        latencies = []
        done = 0
        t0 = time.perf_counter()

        async def worker():
            nonlocal done
            while done < steps:
                done += 1
                s = time.perf_counter()
                await one_step()
                latencies.append(time.perf_counter() - s)

        await asyncio.gather(*[worker() for _ in range(concurrency)])
        return latencies, time.perf_counter() - t0

    runs = [await epoch() for _ in range(epochs)]
    await handler.stop()
    del handler
    gc.collect()

    epoch_rates = [round(len(l) / w / n_chips, 3) for l, w in runs]
    latencies, wall = max(runs, key=lambda e: len(e[0]) / e[1])
    steps_per_sec = len(latencies) / wall / n_chips
    p50_ms = statistics.median(latencies) * 1000.0
    n_params = get_model_config(cfg.model_name).param_count()
    on_accel = cfg.provider != "cpu"
    decode_tok_s = len(latencies) * MAX_NEW_TOKENS / wall / n_chips
    peak_flops = 197e12 if on_accel else 1e12  # v5e bf16 peak per chip
    return {
        "model": cfg.model_name,
        "steps_per_sec_per_chip": round(steps_per_sec, 3),
        "median_epoch_steps_per_sec": round(
            statistics.median(epoch_rates), 3
        ),
        "p50_step_ms": round(p50_ms, 1),
        "decode_tokens_per_sec_per_chip": round(decode_tok_s, 1),
        "mfu": round(decode_tok_s * 2.0 * n_params / peak_flops, 4),
        "concurrency": concurrency,
        "steps": len(latencies),
        "speculate": cfg.engine_speculate,
        "quantize": cfg.quantize,
        "epoch_steps_per_sec": epoch_rates,
    }


async def run_bench():
    from pilottai_tpu.core.config import LLMConfig

    platform = jax.default_backend()
    on_accel = platform not in ("cpu",)
    n_chips = max(len(jax.devices()), 1) if on_accel else 1

    common = dict(
        provider="tpu" if on_accel else "cpu",
        engine_max_seq=512,
        dtype="bfloat16" if on_accel else "float32",
        # Swept on v5e round 2 (chunk ∈ {8,12,16,24} × {bf16,int8}): int8
        # + chunk 12 won; speculation (round 3) rides the same chunking.
        engine_chunk=12,
        quantize="int8" if on_accel else None,
        # n-gram verify-blocks: decode is weight-stream-bound, accepted
        # drafts are ~free tokens (engine/decode.py:decode_chunk_spec).
        engine_speculate=4,
    )

    # Section 1: 1B throughput model (byte vocab: runs without a
    # checkpoint download in the zero-egress environment).
    sec_1b = await bench_model(
        LLMConfig(
            model_name="llama3-1b-byte" if on_accel else "llama-tiny",
            engine_slots=32,
            # One fused admission per 32-slot wave + chunk 14 so a wave's
            # 48 tokens fit one dispatch (swept on v5e round 3:
            # p50 403 -> ~207 ms vs round 2).
            engine_admit_batch=32,
            **{**common, "engine_chunk": 14},
        ),
        concurrency=32, steps=96, epochs=3, n_chips=n_chips,
    )

    # Section 2: the north-star model. int8 8B params stream at ~8 GB per
    # token-pass; speculation is what breaks the one-token-per-pass
    # bandwidth floor (VERDICT r2 Weak #2).
    sec_8b = None
    if on_accel:
        sec_8b = await bench_model(
            LLMConfig(
                # chunk 14 x acceptance ~3.75 covers the whole 48-token
                # step in ONE dispatch (swept 12/14/16 on v5e round 3).
                model_name="llama3-8b-byte", engine_slots=8,
                **{**common, "engine_chunk": 14},
            ),
            concurrency=8, steps=32, epochs=2, n_chips=n_chips,
        )

    headline = sec_8b or sec_1b
    out = {
        "metric": "agent_steps_per_sec_per_chip",
        "value": sec_1b["steps_per_sec_per_chip"],
        "unit": "steps/s/chip",
        # ≥ 1.0 ⇔ the north-star model meets the ≤500 ms p50 target.
        "vs_baseline": round(TARGET_P50_MS / headline["p50_step_ms"], 3),
        "p50_step_ms": sec_1b["p50_step_ms"],
        "p50_step_ms_8b": sec_8b["p50_step_ms"] if sec_8b else None,
        "provider": "tpu" if on_accel else "cpu",
        "n_chips": n_chips,
        "models": {sec_1b["model"]: sec_1b,
                   **({sec_8b["model"]: sec_8b} if sec_8b else {})},
    }
    print(json.dumps(out))


if __name__ == "__main__":
    asyncio.run(run_bench())
