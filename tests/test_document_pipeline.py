"""Flagship example integration test: the hierarchical document pipeline
(examples/document_pipeline) end-to-end on the mock provider.

Reference counterpart: ``docs/examples/pdf_processing`` — the reference's
only end-to-end workload, which its own test suite never exercises
(SURVEY.md §4: the integration test there targets a nonexistent API).
"""

import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from examples.document_pipeline.pipeline import (  # noqa: E402
    SAMPLE_DOC,
    build_pipeline,
    run_pipeline,
    split_sections,
    stage_tasks,
)


def test_split_sections_parses_sample():
    text = SAMPLE_DOC.read_text(encoding="utf-8")
    sections = split_sections(text)
    assert len(sections) == 4
    assert sections[0][0] == "Serving fleet"
    assert all(len(body) > 50 for _, body in sections)


def test_split_sections_headingless():
    assert split_sections("just a note") == [("document", "just a note")]


@pytest.mark.asyncio
async def test_pipeline_end_to_end_mock():
    out = await run_pipeline(provider="mock")
    stages = out["stages"]
    assert stages["extract"]["success"]
    assert stages["extract"]["output"]["sections"] == 4
    assert stages["evaluate"]["success"]
    assert stages["evaluate"]["output"]["valid"]
    assert stages["summarize"]["success"]
    # The answer is grounded in retrieved sections, and the risk section
    # (the question asks for "the main risk") is among them.
    assert any("saturating" in text for text in out["answer"])
    assert out["memory_items"] == 4
    assert out["serve_metrics"]["tasks_completed"] == 3
    assert out["serve_metrics"]["tasks_failed"] == 0


@pytest.mark.asyncio
async def test_pipeline_end_to_end_with_embedder():
    """Same flow with the on-device embedding encoder attached: the
    summarize stage must retrieve via semantic top-k (BASELINE config #2
    path) rather than the keyword fallback."""
    out = await run_pipeline(provider="mock", use_embedder=True)
    assert out["stages"]["summarize"]["success"]
    assert len(out["answer"]) >= 2  # semantic top-k returns multiple sections
    assert out["grounding"], "semantic_search returned nothing"


@pytest.mark.asyncio
async def test_manager_hierarchy_and_stage_routing():
    """The manager's children are the three workers, and each stage lands
    on the agent specialized for it (hierarchy: SURVEY §2.12-b)."""
    serve, memory = build_pipeline(provider="mock")
    assert len(serve.manager_agent.child_agents) == 3
    await serve.start()
    try:
        tasks = stage_tasks(str(SAMPLE_DOC), "what changed?")
        results = await serve.execute(list(tasks))
        assert all(r.success for r in results)
        by_role = {
            a.role: a for a in serve.agent_list()
        }
        assert by_role["extractor"].task_metrics["completed"] == 1
        assert by_role["evaluator"].task_metrics["completed"] == 1
        assert by_role["generator"].task_metrics["completed"] == 1
    finally:
        await serve.stop()


@pytest.mark.asyncio
async def test_dependency_order_enforced():
    """summarize depends on evaluate depends on extract: completion order
    must follow the chain even under a parallel orchestrator."""
    serve, memory = build_pipeline(provider="mock")
    order = []
    serve.task_callback = lambda task, result: order.append(task.type)
    await serve.start()
    try:
        await serve.execute(list(stage_tasks(str(SAMPLE_DOC), "q")))
        assert order == ["extract", "evaluate", "summarize"]
    finally:
        await serve.stop()


def test_read_document_pdf_path_gated(tmp_path):
    """PDF extraction parity with the reference's pdf_extractor
    (``/root/reference/docs/examples/pdf_processing/pdf_extractor.py:7-40``):
    with pypdf installed the pipeline reads PDFs; without it the error is
    actionable, never a crash deeper in the stack."""
    from examples.document_pipeline.pipeline import read_document

    pdf = tmp_path / "report.pdf"
    pdf.write_bytes(b"%PDF-1.4 stub")
    try:
        import pypdf  # noqa: F401
    except ImportError:
        with pytest.raises(RuntimeError, match="pypdf"):
            read_document(str(pdf))
        return
    # pypdf present: a real (if trivial) parse attempt happens; errors
    # from a stub file are pypdf's own, not an AttributeError from us.
    try:
        read_document(str(pdf))
    except RuntimeError:
        pytest.fail("pypdf present but gated path still raised RuntimeError")
    except Exception:
        pass  # malformed stub — pypdf's parser complained, which is fine


def test_read_document_text(tmp_path):
    from examples.document_pipeline.pipeline import read_document

    doc = tmp_path / "notes.md"
    doc.write_text("## Heading\nBody text", encoding="utf-8")
    assert "Body text" in read_document(str(doc))


@pytest.mark.asyncio
async def test_bare_tool_args_act_on_the_requested_document(tmp_path):
    """code-review r5: a model invoking extract_sections with bare {}
    must act on the pipeline's own document, never silently fall back
    to the bundled sample."""
    from examples.document_pipeline.pipeline import build_pipeline

    doc = tmp_path / "mine.md"
    doc.write_text("## Only Section\nDistinctive body here", encoding="utf-8")
    serve, memory = build_pipeline(provider="mock", doc_path=doc)
    extractor = next(
        a for a in serve.agents.values() if a.config.role == "extractor"
    )
    out = await extractor.tools.get("extract_sections").execute({})
    assert out["headings"] == ["Only Section"]
    items = await memory.keyword_search("Distinctive", tags={"extract"})
    assert items
