"""Live metrics dashboard (utils/dashboard.py) — supersedes the
reference's static marketing stats (SURVEY.md §2.19)."""

import json
import urllib.error
import urllib.request

from pilottai_tpu.utils.dashboard import MetricsDashboard
from pilottai_tpu.utils.metrics import global_metrics


class _FakeServe:
    def get_metrics(self):
        return {"tasks_completed": 7, "agents": 2}


def _get(url):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get_content_type(), r.read()


def test_dashboard_serves_metrics_json_and_html():
    global_metrics.inc("dash.test_counter", 3)
    global_metrics.observe("dash.test_hist", 0.5)
    d = MetricsDashboard(source=_FakeServe(), port=0).start()
    try:
        status, ctype, body = _get(f"http://127.0.0.1:{d.port}/metrics.json")
        assert status == 200 and ctype == "application/json"
        m = json.loads(body)
        assert m["counters"]["dash.test_counter"] >= 3
        assert "dash.test_hist" in m["histograms"]
        assert m["component"] == {"tasks_completed": 7, "agents": 2}

        status, ctype, body = _get(f"http://127.0.0.1:{d.port}/")
        assert status == 200 and ctype == "text/html"
        assert b"pilottai-tpu metrics" in body

        try:
            _get(f"http://127.0.0.1:{d.port}/nope")
            raise AssertionError("404 expected")
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        d.stop()


def test_dashboard_serves_slo_snapshot():
    """/slo.json mirrors the API server's route: per-class targets +
    attainment/burn surface from obs.global_slo."""
    d = MetricsDashboard(source=_FakeServe(), port=0).start()
    try:
        status, ctype, body = _get(f"http://127.0.0.1:{d.port}/slo.json")
        assert status == 200 and ctype == "application/json"
        snap = json.loads(body)
        assert "interactive" in snap and "batch" in snap
        for entry in snap.values():
            assert "attainment" in entry and "burn_rate" in entry
            assert "targets" in entry
    finally:
        d.stop()


def test_dashboard_source_errors_do_not_break_endpoint():
    class Bad:
        def get_metrics(self):
            raise RuntimeError("boom")

    d = MetricsDashboard(source=Bad(), port=0).start()
    try:
        _, _, body = _get(f"http://127.0.0.1:{d.port}/metrics.json")
        m = json.loads(body)
        assert "error" in m["component"]
    finally:
        d.stop()
