"""Profile-guided configuration loop (ISSUE 18): workload profiler,
cost model, seasonal arrival forecasting and the glue around them —
atomic profile/autotune stores, the recommend CLI, the engine's boot
divergence warning and export-completeness over every new series."""

import json
import os
import subprocess
import sys
import threading
import time
from types import SimpleNamespace

import pytest

from pilottai_tpu.obs.costmodel import CostModel, validate_knobs
from pilottai_tpu.obs.flight import FlightRecorder
from pilottai_tpu.obs.forecast import ArrivalForecast, burstiness_cv
from pilottai_tpu.obs.profile import WorkloadProfiler
from pilottai_tpu.utils.metrics import MetricsRegistry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_SAMPLES = os.path.join(REPO, "tests", "fixtures",
                               "autoconf_samples.json")
FIXTURE_PROFILE = os.path.join(REPO, "tests", "fixtures",
                               "autoconf_profile.json")


# --------------------------------------------------------------------- #
# Forecast
# --------------------------------------------------------------------- #

def _sine_rate(phase, n_phases, lo=4.0, hi=16.0):
    import math

    return lo + (hi - lo) * 0.5 * (
        1.0 + math.sin(2.0 * math.pi * phase / n_phases)
    )


def test_forecast_tracks_shifted_diurnal_sine():
    """After a few replayed 'days' of a sinusoidal arrival trace, the
    forecast at lead L must track the TRUE rate L seconds ahead — the
    whole point of the seasonal curve is that forecast(now+L) is read
    off the learned shape, not extrapolated from the current rate."""
    bucket_s, n_phases = 1.0, 24
    fc = ArrivalForecast(bucket_s=bucket_s, period_s=bucket_s * n_phases,
                         alpha=0.5, gamma=0.5)
    for b in range(4 * n_phases):
        # Integer-rounded counts: the forecaster only ever sees whole
        # arrivals, tolerance below absorbs the quantization.
        fc.ingest_bucket(
            round(_sine_rate(b % n_phases, n_phases) * bucket_s),
            at=b * bucket_s,
        )
    assert fc.ready()
    now = 4 * n_phases * bucket_s
    for lead_phases in (2, 6, 12):
        lead = lead_phases * bucket_s
        predicted = fc.forecast_rps(lead_s=lead, now=now)
        truth = _sine_rate((4 * n_phases + lead_phases) % n_phases,
                           n_phases)
        assert abs(predicted - truth) <= 0.25 * truth + 1.0, (
            f"lead {lead_phases} phases: predicted {predicted:.2f} "
            f"vs truth {truth:.2f}"
        )


def test_forecast_leads_recurring_step_burst():
    """A recurring step burst must be visible in the forecast BEFORE it
    arrives: standing just ahead of the learned burst window, the
    lead-time forecast has to be a multiple of the current rate."""
    bucket_s, n_phases = 1.0, 20
    burst = set(range(12, 15))
    fc = ArrivalForecast(bucket_s=bucket_s, period_s=bucket_s * n_phases,
                         alpha=0.5, gamma=0.5)
    # Three periods of history, then live traffic up to phase 10 of the
    # fourth — the forecaster must not be read across a silent gap here
    # (silence is data and would rightly pull the level down).
    for b in range(3 * n_phases + 10):
        rate = 20.0 if (b % n_phases) in burst else 4.0
        fc.ingest_bucket(int(rate * bucket_s), at=b * bucket_s)
    assert fc.ready()
    now = (3 * n_phases + 10) * bucket_s  # phase 10: two phases pre-burst
    current = fc.current_rps(now=now)
    ahead = fc.forecast_rps(lead_s=2 * bucket_s, now=now)
    assert ahead >= 3.0 * current, (
        f"forecast {ahead:.2f} does not lead current {current:.2f}"
    )
    # And the forecast past the burst window falls back to base rate.
    after = fc.forecast_rps(lead_s=7 * bucket_s, now=now)
    assert after <= 2.0 * current


def test_forecast_not_ready_until_full_period():
    fc = ArrivalForecast(bucket_s=1.0, period_s=10.0)
    for b in range(9):
        fc.ingest_bucket(5, at=float(b))
    assert not fc.ready()
    # Consumers see the open-bucket estimate, and DynamicScaling's
    # boost stays 1.0 (gated on ready()) — checked in the scaling test.
    fc.ingest_bucket(5, at=9.0)
    fc.ingest_bucket(5, at=10.0)  # closes bucket 9 -> full period
    assert fc.ready()


def test_forecast_counts_silence_and_bounds_gaps():
    """Empty buckets are data (rate 0); a gap longer than one period
    folds in at most one period of silence."""
    fc = ArrivalForecast(bucket_s=1.0, period_s=4.0)
    fc.ingest_bucket(8, at=0.0)
    fc.ingest_bucket(8, at=1.0)
    # Jump far ahead: only n_phases empty buckets close.
    fc.observe(at=100.0, n=1)
    snap = fc.snapshot()
    assert snap["ready"]
    assert snap["seasonal_mean_rps"] < 4.0  # silence pulled the curve down


def test_burstiness_cv():
    assert burstiness_cv([1.0] * 10) == pytest.approx(0.0)
    bursty = [0.01] * 9 + [10.0]
    assert burstiness_cv(bursty) > 1.5
    assert burstiness_cv([]) == 0.0
    assert burstiness_cv([5.0]) == 0.0


# --------------------------------------------------------------------- #
# Cost model
# --------------------------------------------------------------------- #

def _samples_1d():
    return [
        {"knobs": {"engine_chunk": 8, "engine_slots": 8},
         "metrics": {"attainment": 0.80, "steps_per_s": 10.0},
         "workload": "interactive"},
        {"knobs": {"engine_chunk": 24, "engine_slots": 8},
         "metrics": {"attainment": 0.92, "steps_per_s": 14.0},
         "workload": "interactive"},
    ]


def test_costmodel_exact_on_recorded_points():
    model = CostModel(samples=_samples_1d())
    assert model.predict({"engine_chunk": 8, "engine_slots": 8},
                         "attainment") == pytest.approx(0.80)
    assert model.predict({"engine_chunk": 24, "engine_slots": 8},
                         "steps_per_s") == pytest.approx(14.0)


def test_costmodel_monotone_between_recorded_points():
    """Between two recorded 1-D knob points the interpolation is a
    convex combination: values stay inside the recorded bracket and move
    monotonically as the query slides from one point to the other."""
    model = CostModel(samples=_samples_1d())
    preds = [
        model.predict({"engine_chunk": c, "engine_slots": 8}, "attainment")
        for c in (8, 12, 16, 20, 24)
    ]
    assert all(0.80 <= p <= 0.92 for p in preds)
    assert preds == sorted(preds), f"not monotone: {preds}"


def test_costmodel_recommend_weights_by_class_mix():
    """The recommendation must follow the profile's class mix: a vector
    that wins interactive loses to one that wins batch when the measured
    traffic is batch-heavy, and vice versa."""
    samples = [
        {"knobs": {"engine_chunk": 8}, "workload": "interactive",
         "metrics": {"attainment": 0.95, "steps_per_s": 10.0}},
        {"knobs": {"engine_chunk": 8}, "workload": "batch",
         "metrics": {"attainment": 0.60, "steps_per_s": 10.0}},
        {"knobs": {"engine_chunk": 32}, "workload": "interactive",
         "metrics": {"attainment": 0.70, "steps_per_s": 10.0}},
        {"knobs": {"engine_chunk": 32}, "workload": "batch",
         "metrics": {"attainment": 0.90, "steps_per_s": 10.0}},
    ]
    model = CostModel(samples=samples)
    rec_i = model.recommend(
        profile={"class_mix": {"interactive": 0.9, "batch": 0.1}}
    )
    rec_b = model.recommend(
        profile={"class_mix": {"interactive": 0.1, "batch": 0.9}}
    )
    assert rec_i["knobs"]["engine_chunk"] == 8
    assert rec_b["knobs"]["engine_chunk"] == 32


def test_costmodel_recommend_deterministic_with_deltas():
    model = CostModel(samples=_samples_1d())
    profile = {"class_mix": {"interactive": 1.0}}
    default = {"engine_chunk": 8, "engine_slots": 8}
    a = model.recommend(profile=profile, default_knobs=default)
    b = model.recommend(profile=profile, default_knobs=default)
    assert a == b
    assert a["knobs"]["engine_chunk"] == 24
    assert a["delta"]["attainment"] == pytest.approx(0.12)
    assert a["violations"] == []


def test_validate_knobs_flags_out_of_bounds_and_unknown():
    problems = validate_knobs({
        "engine_chunk": 9999,           # outside [1, 512]
        "engine_slots": 8,              # fine
        "engine_chunk_policy": "magic",  # not in the categorical set
        "made_up_knob": 3,              # unknown
    })
    assert any("engine_chunk=9999" in p for p in problems)
    assert any("engine_chunk_policy" in p for p in problems)
    assert any("made_up_knob" in p for p in problems)
    assert not any("engine_slots" in p for p in problems)
    assert validate_knobs({"engine_chunk": 16}) == []


# --------------------------------------------------------------------- #
# Atomic stores
# --------------------------------------------------------------------- #

@pytest.fixture()
def _cache_dir(tmp_path, monkeypatch):
    from pilottai_tpu.utils import compile_cache

    monkeypatch.setenv("PILOTTAI_COMPILE_CACHE", str(tmp_path))
    monkeypatch.setattr(compile_cache, "_enabled_dir", None)
    return tmp_path


def test_store_autotune_survives_concurrent_writers(_cache_dir):
    """N threads each persist their own key into the shared autotune
    store; the merge-under-race discipline (write-temp + rename +
    verify-own-key) must keep every entry — a plain read-modify-rename
    loses whichever writer renamed first."""
    from pilottai_tpu.utils.compile_cache import load_autotune, store_autotune

    n = 12
    barrier = threading.Barrier(n)

    def writer(i):
        barrier.wait()
        store_autotune(f"race_key_{i}", 100 + i)

    threads = [threading.Thread(target=writer, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    lost = [i for i in range(n) if load_autotune(f"race_key_{i}") != 100 + i]
    assert not lost, f"store race lost keys {lost}"


def test_store_profile_roundtrip_preserves_other_keys(_cache_dir):
    from pilottai_tpu.utils.compile_cache import load_profile, store_profile

    store_profile("dep-a", {"fingerprint": {"requests": 10}})
    store_profile("dep-b", {"recommendation": {"knobs": {"engine_chunk": 8}}})
    assert load_profile("dep-a") == {"fingerprint": {"requests": 10}}
    assert load_profile("dep-b")["recommendation"]["knobs"] == {
        "engine_chunk": 8
    }
    # Corrupt store starts fresh instead of raising.
    (_cache_dir / "profiles.json").write_text("{not json")
    assert load_profile("dep-a") is None
    store_profile("dep-c", {"x": 1})
    assert load_profile("dep-c") == {"x": 1}


# --------------------------------------------------------------------- #
# Profiler
# --------------------------------------------------------------------- #

def _stub_flight(**attrs):
    return SimpleNamespace(
        attributes=attrs, n_tokens=attrs.pop("_n_tokens", 0)
    )


def test_profiler_fingerprint_and_gauges():
    reg = MetricsRegistry()
    fc = ArrivalForecast(bucket_s=1.0, period_s=10.0)
    prof = WorkloadProfiler(window=64, registry=reg, forecast=fc)
    prof.configure("dep-test")
    for i in range(10):
        prof.observe_start(_stub_flight())
        prof.observe_flight(_stub_flight(
            prompt_tokens=100 + i, _n_tokens=20,
            slo_class="interactive" if i % 2 else "batch",
            session_id="s1" if i < 5 else None,
            dag_node="stage-a" if i < 3 else None,
        ))
    fp = prof.fingerprint()
    assert fp["deployment"] == "dep-test"
    assert fp["requests"] == 10
    assert 100 <= fp["prompt_tokens"]["p50"] <= 109
    assert fp["output_tokens"]["p50"] == 20
    assert fp["class_mix"] == {"batch": 0.5, "interactive": 0.5}
    assert fp["session_frac"] == pytest.approx(0.5)
    assert fp["dag"]["frac"] == pytest.approx(0.3)
    assert fp["dag"]["stage_mix"] == {"stage-a": 1.0}
    assert fp["arrival"]["observed"] == 10
    assert "forecast" in fp

    prof.refresh_gauges()
    gauges = reg.snapshot()["gauges"]
    assert gauges["profile.class_frac.interactive"] == pytest.approx(0.5)
    assert gauges["profile.session_frac"] == pytest.approx(0.5)
    assert gauges["profile.prompt_tokens_p50"] >= 100

    prof.reset()
    assert prof.fingerprint()["requests"] == 0


def test_profiler_persist_roundtrip(_cache_dir):
    from pilottai_tpu.utils.compile_cache import load_profile, store_profile

    reg = MetricsRegistry()
    prof = WorkloadProfiler(
        window=16, registry=reg,
        forecast=ArrivalForecast(bucket_s=1.0, period_s=4.0),
    )
    prof.configure("dep-persist")
    prof.observe_flight(_stub_flight(prompt_tokens=42, _n_tokens=7))
    # A stored recommendation must survive a fingerprint persist.
    store_profile("dep-persist", {"recommendation": {"knobs": {"x": 1}}})
    assert prof.persist() == "dep-persist"
    blob = load_profile("dep-persist")
    assert blob["fingerprint"]["requests"] == 1
    assert blob["recommendation"] == {"knobs": {"x": 1}}


def test_flight_start_listener_fires_once_per_flight():
    rec = FlightRecorder(max_finished=16)
    fired = []
    rec.add_start_listener(lambda f: fired.append(f.flight_id))
    rec.start("f-1", slo_class="interactive")
    rec.start("f-1", prompt_tokens=12)  # attribute merge, not an arrival
    rec.start("f-2")
    assert fired == ["f-1", "f-2"]
    # A raising listener must not break the hot path.
    rec.add_start_listener(lambda f: 1 / 0)
    rec.start("f-3")
    assert fired[-1] == "f-3"


# --------------------------------------------------------------------- #
# Scaling integration + export completeness
# --------------------------------------------------------------------- #

def _sim_orchestrator(n_agents=2, util=0.0):
    class _Agent:
        queue_utilization = util
        current_tasks = ()
        success_rate = 1.0
        status = "busy"

        class task_queue:  # noqa: N801 — queue-shaped stub
            @staticmethod
            def qsize():
                return 0

    return SimpleNamespace(
        agents={f"a{i}": object() for i in range(n_agents)},
        task_queue=[],
        running_tasks={},
        config=SimpleNamespace(max_queue_size=100, max_concurrent_tasks=16),
        agent_list=lambda: [_Agent() for _ in range(n_agents)],
    )


def test_scaling_forecast_boost_gated_and_exported():
    """A primed forecaster showing a coming ramp multiplies the load
    signal (capped); a cold forecaster or ``forecast_enabled=False``
    leaves the load untouched. Both cases export scaling.forecast_*."""
    from pilottai_tpu.core.config import ScalingConfig
    from pilottai_tpu.orchestration.scaling import DynamicScaling

    now = [0.0]
    fc = ArrivalForecast(bucket_s=1.0, period_s=10.0,
                         alpha=0.5, gamma=0.5, clock=lambda: now[0])
    burst = {7, 8}
    for b in range(35):  # 3 periods + live traffic up to phase 5
        rate = 20.0 if (b % 10) in burst else 4.0
        fc.ingest_bucket(int(rate), at=float(b))
    assert fc.ready()
    now[0] = 35.0  # phase 5: burst is 2 phases ahead

    reg = MetricsRegistry()
    scaler = DynamicScaling(
        _sim_orchestrator(),
        ScalingConfig(forecast_enabled=True, forecast_lead_s=2.0,
                      forecast_boost_cap=3.0),
        registry=reg, forecast=fc,
    )
    sig = scaler.signals()
    assert sig["forecast_boost"] > 2.0  # 20/4 capped at 3.0
    assert sig["forecast_rps"] > 10.0
    gauges = reg.snapshot()["gauges"]
    assert gauges["scaling.forecast_rps"] > 10.0
    assert gauges["scaling.forecast_lead_s"] == 2.0
    # Boost multiplies the blended load.
    base = {k: 0.0 for k in sig}
    base.update(agent_queue_util=0.3, forecast_boost=sig["forecast_boost"])
    assert scaler.system_load(signals=base) == pytest.approx(
        min(1.0, 0.3 * sig["forecast_boost"])
    )

    # Disabled: boost pinned to 1.0 even with the same hot forecaster.
    reg2 = MetricsRegistry()
    off = DynamicScaling(
        _sim_orchestrator(),
        ScalingConfig(forecast_enabled=False, forecast_lead_s=2.0),
        registry=reg2, forecast=fc,
    )
    assert off.signals()["forecast_boost"] == 1.0

    # Cold forecaster: not ready -> boost 1.0.
    reg3 = MetricsRegistry()
    cold = DynamicScaling(
        _sim_orchestrator(),
        ScalingConfig(forecast_enabled=True),
        registry=reg3, forecast=ArrivalForecast(bucket_s=1.0, period_s=10.0),
    )
    assert cold.signals()["forecast_boost"] == 1.0


def test_export_completeness_clean_over_new_series():
    """Every series this PR adds — profile.*, scaling.forecast_*,
    engine.spec_acceptance — must reach both export surfaces from
    declaration alone (zero-filled before traffic)."""
    from pilottai_tpu import obs
    from pilottai_tpu.core.config import ScalingConfig
    from pilottai_tpu.orchestration.scaling import DynamicScaling

    # Global surface: profiler gauges + engine.spec_acceptance are
    # declared at import; the global registry must stay clean.
    assert obs.export_completeness() == []
    snap = obs.metrics_snapshot()
    for name in ("profile.arrival_rps", "profile.class_frac.interactive",
                 "engine.spec_acceptance"):
        assert name in snap["gauges"], f"{name} missing from snapshot"

    # Isolated scaler surface: scaling.* declared at construction.
    reg = MetricsRegistry()
    WorkloadProfiler(registry=reg,
                     forecast=ArrivalForecast(bucket_s=1.0, period_s=4.0))
    DynamicScaling(_sim_orchestrator(), ScalingConfig(), registry=reg)
    assert obs.export_completeness(registry=reg) == []
    gauges = obs.metrics_snapshot(registry=reg)["gauges"]
    for name in ("scaling.forecast_rps", "scaling.forecast_lead_s",
                 "profile.burstiness_cv"):
        assert name in gauges, f"{name} missing from isolated snapshot"


@pytest.mark.asyncio
async def test_profile_json_on_api_server_and_dashboard():
    """The fingerprint ships on BOTH http surfaces with the same shape
    (server.py + utils/dashboard.py mirror every export route)."""
    import urllib.request

    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.mock import MockBackend
    from pilottai_tpu.server import APIServer
    from pilottai_tpu.utils.dashboard import MetricsDashboard
    from tests.test_server import _request

    llm = LLMHandler(LLMConfig(provider="mock"), backend=MockBackend())
    server = await APIServer(llm).start()
    dash = MetricsDashboard().start()
    try:
        status, _, body = await _request(server.port, "GET", "/profile.json")
        assert status == 200
        fp = json.loads(body)
        for key in ("arrival", "class_mix", "prompt_tokens",
                    "output_tokens", "forecast", "session_frac"):
            assert key in fp, f"{key} missing from /profile.json"

        with urllib.request.urlopen(
            f"http://127.0.0.1:{dash.port}/profile.json", timeout=10
        ) as resp:
            dfp = json.loads(resp.read())
        assert set(dfp) == set(fp)
    finally:
        dash.stop()
        await server.stop()


# --------------------------------------------------------------------- #
# Boot divergence warning
# --------------------------------------------------------------------- #

def test_engine_boot_warning_on_knob_divergence(monkeypatch):
    """One-shot advisory when the active knob vector diverges from the
    stored recommendation; silent when nothing is stored."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.native import NativeEngine
    from pilottai_tpu.utils import compile_cache

    warnings = []

    def _engine(model="warn-test-model"):
        eng = NativeEngine.__new__(NativeEngine)
        eng.config = LLMConfig(model_name=model, provider="cpu",
                               engine_chunk=16)
        eng._log = SimpleNamespace(
            warning=lambda msg, *a: warnings.append(msg % a if a else msg)
        )
        return eng

    # Nothing stored: silent.
    monkeypatch.setattr(compile_cache, "load_profile", lambda key: None)
    _engine()._warn_knob_divergence()
    assert warnings == []

    # Stored recommendation diverges: exactly one warning per engine.
    monkeypatch.setattr(
        compile_cache, "load_profile",
        lambda key: {"recommendation": {"knobs": {"engine_chunk": 24}}},
    )
    eng = _engine()
    eng._warn_knob_divergence()
    eng._warn_knob_divergence()
    assert len(warnings) == 1
    assert "engine_chunk=16" in warnings[0]
    assert "24" in warnings[0]

    # Matching vector: silent.
    monkeypatch.setattr(
        compile_cache, "load_profile",
        lambda key: {"recommendation": {"knobs": {"engine_chunk": 16}}},
    )
    warnings.clear()
    _engine()._warn_knob_divergence()
    assert warnings == []


# --------------------------------------------------------------------- #
# recommend CLI over the committed fixtures (the CI autoconf lane gate)
# --------------------------------------------------------------------- #

def _run_recommend():
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "recommend.py"),
         "--json"],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout)


def test_recommend_cli_deterministic_and_in_bounds():
    a = _run_recommend()
    b = _run_recommend()
    assert a == b, "recommendation is not deterministic"
    assert a["violations"] == []
    assert validate_knobs(a["knobs"]) == []
    # The recommendation must not lose to the default on its own
    # weighted-score axis over the recorded workload.
    assert a["score"]["attainment"] >= a["default_score"]["attainment"]


def test_recommend_fixtures_are_committed_and_consistent():
    with open(FIXTURE_SAMPLES) as fh:
        samples = json.load(fh)["samples"]
    assert len(samples) >= 4
    for s in samples:
        assert validate_knobs(s["knobs"]) == [], s
        assert "attainment" in s["metrics"]
        assert "steps_per_s" in s["metrics"]
    with open(FIXTURE_PROFILE) as fh:
        profile = json.load(fh)
    fp = profile.get("fingerprint", profile)
    assert fp["class_mix"], "profile fixture has no class mix"
