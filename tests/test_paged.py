"""Paged KV cache (ops/paged.py + ops/pallas/paged_attention.py).

Parity discipline: every paged path is pinned against the dense cache,
which is itself pinned against the single-step reference
(tests/test_decode_chunk.py) — so paged == dense == reference.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.decode import (
    DecodeState,
    admit_group,
    decode_chunk,
    pack_admit_meta,
)
from pilottai_tpu.engine.sampling import SamplingState
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.ops.kvcache import KVCache
from pilottai_tpu.ops.paged import (
    PageAllocator,
    PagedKVCache,
    gather_pages,
)
from pilottai_tpu.ops.pallas.paged_attention import paged_decode_attention


# --------------------------------------------------------------------- #
# Allocator
# --------------------------------------------------------------------- #

def test_allocator_lifecycle():
    a = PageAllocator(num_pages=9, page_size=16, n_slots=4, max_pages_per_slot=4)
    assert a.free_pages == 8            # sentinel page never allocated
    assert a.pages_needed(1) == 1 and a.pages_needed(16) == 1
    assert a.pages_needed(17) == 2
    assert a.allocate(0, 40)            # 3 pages
    assert a.free_pages == 5
    assert (a.table[0, :3] != a.sentinel).all() and a.table[0, 3] == a.sentinel
    assert a.allocate(1, 64)            # 4 pages
    assert a.free_pages == 1
    assert not a.allocate(2, 17)        # needs 2, only 1 free — no change
    assert a.free_pages == 1
    a.release(0)
    assert a.free_pages == 4
    assert (a.table[0] == a.sentinel).all()
    assert a.allocate(2, 17)
    # Per-slot capacity cap.
    a2 = PageAllocator(num_pages=100, page_size=16, n_slots=1, max_pages_per_slot=2)
    assert not a2.allocate(0, 64)       # 4 pages > 2-page slot capacity


# --------------------------------------------------------------------- #
# Kernel parity (interpret mode on CPU)
# --------------------------------------------------------------------- #

def _mk_paged(rng, B=4, K=2, P=16, num_pages=33, H=64, lengths=(37, 20, 0, 50)):
    """Build a pool + table holding random K/V at the right positions, and
    the equivalent dense [B, K, S, H] panels for the oracle."""
    alloc = PageAllocator(num_pages, P, B, max_pages_per_slot=4)
    S = 4 * P
    k_dense = jnp.asarray(rng.normal(size=(B, K, S, H)), jnp.float32)
    v_dense = jnp.asarray(rng.normal(size=(B, K, S, H)), jnp.float32)
    k_pool = np.zeros((K, num_pages, P, H), np.float32)
    v_pool = np.zeros((K, num_pages, P, H), np.float32)
    for b, ln in enumerate(lengths):
        if ln == 0:
            continue
        assert alloc.allocate(b, ln)
        for j in range(alloc.pages_needed(ln)):
            pg = alloc.table[b, j]
            k_pool[:, pg] = np.asarray(k_dense[b, :, j * P:(j + 1) * P])
            v_pool[:, pg] = np.asarray(v_dense[b, :, j * P:(j + 1) * P])
    return (
        jnp.asarray(k_pool), jnp.asarray(v_pool),
        jnp.asarray(alloc.table), k_dense, v_dense,
        jnp.asarray(lengths, jnp.int32),
    )


@pytest.mark.parametrize("window,softcap", [(0, 0.0), (24, 0.0), (0, 30.0)])
def test_paged_kernel_matches_gather(window, softcap):
    from pilottai_tpu.engine.decode import _prefix_stats_dense

    rng = np.random.default_rng(0)
    B, K, P, H, N = 4, 2, 16, 64, 4
    k_pool, v_pool, table, k_dense, v_dense, lengths = _mk_paged(rng)
    q = jnp.asarray(rng.normal(size=(B, N, H)), jnp.float32)
    last = lengths - 1
    qpos = lengths  # decoding the next position
    scale = H ** -0.5

    acc, m, l = paged_decode_attention(
        q, k_pool, v_pool, table, last, q_positions=qpos,
        n_blocks=4, scale=scale, softcap=softcap, window=window,
        interpret=True,
    )
    G = N // K
    acc_r, m_r, l_r = _prefix_stats_dense(
        q.reshape(B, K, G, H),
        gather_pages(k_pool, table, 4), gather_pages(v_pool, table, 4),
        last, qpos, scale, softcap, window,
    )
    # Live rows agree; fully-empty rows (length 0) produce l == 0 in both.
    np.testing.assert_allclose(np.asarray(l), np.asarray(l_r), rtol=1e-5)
    live = np.asarray(lengths) > 0
    np.testing.assert_allclose(
        np.asarray(acc)[live], np.asarray(acc_r)[live], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(m)[live], np.asarray(m_r)[live], rtol=1e-5
    )
    assert float(np.asarray(l)[~live].max(initial=0.0)) == 0.0


def test_paged_kernel_q_blocks_matches_per_row_calls(
):
    """The speculative q_blocks path: D packed queries per head row must
    equal D separate single-query kernel calls at shifted positions
    (window exercises the per-row position offsets)."""
    from pilottai_tpu.engine.decode import _prefix_stats_dense

    rng = np.random.default_rng(2)
    B, K, P, H, D = 4, 2, 16, 64, 3
    k_pool, v_pool, table, k_dense, v_dense, lengths = _mk_paged(rng)
    G = 2
    q = jnp.asarray(rng.normal(size=(B, K, G, D, H)), jnp.float32)
    last = lengths - 1
    qpos = lengths
    scale = H ** -0.5

    acc, m, l = paged_decode_attention(
        q.reshape(B, K * G * D, H), k_pool, v_pool, table, last,
        q_positions=qpos, n_blocks=4, scale=scale, window=24,
        q_blocks=D, interpret=True,
    )
    acc = np.asarray(acc).reshape(B, K, G, D, H)
    m = np.asarray(m).reshape(B, K, G, D)
    live = np.asarray(lengths) > 0
    for d in range(D):
        acc_r, m_r, _ = _prefix_stats_dense(
            q[:, :, :, d],
            gather_pages(k_pool, table, 4), gather_pages(v_pool, table, 4),
            last, qpos + d, scale, 0.0, 24,
        )
        acc_r = np.asarray(acc_r).reshape(B, K, G, H)
        np.testing.assert_allclose(
            acc[live][:, :, :, d], acc_r[live], rtol=2e-4, atol=2e-4
        )
        np.testing.assert_allclose(
            m[live][:, :, :, d], np.asarray(m_r).reshape(B, K, G)[live],
            rtol=1e-5,
        )


def test_paged_kernel_int8_scales_match_dequant_oracle():
    """Quantized pools + in-kernel dequant must agree with the dense
    oracle run over explicitly dequantized panels."""
    from pilottai_tpu.engine.decode import _prefix_stats_dense
    from pilottai_tpu.ops.kvcache import dequantize_kv, quantize_kv

    rng = np.random.default_rng(3)
    B, K, P, H, N = 4, 2, 16, 64, 4
    k_pool, v_pool, table, *_ , lengths = _mk_paged(rng)
    kq, ksc = quantize_kv(k_pool)
    vq, vsc = quantize_kv(v_pool)
    q = jnp.asarray(rng.normal(size=(B, N, H)), jnp.float32)
    last = lengths - 1
    scale = H ** -0.5

    acc, m, l = paged_decode_attention(
        q, kq, vq, table, last, q_positions=lengths,
        n_blocks=4, scale=scale, k_scales=ksc, v_scales=vsc,
        interpret=True,
    )
    acc_r, m_r, l_r = _prefix_stats_dense(
        q.reshape(B, K, N // K, H),
        gather_pages(dequantize_kv(kq, ksc, jnp.float32), table, 4),
        gather_pages(dequantize_kv(vq, vsc, jnp.float32), table, 4),
        last, lengths, scale, 0.0, 0,
    )
    live = np.asarray(lengths) > 0
    np.testing.assert_allclose(
        np.asarray(acc)[live], np.asarray(acc_r)[live], rtol=2e-4, atol=2e-4
    )
    np.testing.assert_allclose(
        np.asarray(l)[live], np.asarray(l_r)[live], rtol=1e-4
    )


def test_gather_pages_reconstructs_dense():
    rng = np.random.default_rng(1)
    k_pool, _, table, k_dense, _, lengths = _mk_paged(rng)
    got = gather_pages(k_pool, table, 4)
    for b, ln in enumerate(np.asarray(lengths)):
        np.testing.assert_array_equal(
            np.asarray(got)[b, :, :ln], np.asarray(k_dense)[b, :, :ln]
        )


# --------------------------------------------------------------------- #
# Fused decode chunk: paged == dense, bit for bit
# --------------------------------------------------------------------- #

def _admit_both(cfg, params, budgets):
    """Admit the same two prompts into a dense cache and a paged cache via
    the production admit_group path."""
    B, S, A, T, P = 4, 128, 4, 64, 32
    rng = np.random.default_rng(0)
    lens = np.array([17, 33, 0, 0], np.int32)
    tokens = np.zeros((A, T), np.int32)
    for i in range(2):
        tokens[i, : lens[i]] = rng.integers(2, cfg.vocab_size, lens[i])
    mi, mf = pack_admit_meta(
        A, slots=[0, 2, B, B], temps=[30.0] * A,
        seeds=range(10, 10 + A), budgets=budgets, lens=lens, pad_slot=B,
    )
    base_args = (jnp.asarray(tokens), jnp.asarray(mi), jnp.asarray(mf))

    dense = KVCache.create(cfg.n_layers, B, S, cfg.n_kv_heads, cfg.head_dim,
                           dtype=jnp.float32)
    d_out = admit_group(
        params, cfg, dense, DecodeState.create(B), SamplingState.create(B),
        *base_args, use_flash=False,
    )

    alloc = PageAllocator(4 * B + 1, P, B, max_pages_per_slot=S // P)
    for row, slot in enumerate([0, 2]):
        assert alloc.allocate(slot, int(lens[row]) + int(budgets[row]) + 1)
    pr = np.full((A, S // P), alloc.sentinel, np.int32)
    pr[0] = alloc.table[0]
    pr[1] = alloc.table[2]
    paged = PagedKVCache.create(
        cfg.n_layers, B, 4 * B + 1, P, cfg.n_kv_heads, cfg.head_dim,
        dtype=jnp.float32,
    )
    p_out = admit_group(
        params, cfg, paged, DecodeState.create(B), SamplingState.create(B),
        *base_args, use_flash=False, page_rows=jnp.asarray(pr),
    )
    return d_out, p_out, jnp.asarray(alloc.table)


@pytest.mark.parametrize("prefix_bound", [None, 64])
def test_paged_chunk_matches_dense(prefix_bound):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    (dc, dd, ds, d_first, _), (pc, pd, psm, p_first, _), table = _admit_both(
        cfg, params, budgets=[20, 20, 0, 0]
    )
    np.testing.assert_array_equal(np.asarray(d_first), np.asarray(p_first))

    for _ in range(3):
        dt, dv, dc, dd, ds = decode_chunk(
            params, cfg, dc, dd, ds, 8, use_pallas=False,
            prefix_bound=prefix_bound,
        )
        pt, pv, pc, pd, psm = decode_chunk(
            params, cfg, pc, pd, psm, 8, use_pallas=False,
            prefix_bound=prefix_bound, table=table,
        )
        np.testing.assert_array_equal(np.asarray(dt), np.asarray(pt))
        np.testing.assert_array_equal(np.asarray(dv), np.asarray(pv))
    np.testing.assert_array_equal(
        np.asarray(dc.lengths), np.asarray(pc.lengths)
    )


# --------------------------------------------------------------------- #
# Engine end to end: long capacity, tiny pool, backpressure
# --------------------------------------------------------------------- #

def test_engine_paged_long_capacity_backpressure():
    """Per-slot capacity far beyond the pool (1 K slots, pool holds ~2
    requests at a time): admission must backpressure on pages, and every
    request still completes. (Capacity kept at 1 K so CPU warmup doesn't
    compile 8 K prefill buckets; the capacity math is identical.)"""
    from pilottai_tpu.core.config import LLMConfig, ReliabilityConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    async def main():
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=4,
            engine_max_seq=1024, engine_chunk=4, dtype="float32",
            engine_paged_kv=True, engine_page_size=32,
            # 9 usable pages = 288 tokens; each request pins
            # ceil((~40 prompt + 8 new)/32) = 2 pages.
            engine_kv_pages=10,
            # Deflake: page-gated requests queue behind a ~2-resident
            # pool, so one transiently slow attempt on a loaded box
            # could cascade through the handler-wide breaker and fail
            # the REMAINING requests as CircuitOpenError — masking
            # whatever actually hiccuped. The breaker is not what this
            # test measures; with it off, a genuine engine failure
            # still fails the test, with its real exception. The long
            # timeout absorbs in-module compile storms the same way.
            timeout=600.0,
            reliability=ReliabilityConfig(breaker_enabled=False),
        ))
        outs = await asyncio.gather(*[
            h.apredict(
                "x" * 40,
                params=GenerationParams(max_new_tokens=8, temperature=0.3,
                                        seed=i),
            )
            for i in range(8)
        ])
        # Page release happens at the device loop's next admission tick;
        # give it a beat before snapshotting. The prefix index keeps the
        # prompts' fully-covered pages pinned by design — every page is
        # either free or deliberately cached, none leaked to dead slots.
        # Deflake: up to 30 s of polling (was 5 s) — on a loaded box the
        # release tick queues behind slow folds, and a stale snapshot
        # here failed the page-accounting assertion below with a
        # wall-clock-derived miss, not a real leak.
        for _ in range(600):
            m = h.get_metrics()["backend"]
            if (
                m.get("kv_pages_free", 0) + m.get("prefix_pages", 0)
                == m.get("kv_pages_total")
            ):
                break
            await asyncio.sleep(0.05)
        await h.stop()
        return outs, m

    outs, metrics = asyncio.run(main())
    assert all(isinstance(o, str) for o in outs) and len(outs) == 8
    assert metrics["kv_pages_total"] == 9
    # All slot refs released; only the prefix cache's pins remain (the 8
    # prompts are identical, so the pins converge on one chain).
    assert metrics["kv_pages_free"] + metrics["prefix_pages"] == 9
    assert metrics["prefix_pages"] <= 2


def test_oversized_max_new_tokens_does_not_deadlock():
    """A request whose max_new_tokens exceeds the whole pool must still be
    admitted (need clamps to slot capacity; decode stops at ctx-full) —
    review finding: unclamped need made can_allocate permanently false and
    starved the FIFO head forever."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    async def main():
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=256, engine_chunk=4, dtype="float32",
            engine_paged_kv=True, engine_page_size=32, engine_kv_pages=9,
        ))
        # Pool: 8 usable pages = 256 tokens; max_new far beyond it.
        out = await h.apredict(
            "hi", params=GenerationParams(max_new_tokens=100000,
                                          temperature=0.0, json_mode=False),
        )
        # A normal request behind it must also complete.
        out2 = await h.apredict(
            "ok", params=GenerationParams(max_new_tokens=4)
        )
        await h.stop()
        return out, out2

    out, out2 = asyncio.run(main())
    assert isinstance(out, str) and isinstance(out2, str)


def test_prefill_failure_releases_pages():
    """A failed prefill group must return its pages to the pool and leave
    the slot reusable (review finding: the leak tripped allocate()'s
    held-pages invariant on slot reuse and shrank the pool forever)."""
    import pilottai_tpu.engine.batcher as bmod
    from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    b = ContinuousBatcher(cfg, params, n_slots=2, max_seq_len=128,
                          cache_dtype=jnp.float32, paged=True,
                          page_size=32, num_pages=9)
    real = bmod.admit_group

    def boom(*a, **k):
        raise RuntimeError("prefill exploded")

    bmod.admit_group = boom
    try:
        b.start()
        req = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=4)
        fut = b.submit(req)
        with pytest.raises(RuntimeError, match="prefill exploded"):
            fut.result(timeout=30)
        import time
        deadline = time.monotonic() + 10
        while b.alloc.free_pages != 8:
            assert time.monotonic() < deadline, b.alloc.free_pages
            time.sleep(0.02)
        # Slot is reusable with the real path restored.
        bmod.admit_group = real
        out = b.submit(
            GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=3)
        ).result(timeout=60)
        assert len(out) == 3
    finally:
        bmod.admit_group = real
        b.stop()


def test_degenerate_pool_config_fails_fast():
    """A pool that can't hold one request must raise at construction, not
    hang every request (review finding)."""
    from pilottai_tpu.engine.batcher import ContinuousBatcher

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    with pytest.raises(ValueError, match="can't hold a single request"):
        ContinuousBatcher(cfg, params, n_slots=1, max_seq_len=2048,
                          cache_dtype=jnp.float32, paged=True,
                          page_size=4096, num_pages=1)


def test_chunked_prefill_matches_monolithic():
    """Chunked-prefill admission (VERDICT r5 #6) must produce byte-
    identical output to a monolithic prefill of the same long prompt —
    segments write the same KV the fused path writes — and must actually
    engage (prefill_segments > 0), with short prompts still completing
    alongside (interleaving path)."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams
    from pilottai_tpu.utils.metrics import global_metrics

    # Long prompt: 300 tokens of varied bytes; chunk 64 → 4 full
    # segments + a final tail.
    long_prompt = "".join(chr(65 + (i * 7) % 26) for i in range(300))
    params = GenerationParams(max_new_tokens=8, temperature=0.0)

    def cfg(prefill_chunk):
        return LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=4,
            engine_max_seq=512, engine_chunk=4, dtype="float32",
            engine_paged_kv=True, engine_page_size=32,
            engine_prefix_cache=0,  # isolate: no cross-run page sharing
            engine_prefill_chunk=prefill_chunk,
        )

    async def run(prefill_chunk, with_short=False):
        h = LLMHandler(cfg(prefill_chunk))
        try:
            if with_short:
                outs = await asyncio.gather(
                    h.apredict(long_prompt, params=params),
                    h.apredict("short prompt one", params=params),
                    h.apredict("short prompt two", params=params),
                )
                return outs
            return [await h.apredict(long_prompt, params=params)]
        finally:
            await h.stop()

    mono = asyncio.run(run(0))[0]
    seg0 = global_metrics.get("engine.prefill_segments")
    outs = asyncio.run(run(64, with_short=True))
    assert global_metrics.get("engine.prefill_segments") - seg0 >= 4
    assert outs[0] == mono
    assert all(isinstance(o, str) for o in outs)


def test_chain_tail_prefill_lazy_matches_stacked(monkeypatch):
    """The per-layer lazy prefix gather (large chains, where stacking all
    layers' panels OOMs an 8B model at 8K) must produce the same output
    as the stacked path."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import GenerationParams

    long_prompt = "".join(chr(65 + (i * 11) % 26) for i in range(300))
    params = GenerationParams(max_new_tokens=8, temperature=0.0)

    async def run():
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=512, engine_chunk=4, dtype="float32",
            engine_paged_kv=True, engine_page_size=32,
            engine_prefix_cache=0, engine_prefill_chunk=64,
        ))
        try:
            return await h.apredict(long_prompt, params=params)
        finally:
            await h.stop()

    jax.clear_caches()
    stacked = asyncio.run(run())
    # Force every chain through the lazy path; clear caches so the
    # budget branch (read at trace time) re-evaluates.
    monkeypatch.setenv("PILOTTAI_GATHER_BUDGET", "1")
    jax.clear_caches()
    lazy = asyncio.run(run())
    assert lazy == stacked
