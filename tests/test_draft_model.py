"""Adaptive draft-model speculation (shallow-layer self-drafting).

VERDICT r3 next-step 3: n-gram drafting collapses to ~1 token/pass on
novel text. The draft-model path runs the target's own first N layers +
unembed as the drafter (engine/decode.py:_model_drafts). The safety
invariant is the same as all speculation here: draft SOURCE can never
change output — acceptance compares the target's own masked greedy rows
against the proposal — so every test pins bit-parity with the plain
chunk while the drafts come from the model.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.decode import (
    decode_chunk,
    decode_chunk_spec,
)
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from tests.test_speculative import PROMPTS, _admit, _collect


@pytest.mark.parametrize("model", ["llama-tiny", "gemma-tiny"])
def test_model_draft_greedy_parity(model):
    """draft_mode=ON for every slot: the stream must still be
    bit-identical to the plain chunk (gemma covers the sliding-window +
    softcap branches of the draft's three-source attention)."""
    cfg = get_model_config(model)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    budgets = [25, 25, 25]

    c1, d1, s1, _, f1 = _admit(cfg, params, PROMPTS, budgets)
    plain = [[] for _ in range(4)]
    for _ in range(4):
        t, v, c1, d1, s1 = decode_chunk(
            params, cfg, c1, d1, s1, 8, use_pallas=False
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            plain[b].extend(seq)

    c2, d2, s2, h2, f2 = _admit(cfg, params, PROMPTS, budgets)
    np.testing.assert_array_equal(f1, f2)
    spec = [[] for _ in range(4)]
    for _ in range(4):
        t, v, c2, d2, s2, h2 = decode_chunk_spec(
            params, cfg, c2, d2, s2, h2, 8, 4,
            draft_layers=2, draft_mode=jnp.ones((4,), bool),
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            spec[b].extend(seq)

    for b in range(3):
        assert spec[b] == plain[b], f"slot {b} diverged under model drafts"
    np.testing.assert_array_equal(
        np.asarray(c1.lengths), np.asarray(c2.lengths)
    )


def test_model_draft_mixed_mode_parity():
    """Half the slots draft via the model, half via the n-gram — output
    must still match the plain chunk slot for slot."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    budgets = [20, 20, 20]

    c1, d1, s1, _, _ = _admit(cfg, params, PROMPTS, budgets)
    plain = [[] for _ in range(4)]
    for _ in range(3):
        t, v, c1, d1, s1 = decode_chunk(
            params, cfg, c1, d1, s1, 8, use_pallas=False
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            plain[b].extend(seq)

    c2, d2, s2, h2, _ = _admit(cfg, params, PROMPTS, budgets)
    mode = jnp.asarray([True, False, True, False])
    spec = [[] for _ in range(4)]
    for _ in range(3):
        t, v, c2, d2, s2, h2 = decode_chunk_spec(
            params, cfg, c2, d2, s2, h2, 8, 4,
            draft_layers=2, draft_mode=mode,
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            spec[b].extend(seq)
    for b in range(3):
        assert spec[b] == plain[b], f"slot {b} diverged in mixed mode"


def test_model_drafts_accept_on_shallow_agreement():
    """A 2-layer draft of a 2-layer model IS the model (minus nothing):
    drafts must be exact and acceptance full — the mechanism's upper
    bound works. Uses a truncated-depth config so draft == target."""
    cfg = get_model_config("llama-tiny")
    assert cfg.n_layers >= 2
    params = init_params(cfg, jax.random.PRNGKey(1), dtype=jnp.float32)
    c, d, s, h, _ = _admit(cfg, params, [[7, 8, 9, 10, 11]], [40], n_slots=2)
    emitted = 0
    blocks = 0
    for _ in range(4):
        t, v, c, d, s, h = decode_chunk_spec(
            params, cfg, c, d, s, h, 4, 4,
            draft_layers=cfg.n_layers,  # full-depth draft == the target
            draft_mode=jnp.asarray([True, True]),
        )
        vv = np.asarray(v)[:, 0]
        emitted += int(vv.sum())
        blocks += int(np.asarray(v).reshape(4, 4, 2)[:, :, 0].any(axis=1).sum())
    # Full-depth drafts are exact: every non-terminal block accepts all
    # D-1 drafts + bonus.
    assert emitted / max(blocks, 1) >= 3.5, (emitted, blocks)


@pytest.mark.asyncio
async def test_engine_draft_layers_e2e_parity():
    """Full engine with engine_draft_layers on: byte-identical output to
    the plain engine, whatever the adaptive mode did internally."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    async def run(draft_layers):
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=128, engine_chunk=4, dtype="float32",
            engine_speculate=4, engine_draft_layers=draft_layers,
        ))
        await h.start()
        try:
            outs = []
            for prompt in ("abc abc abc", "novel one-off text xyz"):
                r = await h.generate_response(
                    [ChatMessage(content=prompt)],
                    params=GenerationParams(max_new_tokens=14,
                                            temperature=0.0),
                )
                outs.append(r.content)
            return outs
        finally:
            await h.stop()

    plain = await run(0)
    drafted = await run(2)
    assert drafted == plain
