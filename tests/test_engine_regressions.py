"""Regression tests for code-review findings in the engine layer."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import ChatMessage, GenerationParams
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_prefill
from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
from pilottai_tpu.parallel.sharding import shard_params


def _tiny_batcher(max_seq=64, n_slots=2, **kw):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    return ContinuousBatcher(cfg, params, n_slots=n_slots, max_seq_len=max_seq,
                             cache_dtype=jnp.float32, **kw), cfg


def test_submit_truncation_never_noop():
    # max_new_tokens >= max_seq_len - 1 used to produce a -0 slice that kept
    # the whole oversized prompt and crashed the device thread.
    batcher, _ = _tiny_batcher(max_seq=64)
    req = GenRequest(prompt_ids=list(range(3, 203)), max_new_tokens=63)
    batcher.submit(req)
    assert len(req.prompt_ids) <= 62
    req2 = GenRequest(prompt_ids=list(range(3, 203)), max_new_tokens=1000)
    batcher.submit(req2)
    assert 1 <= len(req2.prompt_ids) <= 62


def test_prefill_failure_fails_future_not_thread():
    batcher, cfg = _tiny_batcher()
    # Force failure via a monkeypatched admission prefill raising: the
    # affected requests' futures must fail, the device thread must not.
    def boom(*a, **k):
        raise RuntimeError("prefill exploded")

    batcher._dispatch_prefill = boom  # type: ignore[assignment]
    batcher.start()
    try:
        req = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=4)
        fut = batcher.submit(req)
        with pytest.raises(RuntimeError, match="prefill exploded"):
            fut.result(timeout=10)
        # Thread must survive and process the next (also failing) request.
        req2 = GenRequest(prompt_ids=[1], max_new_tokens=2)
        fut2 = batcher.submit(req2)
        with pytest.raises(RuntimeError):
            fut2.result(timeout=10)
        assert batcher._thread.is_alive()
    finally:
        batcher.stop()


def test_single_token_request_completes():
    # max_new_tokens=1 has zero decode budget, so no chunk is ever
    # dispatched for it: the prefill-sampled first token must still reach
    # the future via the idle-path drain (review finding: these hung).
    batcher, _ = _tiny_batcher(max_seq=64, n_slots=2)
    batcher.start()
    try:
        req = GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=1)
        out = batcher.submit(req).result(timeout=60)
        assert len(out) <= 1
    finally:
        batcher.stop()


def test_short_generation_one_chunk_boundary():
    # max_new just past one chunk (review finding: a first-token drain on
    # the device thread could race the reader and drop a chunk's tokens,
    # hanging the request). Folding is now serialized on the reader.
    batcher, _ = _tiny_batcher(max_seq=64, n_slots=2)
    batcher.chunk_size = 8
    batcher.start()
    try:
        for _ in range(3):
            req = GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=9)
            out = batcher.submit(req).result(timeout=60)
            assert len(out) <= 9
    finally:
        batcher.stop()


def test_empty_prompt_completes():
    # Review finding: an empty prompt looked like an admission padding row
    # and hung forever; it now decodes from a pad token.
    batcher, _ = _tiny_batcher(max_seq=64, n_slots=2)
    batcher.start()
    try:
        out = batcher.submit(
            GenRequest(prompt_ids=[], max_new_tokens=4)
        ).result(timeout=60)
        assert 1 <= len(out) <= 4
    finally:
        batcher.stop()


def test_cancelled_request_frees_slot():
    batcher, _ = _tiny_batcher(max_seq=64, n_slots=1)
    batcher.start()
    try:
        long_req = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=60)
        batcher.submit(long_req)
        import time
        time.sleep(0.2)
        long_req.cancelled = True
        # The single slot must free up for the next request.
        short = GenRequest(prompt_ids=[4, 5], max_new_tokens=2)
        fut = batcher.submit(short)
        out = fut.result(timeout=60)
        assert isinstance(out, list)
    finally:
        batcher.stop()


def test_first_token_sampling_honors_top_p():
    # The prefill-sampled first token goes through the same device sampler
    # as every later token (one sampling implementation — the host-side
    # duplicate was a review finding). p0 ~ 0.87, top_p=0.5 => always 0.
    from pilottai_tpu.engine.decode import sample_prefill_tokens
    from pilottai_tpu.engine.sampling import SamplingState, admit_sampling

    logits = jnp.asarray([[[4.0, 2.0, 0.0, -1.0]]], jnp.float32)  # [1, 1, V]
    valid = jnp.asarray([1], jnp.int32)
    slots = jnp.asarray([0], jnp.int32)
    picks = set()
    for seed in range(30):
        sampling = SamplingState.create(1)
        sampling = admit_sampling(
            sampling, slots, jnp.asarray([1.0]), jnp.asarray([0], jnp.int32),
            jnp.asarray([0.5]), jnp.asarray([seed], jnp.int32),
            jnp.asarray([-1], jnp.int32), jnp.asarray([False]),
        )
        tok, _ = sample_prefill_tokens(logits, valid, slots, sampling)
        picks.add(int(tok[0]))
    assert picks == {0}


@pytest.mark.asyncio
async def test_concurrent_start_single_batcher():
    from pilottai_tpu.engine.native import NativeEngine

    import threading

    engine = NativeEngine(
        LLMConfig(model_name="llama-tiny", provider="cpu", engine_max_seq=128),
        platform="cpu",
    )
    # Count only threads this test creates — a prior test's device loop may
    # still be winding down (stop() joins, but daemon threads can linger).
    before = {
        t for t in threading.enumerate() if t.name == "pilottai-device-loop"
    }
    try:
        await asyncio.gather(engine.start(), engine.start(), engine.start())
        assert engine.batcher is not None
        after = {
            t for t in threading.enumerate()
            if t.name == "pilottai-device-loop"
        }
        assert len(after - before) == 1
    finally:
        await engine.stop()


def test_prefill_mask_uses_absolute_positions():
    # Prefill at a nonzero offset: token i may only attend j with pos_j <=
    # pos_i. With the old arange-based mask this is indistinguishable; with
    # *decreasing* positions the two disagree.
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    tokens = jnp.asarray([[5, 6, 7, 8]])
    inc = jnp.asarray([[0, 1, 2, 3]])
    dec = jnp.asarray([[3, 2, 1, 0]])
    valid = jnp.asarray([4])
    logits_inc, _, _ = forward_prefill(params, cfg, tokens, inc, valid)
    logits_dec, _, _ = forward_prefill(params, cfg, tokens, dec, valid)
    # Row 0 under decreasing positions attends everything (pos 3 is max);
    # under increasing positions it attends only itself → logits differ.
    assert not np.allclose(np.asarray(logits_inc[0, 0]), np.asarray(logits_dec[0, 0]))


def test_shard_params_accepts_bare_none_leaf():
    mesh = create_mesh(MeshConfig(data=2, model=4))
    params = {"w": jnp.ones((8, 8)), "b": jnp.ones((8,))}
    logical = {"w": ("embed", "mlp"), "b": None}  # bare None = replicated
    placed = shard_params(params, logical, mesh)
    assert placed["b"].sharding.is_fully_replicated


def test_donated_admit_failure_rebuilds_state():
    """admit_group donates cache/dstate/sampling; a dispatch failure that
    consumed them must not leave the engine pointing at deleted buffers —
    in-flight work fails loudly, state is rebuilt, and the engine serves
    the next request (code-review finding, round 2). Recovery is OFF
    here so the ORIGINAL failure surfaces after one attempt and the
    rebuild machinery is tested surgically (recovery's own contract
    lives in tests/test_chaos.py)."""
    import pilottai_tpu.engine.batcher as bmod

    batcher, cfg = _tiny_batcher(recovery_max_attempts=0)
    real_admit = bmod.admit_group

    def poison(params, cfg_, cache, dstate, sampling, *a, **k):
        # Simulate the donated buffers being consumed before the failure.
        for k_, v_ in cache.layers:
            k_.delete()
            v_.delete()
        cache.lengths.delete()
        raise RuntimeError("tunnel dropped mid-dispatch")

    bmod.admit_group = poison
    try:
        batcher.start()
        req = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=4)
        fut = batcher.submit(req)
        with pytest.raises(RuntimeError, match="tunnel dropped"):
            fut.result(timeout=30)
        # State was rebuilt with live buffers.
        import time as _time

        deadline = _time.monotonic() + 10
        while batcher.cache.lengths.is_deleted():
            assert _time.monotonic() < deadline
        # With the real admission path back, the engine still serves.
        bmod.admit_group = real_admit
        req2 = GenRequest(prompt_ids=[1, 2, 3], max_new_tokens=3)
        out = batcher.submit(req2).result(timeout=60)
        assert len(out) == 3
    finally:
        bmod.admit_group = real_admit
        batcher.stop()


@pytest.mark.asyncio
async def test_stop_after_lazy_start_kills_device_threads():
    """generate() starts the backend lazily without flipping the handler's
    _started flag; stop() must still stop the backend, or live device
    threads outlast the handler and crash the process at exit (verify
    finding, round 2)."""
    import threading

    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=2,
        engine_max_seq=64, engine_chunk=4, dtype="float32",
    ))
    before = {
        t for t in threading.enumerate() if t.name == "pilottai-device-loop"
    }
    # No explicit start(): the engine boots inside the first generate.
    await h.apredict("hello", params=GenerationParams(max_new_tokens=3))
    await h.stop()
    after = {
        t for t in threading.enumerate()
        if t.name == "pilottai-device-loop" and t.is_alive() and t not in before
    }
    assert not after, f"device threads leaked past stop(): {after}"
