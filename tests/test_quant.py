"""Weight-only int8 serving quantization (models/quant.py)."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.quant import (
    QTensor,
    dequant,
    quantize_array,
    quantize_params,
)
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_prefill


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(4, 64, 128)) * 0.02, jnp.float32)
    qt = quantize_array(w, dtype=jnp.float32)
    assert qt.q.dtype == jnp.int8 and qt.s.shape == (4, 1, 128)
    back = dequant(qt)
    # Symmetric 8-bit per-channel: worst-case error is scale/2 = amax/254.
    amax = np.abs(np.asarray(w)).max(axis=1, keepdims=True)
    bound = np.broadcast_to(amax / 254 + 1e-8, w.shape)
    np.testing.assert_array_less(np.abs(np.asarray(back) - np.asarray(w)), bound)


def test_quantize_params_selects_matmul_weights():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params, dtype=jnp.float32)
    lp = qp["layers"]
    assert isinstance(lp["attn"]["wq"], QTensor)
    assert isinstance(lp["mlp"]["wd"], QTensor)
    # Norm scales and embeds stay dense.
    assert not isinstance(lp["ln1"]["scale"], QTensor)
    assert not isinstance(qp["embed"], QTensor)


def test_quantized_forward_close_to_dense():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params, dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(2, cfg.vocab_size, (2, 16)), jnp.int32
    )
    pos = jnp.broadcast_to(jnp.arange(16)[None], (2, 16)).astype(jnp.int32)
    valid = jnp.full((2,), 16, jnp.int32)
    ld, _, _ = forward_prefill(params, cfg, tokens, pos, valid, use_flash=False)
    lq, _, _ = forward_prefill(qp, cfg, tokens, pos, valid, use_flash=False)
    ld, lq = np.asarray(ld), np.asarray(lq)
    # 8-bit weight error perturbs logits slightly; correlation stays high
    # and the greedy next token rarely flips on random weights.
    corr = np.corrcoef(ld.ravel(), lq.ravel())[0, 1]
    assert corr > 0.999, corr
    agree = (ld.argmax(-1) == lq.argmax(-1)).mean()
    assert agree > 0.9, agree


def test_moe_quantized_forward_runs():
    cfg = get_model_config("moe-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    qp = quantize_params(params, dtype=jnp.float32)
    # Router must stay dense: expert SELECTION should not be perturbed.
    assert not isinstance(qp["layers"]["moe"]["router"], QTensor)
    assert isinstance(qp["layers"]["moe"]["wg"], QTensor)
    tokens = jnp.ones((2, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (2, 8)).astype(jnp.int32)
    valid = jnp.full((2,), 8, jnp.int32)
    lq, _, _ = forward_prefill(qp, cfg, tokens, pos, valid, use_flash=False)
    assert not bool(jnp.isnan(lq).any())


def test_engine_serves_int8():
    async def main():
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=64, engine_chunk=4, dtype="float32",
            quantize="int8",
        ))
        out = await h.apredict(
            "hello world", params=GenerationParams(max_new_tokens=6)
        )
        await h.stop()
        return out

    out = asyncio.run(main())
    assert isinstance(out, str) and len(out) > 0


def test_init_params_direct_int8():
    """init_params(quantize=True) emits QTensor matmul weights directly
    (per-layer-slice generation — the path that lets llama3-8b random-init
    fit one 16 GB chip), and quantize_params passes them through
    untouched instead of double-quantizing."""
    cfg = get_model_config("llama-tiny")
    qp = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32,
                     quantize=True)
    lp = qp["layers"]
    assert isinstance(lp["attn"]["wq"], QTensor)
    assert isinstance(lp["mlp"]["wd"], QTensor)
    assert lp["attn"]["wq"].q.shape == (cfg.n_layers, cfg.hidden_size, cfg.q_dim)
    assert lp["attn"]["wq"].s.shape == (cfg.n_layers, 1, cfg.q_dim)
    assert not isinstance(lp["ln1"]["scale"], QTensor)
    again = quantize_params(qp, dtype=jnp.float32)
    assert isinstance(again["layers"]["attn"]["wq"], QTensor)
    assert not isinstance(again["layers"]["attn"]["wq"].q, QTensor)

    tokens = jnp.ones((1, 8), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(8)[None], (1, 8)).astype(jnp.int32)
    lq, _, _ = forward_prefill(qp, cfg, tokens, pos,
                               jnp.full((1,), 8, jnp.int32), use_flash=False)
    assert not bool(jnp.isnan(lq).any())
