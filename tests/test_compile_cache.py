"""Persistent compilation cache: warm restarts reuse compiled programs.

VERDICT r3 weak #4: every process start recompiled the whole engine
(141.7 s on the chip), so FaultTolerance's respawn story cost minutes of
dead time. The restart path must now provably hit the on-disk cache —
asserted via the hit counter, not wall-clock (CI machines are noisy).
"""

import json
import os
import subprocess
import sys

import pytest

from pilottai_tpu.utils.compile_cache import (
    cache_hits,
    enable_compilation_cache,
)

_BOOT = r"""
import asyncio, json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.utils.compile_cache import cache_hits

async def main():
    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=2,
        engine_max_seq=128, engine_chunk=4, dtype="float32",
        engine_compile_cache=sys.argv[1],
    ))
    t0 = time.perf_counter()
    await h.start()
    up = time.perf_counter() - t0
    out = await h.apredict(
        "hello", params=GenerationParams(max_new_tokens=4, temperature=0.0)
    )
    await h.stop()
    print(json.dumps({"up": up, "hits": cache_hits(), "ok": len(out) >= 0}))

asyncio.run(main())
"""


def _boot(cache_dir: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)  # single-device process, like a respawn
    out = subprocess.run(
        [sys.executable, "-c", _BOOT, cache_dir],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_respawned_engine_reuses_cache(tmp_path):
    """Process 1 populates the cache; process 2 — the FaultTolerance
    respawn / worker-redeploy shape — must record persistent-cache hits
    while producing a working engine."""
    cache = str(tmp_path / "xla-cache")
    cold = _boot(cache)
    assert cold["ok"]
    assert os.listdir(cache), "first boot persisted nothing"
    warm = _boot(cache)
    assert warm["ok"]
    assert warm["hits"] > 0, (
        f"respawned engine recompiled everything (cold {cold}, warm {warm})"
    )


def test_adaptive_chunk_buckets_bound_decode_executables():
    """Compile-cache tripwire for adaptive chunk scheduling: the bucket
    ladder is the ONLY degree of freedom the scheduler has, so a config
    with one prefix-bound rung must compile at most len(chunk_buckets)
    decode executables no matter how budgets vary — an unquantized pick
    (or a bucket set that grows with traffic) would thrash the compile
    cache with one executable per distinct length."""
    import jax
    import jax.numpy as jnp

    from pilottai_tpu.engine import decode
    from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
    from pilottai_tpu.models.common import init_params
    from pilottai_tpu.models.registry import get_model_config

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    # max_seq 64 keeps _decode_bucket on a single rung, so the only
    # static-axis variation left is the chunk bucket itself.
    batcher = ContinuousBatcher(
        cfg, params, n_slots=2, max_seq_len=64, cache_dtype=jnp.float32,
        chunk_size=8, chunk_policy="adaptive", chunk_buckets=(2, 4, 8),
        prefix_cache=0, use_pallas=False,
    )
    decode.decode_chunk._clear_cache()
    batcher.start()
    try:
        # Warmup's compile sweep covers every bucket...
        batcher.warmup(prompt_lens=(8,))
        after_warmup = decode.decode_chunk._cache_size()
        # ...and varied serve-time budgets may only ever re-hit them.
        for mnt in (2, 3, 5, 7, 9, 12, 17):
            req = GenRequest(
                prompt_ids=list(range(3, 3 + (mnt % 5) + 2)),
                max_new_tokens=mnt,
            )
            batcher.submit(req).result(timeout=120)
    finally:
        batcher.stop()
    n_exec = decode.decode_chunk._cache_size()
    assert after_warmup == len(batcher.chunk_buckets), (
        f"warmup compiled {after_warmup} decode executables, expected "
        f"one per bucket {batcher.chunk_buckets}"
    )
    assert n_exec <= len(batcher.chunk_buckets), (
        f"{n_exec} decode executables for bucket set "
        f"{batcher.chunk_buckets}: adaptive chunking is leaking compiles"
    )


def test_enable_is_idempotent_and_off_disables(tmp_path):
    import jax

    import pilottai_tpu.utils.compile_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_enabled = cc._enabled_dir
    d = str(tmp_path / "cc")
    try:
        assert enable_compilation_cache("off") is None
        p1 = enable_compilation_cache(d)
        p2 = enable_compilation_cache(d)
        assert p1 == p2 == d
        assert isinstance(cache_hits(), int)
    finally:
        # This process runs the rest of the suite: don't leave the cache
        # pointed at a tmp dir pytest is about to delete.
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        cc._enabled_dir = prev_enabled
