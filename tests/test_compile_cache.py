"""Persistent compilation cache: warm restarts reuse compiled programs.

VERDICT r3 weak #4: every process start recompiled the whole engine
(141.7 s on the chip), so FaultTolerance's respawn story cost minutes of
dead time. The restart path must now provably hit the on-disk cache —
asserted via the hit counter, not wall-clock (CI machines are noisy).
"""

import json
import os
import subprocess
import sys

import pytest

from pilottai_tpu.utils.compile_cache import (
    cache_hits,
    enable_compilation_cache,
)

_BOOT = r"""
import asyncio, json, os, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import GenerationParams
from pilottai_tpu.utils.compile_cache import cache_hits

async def main():
    h = LLMHandler(LLMConfig(
        model_name="llama-tiny", provider="cpu", engine_slots=2,
        engine_max_seq=128, engine_chunk=4, dtype="float32",
        engine_compile_cache=sys.argv[1],
    ))
    t0 = time.perf_counter()
    await h.start()
    up = time.perf_counter() - t0
    out = await h.apredict(
        "hello", params=GenerationParams(max_new_tokens=4, temperature=0.0)
    )
    await h.stop()
    print(json.dumps({"up": up, "hits": cache_hits(), "ok": len(out) >= 0}))

asyncio.run(main())
"""


def _boot(cache_dir: str) -> dict:
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    env.pop("XLA_FLAGS", None)  # single-device process, like a respawn
    out = subprocess.run(
        [sys.executable, "-c", _BOOT, cache_dir],
        capture_output=True, text=True, timeout=600, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


def test_respawned_engine_reuses_cache(tmp_path):
    """Process 1 populates the cache; process 2 — the FaultTolerance
    respawn / worker-redeploy shape — must record persistent-cache hits
    while producing a working engine."""
    cache = str(tmp_path / "xla-cache")
    cold = _boot(cache)
    assert cold["ok"]
    assert os.listdir(cache), "first boot persisted nothing"
    warm = _boot(cache)
    assert warm["ok"]
    assert warm["hits"] > 0, (
        f"respawned engine recompiled everything (cold {cold}, warm {warm})"
    )


def test_enable_is_idempotent_and_off_disables(tmp_path):
    import jax

    import pilottai_tpu.utils.compile_cache as cc

    prev_dir = jax.config.jax_compilation_cache_dir
    prev_enabled = cc._enabled_dir
    d = str(tmp_path / "cc")
    try:
        assert enable_compilation_cache("off") is None
        p1 = enable_compilation_cache(d)
        p2 = enable_compilation_cache(d)
        assert p1 == p2 == d
        assert isinstance(cache_hits(), int)
    finally:
        # This process runs the rest of the suite: don't leave the cache
        # pointed at a tmp dir pytest is about to delete.
        jax.config.update("jax_compilation_cache_dir", prev_dir)
        cc._enabled_dir = prev_enabled
