"""Structured function calling on the native engine path (VERDICT r1 #5).

The reference formats OpenAI-style tools and returns ``tool_calls``
(``pilott/engine/llm.py:91-104``, consumed at ``core/agent.py:331-338``).
Here the contract is tested against the REAL NativeEngine pipeline
(tokenize -> batcher -> detokenize -> parse) with a scripted fake batcher
standing in for the model compute, so the assertions are deterministic.
"""

import asyncio
import json
from concurrent.futures import Future

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import AgentConfig, LLMConfig
from pilottai_tpu.core.task import Task
from pilottai_tpu.engine.base import parse_tool_calls
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.native import NativeEngine
from pilottai_tpu.engine.types import ChatMessage, ToolSpec
from pilottai_tpu.tools.tool import Tool


def test_parse_tool_calls_wire_forms():
    calls = parse_tool_calls(
        '{"tool_call": {"name": "search", "arguments": {"q": "tpu"}}}',
        ["search", "fetch"],
    )
    assert len(calls) == 1 and calls[0].name == "search"
    assert calls[0].arguments == {"q": "tpu"}

    calls = parse_tool_calls(
        '{"action": "fetch", "arguments": {"url": "x"}, "task_complete": false}',
        ["search", "fetch"],
    )
    assert len(calls) == 1 and calls[0].name == "fetch"

    # An action that is not an offered tool is NOT a tool call.
    assert parse_tool_calls('{"action": "respond"}', ["search"]) == []
    assert parse_tool_calls("not json at all", ["search"]) == []


def test_parse_tool_calls_malformed_wire_data_degrades():
    # LLM output is untrusted: bad shapes must yield [] (or argument-less
    # calls), never raise into generate() (review finding).
    assert parse_tool_calls('{"tool_call": {"name": 7}}', ["t"]) == []
    assert parse_tool_calls('{"tool_call": "search"}', ["search"]) == []
    assert parse_tool_calls('{"tool_call": {"arguments": {}}}', ["t"]) == []
    calls = parse_tool_calls(
        '{"tool_call": {"name": "t", "arguments": "q=x"}}', ["t"]
    )
    assert len(calls) == 1 and calls[0].arguments == {}
    calls = parse_tool_calls('{"action": "t", "arguments": [1, 2]}', ["t"])
    assert len(calls) == 1 and calls[0].arguments == {}


class _ScriptedBatcher:
    """Stands in for ContinuousBatcher: resolves each request with the
    next scripted reply's bytes. Everything around it (prompt rendering,
    tokenization, tool_call parsing) is the real native path."""

    def __init__(self, replies):
        self.replies = list(replies)
        self.prompts = []

    def submit(self, request):
        self.prompts.append(bytes(
            i for i in request.prompt_ids if 0 <= i < 256
        ).decode("utf-8", "replace"))
        fut: Future = Future()
        fut.set_result(list(self.replies.pop(0).encode("utf-8")))
        return fut

    def stop(self) -> None:
        pass

    def get_metrics(self):
        return {}


def _engine(replies) -> NativeEngine:
    engine = NativeEngine(
        LLMConfig(model_name="llama-tiny", provider="cpu"), platform="cpu"
    )
    engine.batcher = _ScriptedBatcher(replies)  # skip device bring-up
    return engine


@pytest.mark.asyncio
async def test_native_engine_emits_tool_calls():
    engine = _engine(
        ['{"tool_call": {"name": "lookup", "arguments": {"key": "a"}}}']
    )
    resp = await engine.generate(
        [ChatMessage(role="user", content="find a")],
        tools=[ToolSpec(name="lookup", description="kv lookup")],
    )
    assert [tc.name for tc in resp.tool_calls] == ["lookup"]
    assert resp.tool_calls[0].arguments == {"key": "a"}
    # The tool inventory and invocation convention reach the prompt.
    assert "lookup" in engine.batcher.prompts[0]
    assert "tool_call" in engine.batcher.prompts[0]


@pytest.mark.asyncio
async def test_native_engine_no_tools_no_tool_calls():
    engine = _engine(['{"tool_call": {"name": "lookup", "arguments": {}}}'])
    resp = await engine.generate([ChatMessage(role="user", content="hi")])
    assert resp.tool_calls == []


@pytest.mark.asyncio
async def test_agent_step_loop_executes_native_tool_call():
    """Full agent plan/act loop over the native path: a tool_call reply
    must actually run the tool (reference ``core/agent.py:331-338``)."""
    seen = {}

    def lookup(key: str) -> str:
        seen["key"] = key
        return f"value-of-{key}"

    engine = _engine([
        json.dumps({"understanding": "u", "approach": "a",
                    "estimated_steps": 1, "risks": []}),
        json.dumps({"selected_tools": ["lookup"], "reasoning": "need it"}),
        # Step 1 answers with the function-calling wire form only — no
        # "action" key — so the step MUST come from response.tool_calls.
        json.dumps({"tool_call": {"name": "lookup",
                                  "arguments": {"key": "alpha"}},
                    "task_complete": False}),
        json.dumps({"task_complete": True, "action": "respond",
                    "arguments": {}, "reasoning": "done"}),
        json.dumps({"success": True, "quality": 0.9, "issues": [],
                    "suggestions": []}),
    ])
    agent = BaseAgent(
        config=AgentConfig(role="worker", max_iterations=4),
        llm=LLMHandler(LLMConfig(provider="cpu"), backend=engine),
        tools=[Tool(name="lookup", function=lookup,
                    description="kv lookup",
                    parameters={"properties": {"key": {"type": "string"}}})],
    )
    await agent.start()
    try:
        result = await agent.execute_task(Task(description="look up alpha"))
        assert result.success
        assert seen == {"key": "alpha"}
        assert result.metadata["steps"][0]["action"] == "lookup"
        assert result.metadata["steps"][0]["result"] == "value-of-alpha"
    finally:
        await agent.stop()


def test_parse_tool_calls_unhashable_action():
    # {"action": [...]} raised TypeError through generate() (review finding).
    assert parse_tool_calls('{"action": ["lookup"]}', ["lookup"]) == []
    assert parse_tool_calls('{"action": {"n": 1}}', ["lookup"]) == []
