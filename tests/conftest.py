"""Test harness: force JAX onto a virtual 8-device CPU mesh.

SURVEY.md §4: the build's test strategy is (1) deterministic mock-LLM
fixtures, (2) a CPU-jax path so the whole stack runs in CI without TPUs,
(3) multi-device simulation via ``xla_force_host_platform_device_count``.
Environment variables must be set before jax is first imported, hence the
module-level os.environ writes here.
"""

import os

# Force, don't setdefault: the environment may pin JAX_PLATFORMS to a real
# accelerator platform, and tests must be hermetic (and must not hang if
# the accelerator tunnel is unavailable).
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# Belt and braces: the env var alone can be overridden by site-injected
# accelerator plugins; the config flag is authoritative.
jax.config.update("jax_platforms", "cpu")

import asyncio  # noqa: E402
import inspect  # noqa: E402

import pytest  # noqa: E402

# The suite compiles hundreds of XLA:CPU executables in one process; each
# holds mmap'd JIT code pages that are never unmapped while the jit cache
# holds the program. Measured: the process crosses vm.max_map_count
# (65530 default) around 350 tests and LLVM SEGFAULTS on the failed mmap
# mid-compile. Two defenses: raise the limit when we can (CI images run
# as root), and drop compiled programs between test modules — modules
# rarely share shapes, so the recompile cost is small and map growth
# stays bounded.
try:  # best-effort; harmless without privileges
    with open("/proc/sys/vm/max_map_count", "r+") as f:
        if int(f.read()) < 1_048_576:
            f.seek(0)
            f.write("1048576")
except OSError:
    pass


@pytest.fixture(autouse=True, scope="module")
def _clear_jax_caches_per_module():
    yield
    jax.clear_caches()

# pytest-asyncio is not available in this image; provide a minimal strict-mode
# equivalent: coroutine tests marked ``@pytest.mark.asyncio`` run under
# ``asyncio.run`` on a fresh event loop per test.


def pytest_configure(config):
    config.addinivalue_line("markers", "asyncio: run coroutine test on an event loop")


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem):
    test_fn = pyfuncitem.obj
    if inspect.iscoroutinefunction(test_fn):
        sig_names = set(inspect.signature(test_fn).parameters)
        kwargs = {k: v for k, v in pyfuncitem.funcargs.items() if k in sig_names}
        asyncio.run(test_fn(**kwargs))
        return True
    return None
