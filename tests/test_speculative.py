"""Speculative decoding (n-gram self-drafting verify-blocks).

The invariant that makes speculation safe: acceptance only ever compares
the model's OWN masked greedy output against the draft, so for greedy
slots the emitted token stream is BIT-IDENTICAL to the plain fused chunk
— drafts change speed, never content. These tests pin that, plus budget/
EOS bookkeeping and the json_mode interaction. (VERDICT r2 next-step 2.)
"""

import asyncio
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.engine.decode import (
    DecodeState,
    admit_group,
    decode_chunk,
    decode_chunk_spec,
    pack_admit_meta,
)
from pilottai_tpu.engine.sampling import SamplingState
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config


def _admit(cfg, params, prompts, budgets, temps=None, jsonm=None,
           eos=-1, n_slots=4, max_seq=128):
    from pilottai_tpu.ops.kvcache import KVCache

    A = len(prompts)
    T = max(len(p) for p in prompts)
    T = max(16, 1 << (T - 1).bit_length())
    tokens = np.zeros((A, T), np.int32)
    lens = np.zeros((A,), np.int32)
    for i, p in enumerate(prompts):
        tokens[i, : len(p)] = p
        lens[i] = len(p)
    cache = KVCache.create(
        cfg.n_layers, n_slots, max_seq, cfg.n_kv_heads, cfg.head_dim,
        dtype=jnp.float32,
    )
    history = jnp.zeros((n_slots, max_seq), jnp.int32)
    temps = temps or [0.0] * A
    jsonm = jsonm or [False] * A
    mi, mf = pack_admit_meta(
        A, slots=range(A), temps=temps, seeds=range(A), eos=[eos] * A,
        jsonm=[int(j) for j in jsonm],
        budgets=[b - 1 for b in budgets], lens=lens, pad_slot=n_slots,
    )
    cache, dstate, sampling, first, history = admit_group(
        params, cfg, cache, DecodeState.create(n_slots),
        SamplingState.create(n_slots),
        jnp.asarray(tokens), jnp.asarray(mi), jnp.asarray(mf),
        use_flash=False, history=history,
    )
    return cache, dstate, sampling, history, np.asarray(first)[:A]


def _collect(toks, valid, n_slots):
    out = [[] for _ in range(n_slots)]
    t, v = np.asarray(toks), np.asarray(valid)
    for i in range(t.shape[0]):
        for b in range(n_slots):
            if v[i, b]:
                out[b].append(int(t[i, b]))
    return out


# Prompts with internal repetition so the 2-gram draft actually fires.
PROMPTS = [
    [5, 6, 7, 5, 6, 7, 5, 6],
    [9, 9, 9, 9, 9, 9],
    [3, 4, 3, 4, 3, 4, 3],
]


@pytest.mark.parametrize(
    "model", ["llama-tiny", "gemma-tiny", "moe-tiny"]
)
def test_spec_chunk_greedy_parity(model):
    """decode_chunk_spec emits the same greedy token stream as
    decode_chunk, block by block, including cache lengths — across the
    families (gemma-tiny covers the sliding-window + softcap branches of
    the spec block attention; moe-tiny the expert MLP)."""
    cfg = get_model_config(model)
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    budgets = [25, 25, 25]

    c1, d1, s1, _, f1 = _admit(cfg, params, PROMPTS, budgets)
    plain = [[] for _ in range(4)]
    for _ in range(4):
        t, v, c1, d1, s1 = decode_chunk(
            params, cfg, c1, d1, s1, 8, use_pallas=False
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            plain[b].extend(seq)

    c2, d2, s2, h2, f2 = _admit(cfg, params, PROMPTS, budgets)
    np.testing.assert_array_equal(f1, f2)
    spec = [[] for _ in range(4)]
    for _ in range(4):
        t, v, c2, d2, s2, h2 = decode_chunk_spec(
            params, cfg, c2, d2, s2, h2, 8, 4, prefix_bound=None
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            spec[b].extend(seq)

    for b in range(3):
        assert spec[b] == plain[b], f"slot {b} diverged"
    np.testing.assert_array_equal(
        np.asarray(c1.lengths), np.asarray(c2.lengths)
    )
    # History mirrors prompt + generated per position (all families).
    h = np.asarray(h2)
    for b in range(3):
        gen = [f2[b]] + spec[b]
        want = PROMPTS[b] + gen
        got = list(h[b, : len(want)])
        assert got == want, f"slot {b} history wrong"


def test_spec_respects_budget_exactly():
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    for budget in (2, 3, 5):
        c, d, s, h, _ = _admit(cfg, params, PROMPTS, [budget] * 3)
        total = [0, 0, 0]
        for _ in range(3):
            t, v, c, d, s, h = decode_chunk_spec(
                params, cfg, c, d, s, h, 4, 4
            )
            for b, seq in enumerate(_collect(t, v, 4)[:3]):
                total[b] += len(seq)
        # budget-1 decode tokens (first token came from prefill).
        assert total == [budget - 1] * 3, (budget, total)


def test_spec_acceptance_actually_fires():
    """On self-repeating sequences the 2-gram draft must accept > 0
    tokens — otherwise the whole mechanism silently degrades to 1
    token/pass and the perf claim is vapor. Greedy decode on a tiny
    random-weight model collapses to a cycle, so acceptance must appear
    within a few blocks."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    c, d, s, h, _ = _admit(cfg, params, [[7, 8, 9, 7, 8]], [60], n_slots=2)
    emitted = 0
    blocks = 0
    for _ in range(4):
        t, v, c, d, s, h = decode_chunk_spec(params, cfg, c, d, s, h, 4, 4)
        emitted += int(np.asarray(v)[:, 0].sum())
        blocks += 4
    # A cycling greedy stream must reach well past 1 token/block once the
    # cycle is in history (the frontier-matching bug measured exactly
    # 1.0 here).
    assert emitted >= 1.5 * blocks, (
        f"weak speculative acceptance: {emitted} tokens in {blocks} blocks"
    )


def test_spec_sampled_slots_stay_exact():
    """temperature > 0 slots emit exactly one token per block and the
    stream stays within the vocab (distributional path intact)."""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    c, d, s, h, _ = _admit(
        cfg, params, PROMPTS, [10, 10, 10], temps=[1.0, 0.0, 1.0]
    )
    seqs = [[] for _ in range(4)]
    for _ in range(4):
        t, v, c, d, s, h = decode_chunk_spec(params, cfg, c, d, s, h, 3, 4)
        for b, seq in enumerate(_collect(t, v, 4)):
            seqs[b].extend(seq)
    for b in range(3):
        assert len(seqs[b]) == 9  # budget-1
        assert all(0 <= t < cfg.vocab_size for t in seqs[b])


def test_spec_sampled_slots_bit_identical():
    """Sampled (temperature > 0) slots are BIT-IDENTICAL between the
    plain and speculative chunks, not merely same-distribution: a spec
    block advances the PRNG once and emits one sampled token, so the key
    sequence at emission points equals the plain chunk's
    advance-per-step. (Round-3 docs claimed divergence — wrong.)"""
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    temps = [1.3, 0.7, 2.0]

    c1, d1, s1, _, f1 = _admit(cfg, params, PROMPTS, [15] * 3, temps=temps)
    plain = [[] for _ in range(4)]
    for _ in range(4):
        t, v, c1, d1, s1 = decode_chunk(
            params, cfg, c1, d1, s1, 4, use_pallas=False
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            plain[b].extend(seq)

    c2, d2, s2, h2, f2 = _admit(cfg, params, PROMPTS, [15] * 3, temps=temps)
    np.testing.assert_array_equal(f1, f2)
    spec = [[] for _ in range(4)]
    for _ in range(5):
        t, v, c2, d2, s2, h2 = decode_chunk_spec(
            params, cfg, c2, d2, s2, h2, 4, 4
        )
        for b, seq in enumerate(_collect(t, v, 4)):
            spec[b].extend(seq)
    for b in range(3):
        assert spec[b] == plain[b], f"sampled slot {b} diverged"


@pytest.mark.asyncio
async def test_engine_spec_e2e_parity_and_json():
    """Full engine: engine_speculate=4 produces byte-identical greedy
    output to the plain engine, and json_mode under speculation still
    yields parseable documents."""
    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    async def run(speculate):
        h = LLMHandler(LLMConfig(
            model_name="llama-tiny", provider="cpu", engine_slots=2,
            engine_max_seq=128, engine_chunk=4, dtype="float32",
            engine_speculate=speculate,
        ))
        await h.start()
        try:
            outs = []
            for prompt in ("abc abc abc abc", "xyzzy"):
                r = await h.generate_response(
                    [ChatMessage(role="user", content=prompt)],
                    params=GenerationParams(
                        max_new_tokens=16, temperature=0.0
                    ),
                )
                outs.append(r.content)
            j = await h.generate_response(
                [ChatMessage(role="user", content="emit json")],
                params=GenerationParams(
                    max_new_tokens=60, temperature=1.0, seed=3,
                    json_mode=True,
                ),
            )
            return outs, j.content
        finally:
            await h.stop()

    plain_outs, _ = await run(0)
    spec_outs, spec_json = await run(4)
    assert spec_outs == plain_outs
    doc = json.loads(spec_json)
    assert isinstance(doc, (dict, list))
