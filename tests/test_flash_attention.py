"""Pallas flash attention: parity vs the XLA reference implementation.

Runs in interpreter mode on the CPU test platform (the compiled kernel is
exercised on real TPU by bench.py / the engine). Tolerances are tight:
interpret mode is bit-faithful to the kernel's fp32 online softmax.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.ops.attention import (
    dot_product_attention,
    flash_enabled,
    flash_shapes_ok,
    make_attention_mask,
)
from pilottai_tpu.ops.pallas.flash_attention import flash_attention


def _setup(B=2, T=256, N=4, K=2, H=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, T, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, H)), jnp.float32)
    ps = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)).astype(jnp.int32)
    return q, k, v, ps


def _reference(q, k, v, ps, valid, window, softcap, scale):
    """Oracle via the shipped mask helper (contiguous positions only —
    ``make_attention_mask`` assumes cache slot j holds position j)."""
    mask = make_attention_mask(ps, q.shape[1], valid, window=window)
    return dot_product_attention(
        q, k, v, mask=mask, scale=scale, logit_softcap=softcap
    )


@pytest.mark.parametrize(
    "window,softcap",
    [(0, 0.0), (64, 0.0), (0, 50.0), (64, 30.0)],
)
def test_flash_matches_reference(window, softcap):
    q, k, v, ps = _setup()
    valid = jnp.asarray([256, 180], jnp.int32)
    scale = q.shape[-1] ** -0.5
    ref = _reference(q, k, v, ps, valid, window, softcap, scale)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(window),
        scale=scale, softcap=softcap, interpret=True,
    )
    # Rows past valid hold garbage in both paths; compare live rows only.
    np.testing.assert_allclose(ref[0], got[0], atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(ref[1, :180], got[1, :180], atol=2e-5, rtol=2e-5)


def test_flash_gqa_and_mha():
    for N, K in [(4, 4), (8, 2), (4, 1)]:
        q, k, v, ps = _setup(N=N, K=K, T=128)
        valid = jnp.full((2,), 128, jnp.int32)
        scale = q.shape[-1] ** -0.5
        ref = _reference(q, k, v, ps, valid, 0, 0.0, scale)
        got = flash_attention(
            q, k, v, ps, ps, valid, jnp.int32(0), scale=scale, interpret=True
        )
        np.testing.assert_allclose(ref, got, atol=2e-5, rtol=2e-5)


def test_flash_offset_positions():
    """Prefill at a nonzero offset (continuation): positions start at 100.
    Hand-built mask here — make_attention_mask assumes slot j == position j,
    which doesn't hold at an offset."""
    q, k, v, ps = _setup(T=128)
    ps = ps + 100
    valid = jnp.full((2,), 128, jnp.int32)
    scale = q.shape[-1] ** -0.5
    ipos, jpos = ps[:, :, None], ps[:, None, :]
    mask = (jpos <= ipos) & (
        jnp.arange(128)[None, None, :] < valid[:, None, None]
    )
    ref = dot_product_attention(q, k, v, mask=mask, scale=scale)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(0), scale=scale, interpret=True
    )
    np.testing.assert_allclose(ref, got, atol=2e-5, rtol=2e-5)


def test_flash_fully_masked_rows_are_zero():
    """valid=0 for one batch row -> output rows all zeros, no NaN."""
    q, k, v, ps = _setup(T=128)
    valid = jnp.asarray([128, 0], jnp.int32)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(0),
        scale=q.shape[-1] ** -0.5, interpret=True,
    )
    assert not bool(jnp.isnan(got).any())
    np.testing.assert_array_equal(np.asarray(got[1]), 0.0)


def test_dispatch_gates(monkeypatch):
    monkeypatch.setenv("PILOTTAI_NO_FLASH", "1")
    assert not flash_enabled()  # env kill-switch wins on any platform
    assert flash_shapes_ok(256, 256)
    assert flash_shapes_ok(192, 256)   # ragged T pads internally (round 3)
    assert flash_shapes_ok(64, 64)     # sub-block pads to one block
    assert not flash_shapes_ok(8, 8)   # tiny: pad waste dwarfs the work
    assert flash_shapes_ok(8192, 8192, head_dim=128, itemsize=2)
    assert not flash_shapes_ok(16384, 16384, head_dim=128, itemsize=2)  # VMEM
    # The VMEM bound applies to the PADDED S.
    assert not flash_shapes_ok(16300, 16300, head_dim=128, itemsize=2)


# --------------------------------------------------------------------- #
# Backward pass (custom VJP, Pallas bwd kernels)
# --------------------------------------------------------------------- #

@pytest.mark.parametrize(
    "window,softcap",
    [(0, 0.0), (64, 0.0), (0, 50.0), (64, 30.0)],
)
def test_flash_grad_matches_reference(window, softcap):
    """d(loss)/d(q,k,v) through the Pallas bwd kernels == XLA autodiff of
    the dense reference. Loss sums only valid rows (rows past valid hold
    garbage in both implementations)."""
    q, k, v, ps = _setup(T=256)
    valid = jnp.asarray([256, 180], jnp.int32)
    scale = q.shape[-1] ** -0.5
    T = q.shape[1]
    row_ok = (jnp.arange(T)[None, :] < valid[:, None]).astype(jnp.float32)
    # Non-uniform weights so dO varies per element.
    w = jnp.asarray(
        np.random.default_rng(3).normal(size=q.shape), jnp.float32
    ) * row_ok[:, :, None, None]

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, ps, ps, valid, jnp.int32(window),
            scale=scale, softcap=softcap, interpret=True,
        )
        return jnp.sum(o * w)

    def loss_ref(q, k, v):
        o = _reference(q, k, v, ps, valid, window, softcap, scale)
        return jnp.sum(o * w)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, ref, name in zip(g_flash, g_ref, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(got), atol=3e-4, rtol=3e-4,
            err_msg=name,
        )


def test_flash_grad_gqa():
    q, k, v, ps = _setup(T=128, N=8, K=2)
    valid = jnp.full((2,), 128, jnp.int32)
    scale = q.shape[-1] ** -0.5

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, ps, ps, valid, jnp.int32(0), scale=scale, interpret=True
        )
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_reference(q, k, v, ps, valid, 0, 0.0, scale)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, ref, name in zip(g_flash, g_ref, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(got), atol=3e-4, rtol=3e-4,
            err_msg=name,
        )


# --------------------------------------------------------------------- #
# Multi-chip dispatch (shard_map over the 8-device CPU mesh)
# --------------------------------------------------------------------- #

def _tp_mesh():
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh

    return create_mesh(MeshConfig(data=2, fsdp=2, model=2, seq=1))


def test_flash_sharded_matches_single():
    from pilottai_tpu.ops.pallas.flash_attention import (
        flash_attention_sharded,
        flash_sharding_ok,
    )

    mesh = _tp_mesh()
    q, k, v, ps = _setup(B=4, T=128, N=4, K=2)
    valid = jnp.asarray([128, 90, 50, 128], jnp.int32)
    scale = q.shape[-1] ** -0.5
    assert flash_sharding_ok(mesh, 4, 4, 2)

    ref = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(0), scale=scale, interpret=True
    )
    got = flash_attention_sharded(
        mesh, q, k, v, ps, ps, valid, jnp.int32(0),
        scale=scale, interpret=True,
    )
    np.testing.assert_allclose(
        np.asarray(ref), np.asarray(got), atol=2e-5, rtol=2e-5
    )


def test_flash_sharded_grad():
    """shard_map transposes through the kernel's custom VJP — TP training
    keeps the Pallas path end to end."""
    from pilottai_tpu.ops.pallas.flash_attention import flash_attention_sharded

    mesh = _tp_mesh()
    q, k, v, ps = _setup(B=4, T=128, N=4, K=2)
    valid = jnp.full((4,), 128, jnp.int32)
    scale = q.shape[-1] ** -0.5

    def loss_sharded(q, k, v):
        o = flash_attention_sharded(
            mesh, q, k, v, ps, ps, valid, jnp.int32(0),
            scale=scale, interpret=True,
        )
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(_reference(q, k, v, ps, valid, 0, 0.0, scale)))

    g_s = jax.grad(loss_sharded, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for got, ref, name in zip(g_s, g_r, ("dq", "dk", "dv")):
        np.testing.assert_allclose(
            np.asarray(ref), np.asarray(got), atol=3e-4, rtol=3e-4,
            err_msg=name,
        )

def test_flash_sharding_gates():
    from pilottai_tpu.ops.pallas.flash_attention import flash_sharding_ok
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh

    mesh = _tp_mesh()
    assert flash_sharding_ok(mesh, 8, 8, 2)
    assert not flash_sharding_ok(mesh, 3, 8, 2)    # batch not divisible
    assert not flash_sharding_ok(mesh, 8, 8, 1)    # kv heads < TP degree
    sp = create_mesh(MeshConfig(data=1, fsdp=1, model=1, seq=8))
    assert not flash_sharding_ok(sp, 8, 8, 2)      # seq-sharded -> ring path


# ------------------- ragged shapes + with-lse (round 3) ------------------ #

@pytest.mark.parametrize("T", [200, 130, 96])
def test_flash_ragged_T_matches_reference(T):
    """T % block_q != 0 must stay on the kernel path via internal padding
    (VERDICT r2 next-step 8) with exact parity."""
    q, k, v, ps = _setup(T=T)
    H = q.shape[3]
    valid = jnp.asarray([T, T - 37], jnp.int32)
    ref = _reference(q, k, v, ps, valid, 0, 0.0, H**-0.5)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(0), scale=H**-0.5, interpret=True
    )
    assert got.shape == q.shape
    for b in range(2):
        n = int(valid[b])
        np.testing.assert_allclose(got[b, :n], ref[b, :n], atol=2e-5, rtol=2e-5)


def test_flash_ragged_grad_matches_reference():
    """Gradients through the pad/slice pair: padded rows contribute
    exactly zero; real rows match the XLA reference."""
    q, k, v, ps = _setup(T=96)
    H = q.shape[3]
    T = q.shape[1]
    valid = jnp.full((2,), T, jnp.int32)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, ps, ps, valid, jnp.int32(0),
            scale=H**-0.5, interpret=True,
        )
        return jnp.sum(o**2)

    def loss_ref(q, k, v):
        return jnp.sum(_reference(q, k, v, ps, valid, 0, 0.0, H**-0.5) ** 2)

    g1 = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)


def test_flash_with_lse_chunked_merge_and_grad():
    """flash_attention_with_lse: two disjoint KV chunks merged by their
    lse rows must equal full attention — forward AND gradient (the dlse
    cotangent folds into the backward's delta operand)."""
    from pilottai_tpu.ops.pallas.flash_attention import flash_attention_with_lse

    q, k, v, ps = _setup(T=256)
    H = q.shape[3]
    T = q.shape[1]
    half = T // 2
    valid = jnp.asarray([T, 200], jnp.int32)

    def merged(q, k, v):
        outs = []
        for lo in (0, half):
            v_eff = jnp.clip(valid - lo, 0, half)
            o, lse = flash_attention_with_lse(
                q, k[:, lo:lo + half], v[:, lo:lo + half],
                ps, ps[:, lo:lo + half], v_eff, jnp.int32(0),
                scale=H**-0.5, interpret=True,
            )
            outs.append((o, lse))
        (o1, l1), (o2, l2) = outs
        M = jnp.maximum(l1, l2)
        w1 = jnp.where(l1 > -2.0**29, jnp.exp(l1 - M), 0.0)
        w2 = jnp.where(l2 > -2.0**29, jnp.exp(l2 - M), 0.0)
        den = jnp.maximum(w1 + w2, 1e-30)
        out = (o1 * w1 + o2 * w2) / den
        return jnp.where((w1 + w2) > 0, out, 0.0)

    def full(q, k, v):
        return _reference(q, k, v, ps, valid, 0, 0.0, H**-0.5)

    np.testing.assert_allclose(
        merged(q, k, v), full(q, k, v), atol=2e-5, rtol=2e-5
    )
    wmask = (
        jnp.arange(T)[None, :, None, None] < valid[:, None, None, None]
    )
    g1 = jax.grad(lambda *a: jnp.sum((merged(*a) * wmask) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(lambda *a: jnp.sum((full(*a) * wmask) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(a, b, atol=5e-4, rtol=5e-4)
