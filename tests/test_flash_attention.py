"""Pallas flash attention: parity vs the XLA reference implementation.

Runs in interpreter mode on the CPU test platform (the compiled kernel is
exercised on real TPU by bench.py / the engine). Tolerances are tight:
interpret mode is bit-faithful to the kernel's fp32 online softmax.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.ops.attention import (
    dot_product_attention,
    flash_enabled,
    flash_shapes_ok,
    make_attention_mask,
)
from pilottai_tpu.ops.pallas.flash_attention import flash_attention


def _setup(B=2, T=256, N=4, K=2, H=64, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, T, N, H)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, T, K, H)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, T, K, H)), jnp.float32)
    ps = jnp.broadcast_to(jnp.arange(T)[None, :], (B, T)).astype(jnp.int32)
    return q, k, v, ps


def _reference(q, k, v, ps, valid, window, softcap, scale):
    """Oracle via the shipped mask helper (contiguous positions only —
    ``make_attention_mask`` assumes cache slot j holds position j)."""
    mask = make_attention_mask(ps, q.shape[1], valid, window=window)
    return dot_product_attention(
        q, k, v, mask=mask, scale=scale, logit_softcap=softcap
    )


@pytest.mark.parametrize(
    "window,softcap",
    [(0, 0.0), (64, 0.0), (0, 50.0), (64, 30.0)],
)
def test_flash_matches_reference(window, softcap):
    q, k, v, ps = _setup()
    valid = jnp.asarray([256, 180], jnp.int32)
    scale = q.shape[-1] ** -0.5
    ref = _reference(q, k, v, ps, valid, window, softcap, scale)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(window),
        scale=scale, softcap=softcap, interpret=True,
    )
    # Rows past valid hold garbage in both paths; compare live rows only.
    np.testing.assert_allclose(ref[0], got[0], atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(ref[1, :180], got[1, :180], atol=2e-5, rtol=2e-5)


def test_flash_gqa_and_mha():
    for N, K in [(4, 4), (8, 2), (4, 1)]:
        q, k, v, ps = _setup(N=N, K=K, T=128)
        valid = jnp.full((2,), 128, jnp.int32)
        scale = q.shape[-1] ** -0.5
        ref = _reference(q, k, v, ps, valid, 0, 0.0, scale)
        got = flash_attention(
            q, k, v, ps, ps, valid, jnp.int32(0), scale=scale, interpret=True
        )
        np.testing.assert_allclose(ref, got, atol=2e-5, rtol=2e-5)


def test_flash_offset_positions():
    """Prefill at a nonzero offset (continuation): positions start at 100.
    Hand-built mask here — make_attention_mask assumes slot j == position j,
    which doesn't hold at an offset."""
    q, k, v, ps = _setup(T=128)
    ps = ps + 100
    valid = jnp.full((2,), 128, jnp.int32)
    scale = q.shape[-1] ** -0.5
    ipos, jpos = ps[:, :, None], ps[:, None, :]
    mask = (jpos <= ipos) & (
        jnp.arange(128)[None, None, :] < valid[:, None, None]
    )
    ref = dot_product_attention(q, k, v, mask=mask, scale=scale)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(0), scale=scale, interpret=True
    )
    np.testing.assert_allclose(ref, got, atol=2e-5, rtol=2e-5)


def test_flash_fully_masked_rows_are_zero():
    """valid=0 for one batch row -> output rows all zeros, no NaN."""
    q, k, v, ps = _setup(T=128)
    valid = jnp.asarray([128, 0], jnp.int32)
    got = flash_attention(
        q, k, v, ps, ps, valid, jnp.int32(0),
        scale=q.shape[-1] ** -0.5, interpret=True,
    )
    assert not bool(jnp.isnan(got).any())
    np.testing.assert_array_equal(np.asarray(got[1]), 0.0)


def test_dispatch_gates(monkeypatch):
    monkeypatch.setenv("PILOTTAI_NO_FLASH", "1")
    assert not flash_enabled()  # env kill-switch wins on any platform
    assert flash_shapes_ok(256, 256)
    assert not flash_shapes_ok(192, 256)
    assert not flash_shapes_ok(64, 64)          # below one block
    assert flash_shapes_ok(8192, 8192, head_dim=128, itemsize=2)
    assert not flash_shapes_ok(16384, 16384, head_dim=128, itemsize=2)  # VMEM
