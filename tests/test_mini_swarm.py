"""Mini-swarm success rate + routing on the REAL engine with the
committed protocol checkpoint (VERDICT r5 next-step 3b).

The round-5 swarm headline (96/96 through ``Serve``) lived only in
builder-authored prose: CI proved ONE agent-task success on the real
engine, and stage routing was asserted only on the mock backend. This
suite puts both under CI assertion: a Serve swarm sharing one CPU-engine
``protocol-s`` handler must complete ≥90% of ≥12 tasks, and typed tasks
must land on the specialized agent (extract → extractor, summarize →
generator) while the checkpoint engine — not a mock — drives every
agent decision.
"""

import asyncio

import pytest

from pilottai_tpu.train.protocol import (
    DEFAULT_CHECKPOINT,
    SERVE_MAX_NEW,
    SERVE_MAX_SEQ,
    has_checkpoint,
)

# CI's main pytest lane runs `-m "not chaos"` — slow INCLUDED — so this
# gates merges there; the tier-1 quick lane (`-m "not slow"`) skips it
# (one full engine boot + 16 Serve tasks on the CPU engine is a soak).
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(not has_checkpoint(), reason="no committed checkpoint"),
]


def _swarm_llm(sched_policy="dag"):
    from pilottai_tpu.core.config import LLMConfig, SamplingConfig
    from pilottai_tpu.engine.handler import LLMHandler

    return LLMHandler(LLMConfig(
        model_name="protocol-s", provider="cpu",
        checkpoint_path=str(DEFAULT_CHECKPOINT),
        engine_slots=4, engine_admit_batch=4,
        engine_max_seq=SERVE_MAX_SEQ, engine_chunk=16, dtype="float32",
        engine_sched_policy=sched_policy,
        sampling=SamplingConfig(
            temperature=0.0, max_new_tokens=SERVE_MAX_NEW
        ),
    ))


def test_mini_swarm_success_rate_and_checkpoint_routing():
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, ServeConfig
    from pilottai_tpu.core.task import Task
    from pilottai_tpu.serve import Serve

    async def main():
        from pilottai_tpu.obs import global_occupancy
        from pilottai_tpu.utils.metrics import global_metrics

        # Section-pure task histograms (same discipline as PR 6's
        # `request.` resets): earlier suites' task.* samples — and the
        # occupancy windows their agents filled — must not land in this
        # soak's window accounting.
        global_metrics.reset_histograms("task.")
        global_occupancy.reset()
        llm = _swarm_llm()
        agents = [
            BaseAgent(
                config=AgentConfig(
                    role="extractor", specializations=["extract"],
                    max_iterations=2,
                ),
                llm=llm,
            ),
            BaseAgent(
                config=AgentConfig(
                    role="generator", specializations=["summarize"],
                    max_iterations=2,
                ),
                llm=llm,
            ),
            BaseAgent(
                config=AgentConfig(
                    role="worker0", specializations=["generic"],
                    max_iterations=2,
                ),
                llm=llm,
            ),
            BaseAgent(
                config=AgentConfig(
                    role="worker1", specializations=["generic"],
                    max_iterations=2,
                ),
                llm=llm,
            ),
        ]
        serve = Serve(
            name="mini-swarm", agents=agents, manager_llm=llm,
            config=ServeConfig(
                decomposition_enabled=False, max_concurrent_tasks=4,
            ),
        )
        await serve.start()
        try:
            # Typed tasks FIRST, sequentially over an idle pool: routing
            # is load-aware, so idleness isolates the specialization
            # signal (the thing under test) from queue depth.
            routed = []
            for i in range(2):
                routed.append(await serve.execute_task(Task(
                    description=f"extract the order ids from report {i}",
                    type="extract",
                )))
                routed.append(await serve.execute_task(Task(
                    description=f"summarize shipment digest {i}",
                    type="summarize",
                )))
            # Then the concurrent swarm wave for the success-rate bar.
            swarm = await asyncio.gather(*[
                serve.execute_task(f"swarm task {i}: check inventory {i}")
                for i in range(12)
            ])
            by_role = {a.role: a for a in serve.agent_list()}
            counts = {
                role: by_role[role].task_metrics["completed"]
                for role in ("extractor", "generator")
            }
            return routed + list(swarm), counts
        finally:
            await serve.stop()
            await llm.stop()

    results, counts = asyncio.run(main())
    assert len(results) >= 16
    ok = sum(1 for r in results if r.success)
    rate = ok / len(results)
    assert rate >= 0.9, (
        f"{ok}/{len(results)} succeeded",
        [r.error for r in results if not r.success][:4],
    )
    # Checkpoint-backed routing: every typed task landed on its
    # specialist (2 extract + 2 summarize, executed over an idle pool).
    assert counts["extractor"] >= 2, counts
    assert counts["generator"] >= 2, counts


def test_mini_swarm_scheduler_on_at_least_off():
    """ISSUE 12 CI lane: the DAG-aware scheduler must never COST task
    success — scheduler-on (priority backlog + gang + aging + pre-warm)
    completes at least as many mini-swarm tasks as scheduler-off on the
    same workload and checkpoint. (Latency gains are the bench's story;
    this gate is about safety of turning the policy on by default.)"""
    from pilottai_tpu.core.agent import BaseAgent
    from pilottai_tpu.core.config import AgentConfig, ServeConfig
    from pilottai_tpu.serve import Serve

    async def run_swarm(policy):
        from pilottai_tpu.sched import global_scheduler

        global_scheduler.configure(
            policy="dag" if policy == "dag" else "off"
        )
        global_scheduler.reset()
        llm = _swarm_llm(sched_policy=policy)
        agents = [
            BaseAgent(
                config=AgentConfig(
                    role=f"worker{i}", specializations=["generic"],
                    max_iterations=2,
                ),
                llm=llm,
            )
            for i in range(3)
        ]
        serve = Serve(
            name=f"mini-swarm-{policy}", agents=agents, manager_llm=llm,
            config=ServeConfig(
                decomposition_enabled=False, max_concurrent_tasks=3,
            ),
        )
        await serve.start()
        try:
            results = await asyncio.gather(*[
                serve.execute_task(f"swarm task {i}: check inventory {i}")
                for i in range(8)
            ])
            return sum(1 for r in results if r.success), len(results)
        finally:
            await serve.stop()
            await llm.stop()

    async def main():
        try:
            off_ok, off_n = await run_swarm("fifo")
            on_ok, on_n = await run_swarm("dag")
        finally:
            from pilottai_tpu.sched import global_scheduler

            global_scheduler.configure(policy="dag")
        return off_ok, off_n, on_ok, on_n

    off_ok, off_n, on_ok, on_n = asyncio.run(main())
    assert on_n == off_n
    assert on_ok >= off_ok, (
        f"scheduler-on completed {on_ok}/{on_n} vs scheduler-off "
        f"{off_ok}/{off_n} — the DAG policy cost task success"
    )
