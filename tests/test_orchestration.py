"""Orchestration control-plane tests — reference strategy (SURVEY §4):
force the metric inputs, assert the control decision."""

import asyncio
import time

import pytest

from pilottai_tpu.core.agent import BaseAgent
from pilottai_tpu.core.config import (
    AgentConfig,
    FaultToleranceConfig,
    LLMConfig,
    LoadBalancerConfig,
    ScalingConfig,
    ServeConfig,
)
from pilottai_tpu.core.status import AgentStatus, HealthStatus
from pilottai_tpu.core.task import Task
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.orchestration.fault_tolerance import FaultTolerance
from pilottai_tpu.orchestration.load_balancer import LoadBalancer
from pilottai_tpu.orchestration.scaling import DynamicScaling
from pilottai_tpu.serve import Serve


def worker(**cfg):
    return BaseAgent(
        config=AgentConfig(role="worker", **cfg),
        llm=LLMHandler(LLMConfig(provider="mock")),
    )


def make_serve(agents):
    return Serve(
        name="orch-test",
        agents=agents,
        manager_llm=LLMHandler(LLMConfig(provider="mock")),
        config=ServeConfig(max_concurrent_tasks=4),
    )


# ----------------------------- balancer -------------------------------- #

@pytest.mark.asyncio
async def test_balancer_moves_tasks_from_hot_to_cold():
    hot = worker(max_queue_size=4)
    cold = worker(max_queue_size=100)
    await hot.start(); await cold.start()
    for i in range(4):
        await hot.add_task(Task(description=f"queued {i}"))
    serve = make_serve([hot, cold])
    lb = LoadBalancer(serve, LoadBalancerConfig(max_tasks_per_cycle=2))
    moved = await lb.balance_once()
    assert moved == 2
    assert len(cold.queued_tasks()) == 2
    assert len(hot.queued_tasks()) == 2
    assert lb.get_metrics()["moves"] == 2


@pytest.mark.asyncio
async def test_balancer_respects_unmoveable():
    hot = worker(max_queue_size=2)
    cold = worker()
    await hot.start(); await cold.start()
    pinned = Task(description="pinned", metadata={"unmoveable": True})
    await hot.add_task(pinned)
    await hot.add_task(Task(description="free"))
    serve = make_serve([hot, cold])
    lb = LoadBalancer(serve)
    await lb.balance_once()
    assert pinned.id in {t.id for t in hot.queued_tasks()}


@pytest.mark.asyncio
async def test_balancer_noop_when_balanced():
    a, b = worker(), worker()
    await a.start(); await b.start()
    serve = make_serve([a, b])
    lb = LoadBalancer(serve)
    assert await lb.balance_once() == 0


# ----------------------------- scaling --------------------------------- #
# The scaler consumes the obs metrics snapshot; every test injects an
# ISOLATED registry so gauges from other tests in the process (engine
# runs set engine.*/slo.* on the global bus) can't tilt the decision.

from pilottai_tpu.utils.metrics import MetricsRegistry


@pytest.mark.asyncio
async def test_scaling_up_on_high_load():
    busy = worker(max_queue_size=2)
    await busy.start()
    for i in range(2):
        await busy.add_task(Task(description=f"q{i}"))
    serve = make_serve([busy])
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=3, cooldown=0.0),
        registry=MetricsRegistry(),
    )
    decision = await scaler.scale_once()
    assert decision == "up"
    assert len(serve.agents) == 2
    assert scaler.scale_ups == 1


@pytest.mark.asyncio
async def test_scaling_down_drains_idle_lowest_success():
    a, b, c = worker(), worker(), worker()
    for agent in (a, b, c):
        await agent.start()
    b.task_metrics["failed"] = 5  # lowest success rate
    serve = make_serve([a, b, c])
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=5, cooldown=0.0,
                             scale_down_threshold=0.5),
        registry=MetricsRegistry(),
    )
    decision = await scaler.scale_once()
    assert decision == "down"
    assert b.id not in serve.agents
    assert b.status == AgentStatus.STOPPED


@pytest.mark.asyncio
async def test_scaling_cooldown_blocks_consecutive_actions():
    busy = worker(max_queue_size=1)
    await busy.start()
    await busy.add_task(Task(description="q"))
    serve = make_serve([busy])
    scaler = DynamicScaling(
        serve,
        ScalingConfig(min_agents=1, max_agents=5, cooldown=300.0,
                      scale_up_threshold=0.3),
        registry=MetricsRegistry(),
    )
    assert await scaler.scale_once() == "up"
    assert await scaler.scale_once() is None  # cooling down


@pytest.mark.asyncio
async def test_scaling_respects_max_agents():
    busy = worker(max_queue_size=1)
    await busy.start()
    await busy.add_task(Task(description="q"))
    serve = make_serve([busy])
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=1, cooldown=0.0),
        registry=MetricsRegistry(),
    )
    assert await scaler.scale_once() is None


@pytest.mark.asyncio
async def test_scaling_up_on_slo_burn_rate_alone():
    """A burning SLO error budget (slo.*.burn_rate gauge >= 2x) must
    read as full load and scale up even with every queue empty — the
    obs-driven half of the autoscaling loop."""
    idle_agent = worker()
    await idle_agent.start()
    serve = make_serve([idle_agent])
    registry = MetricsRegistry()
    registry.set_gauge("slo.interactive.burn_rate", 3.0)
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=3, cooldown=0.0),
        registry=registry,
    )
    decision = await scaler.scale_once()
    assert decision == "up"
    assert registry.get("scaling.recommendation") == 1.0
    assert registry.get("scaling.system_load") >= 0.8


@pytest.mark.asyncio
async def test_scaling_engine_queue_signal_and_recommendation_gauge():
    """Engine admission-queue pressure flows through the snapshot, and
    the decision is exported as a gauge even when the actuator can't act
    (max_agents cap): recommendation says "grow", action stays None."""
    busy = worker(max_queue_size=2)
    await busy.start()
    for i in range(2):
        await busy.add_task(Task(description=f"q{i}"))
    serve = make_serve([busy])
    registry = MetricsRegistry()
    registry.set_gauge("engine.queue_depth", 40.0)
    registry.set_gauge("engine.max_queue_depth", 40.0)
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=1, cooldown=0.0),
        registry=registry,
    )
    assert scaler.signals()["engine_queue_frac"] == 1.0
    assert await scaler.scale_once() is None  # capped
    assert registry.get("scaling.recommendation") == 1.0
    assert registry.get("scaling.target_agents") == 1.0
    # The orchestrator-side pressure was published as gauges too — one
    # surface for decision, dashboard and scraper.
    gauges = registry.snapshot()["gauges"]
    assert gauges["orchestrator.agent_queue_util"] == 1.0


@pytest.mark.asyncio
async def test_scaling_burn_pressure_decays_on_idle_system():
    """Review regression: burn gauges are written at flight-finish only,
    so after an outage-then-silence the scaler would read the final
    (alarming) burn forever and hold max capacity on an idle system.
    With a tracker wired in, signals() refreshes against the clock: an
    empty burn window decays to 0 and the idle pool can shrink."""
    import time as _time

    from pilottai_tpu.obs.slo import SLOTracker

    a, b = worker(), worker()
    await a.start(); await b.start()
    serve = make_serve([a, b])
    registry = MetricsRegistry()
    tracker = SLOTracker(registry=registry)
    old = _time.monotonic() - 400.0  # misses now outside the burn window
    for _ in range(20):
        tracker.record("interactive", ok=False, at=old)
    assert registry.snapshot()["gauges"]["slo.interactive.burn_rate"] > 1.0
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=5, cooldown=0.0,
                             scale_down_threshold=0.4),
        registry=registry, slo_tracker=tracker,
    )
    assert scaler.signals()["slo_burn_rate"] == 0.0
    assert await scaler.scale_once() == "down"


@pytest.mark.asyncio
async def test_scaling_holds_while_budget_burns():
    """Burn ~1x floors the load mid-range: the scaler must not drain
    agents while the error budget is burning at provisioned rate."""
    a, b = worker(), worker()
    await a.start(); await b.start()
    serve = make_serve([a, b])
    registry = MetricsRegistry()
    registry.set_gauge("slo.batch.burn_rate", 1.0)
    scaler = DynamicScaling(
        serve, ScalingConfig(min_agents=1, max_agents=5, cooldown=0.0,
                             scale_down_threshold=0.4),
        registry=registry,
    )
    assert await scaler.scale_once() is None
    assert len(serve.agents) == 2


# ----------------------------- fault tolerance -------------------------- #

@pytest.mark.asyncio
async def test_health_classification_and_recovery():
    agent = worker()
    await agent.start()
    serve = make_serve([agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=0.05, recovery_cooldown=0.0, max_recovery_attempts=3,
    ))
    ft.register_agent(agent)
    statuses = await ft.check_once()
    assert statuses[agent.id] == HealthStatus.HEALTHY

    # Stale heartbeat -> UNHEALTHY -> in-place recovery refreshes it.
    agent._last_heartbeat = time.time() - 10
    statuses = await ft.check_once()
    assert ft.health[agent.id].recovery_attempts == 1
    assert agent.status == AgentStatus.IDLE
    assert time.time() - agent._last_heartbeat < 5
    assert ft.recovery_history[-1]["action"] == "recover"
    assert ft.recovery_history[-1]["success"] is True


@pytest.mark.asyncio
async def test_critical_agent_replaced_with_task_transfer():
    sick = worker()
    await sick.start()
    await sick.add_task(Task(description="queued work"))
    await sick.add_task(Task(description="lost cause", metadata={"non_recoverable": True}))
    serve = make_serve([sick])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=0.01, max_recovery_attempts=0,  # recovery exhausted
        error_threshold=1,
    ))
    ft.register_agent(agent := sick)
    # stale heartbeat + errors + error status -> CRITICAL
    agent._last_heartbeat = time.time() - 100
    agent._error_count = 5
    agent.status = AgentStatus.ERROR
    await ft.check_once()
    assert sick.id not in serve.agents
    assert len(serve.agents) == 1
    replacement = next(iter(serve.agents.values()))
    transferred = replacement.queued_tasks()
    assert len(transferred) == 1
    assert transferred[0].description == "queued work"
    assert ft.get_metrics()["replacements"] >= 1


@pytest.mark.asyncio
async def test_recovery_preserves_queued_backlog():
    """In-place recovery must not cancel the agent's queued tasks (reset()
    drops the queue; FT detaches and re-adds around it)."""
    agent = worker()
    await agent.start()
    backlog = [Task(description=f"backlog {i}") for i in range(3)]
    for t in backlog:
        await agent.add_task(t)
    serve = make_serve([agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=0.05, recovery_cooldown=0.0, max_recovery_attempts=3,
    ))
    ft.register_agent(agent)
    agent._last_heartbeat = time.time() - 10
    await ft.check_once()
    assert ft.health[agent.id].recovery_attempts == 1
    queued = {t.id for t in agent.queued_tasks()}
    assert queued == {t.id for t in backlog}
    assert all(not t.status.is_terminal for t in backlog)


@pytest.mark.asyncio
async def test_replacement_overflow_requeues_at_orchestrator():
    """Transfer overflow (replacement queue smaller than the backlog) must
    requeue through the orchestrator, never orphan tasks."""
    sick = worker(max_queue_size=10, max_concurrent_tasks=1)
    sick.config.max_queue_size = 1  # replacement copies this: holds 1 task
    sick.task_queue.maxsize = 10
    await sick.start()
    tasks = [Task(description=f"work {i}") for i in range(3)]
    for t in tasks:
        await sick.add_task(t)
    serve = make_serve([sick])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=0.01, max_recovery_attempts=0, error_threshold=1,
    ))
    ft.register_agent(sick)
    sick._last_heartbeat = time.time() - 100
    sick._error_count = 5
    sick.status = AgentStatus.ERROR
    await ft.check_once()
    assert sick.id not in serve.agents
    replacement = next(a for a in serve.agents.values() if a.id != sick.id)
    assert len(replacement.queued_tasks()) == 1
    # The other two went through Serve.requeue_task -> orchestrator queue.
    orphaned = [
        t for t in tasks
        if t.id not in {q.id for q in replacement.queued_tasks()}
        and t.id not in serve.all_tasks
    ]
    assert not orphaned


@pytest.mark.asyncio
async def test_recovery_attempt_cap():
    agent = worker()
    await agent.start()
    serve = make_serve([agent])
    ft = FaultTolerance(serve, FaultToleranceConfig(
        heartbeat_timeout=0.01, recovery_cooldown=1000.0, max_recovery_attempts=1,
    ))
    ft.register_agent(agent)
    agent._last_heartbeat = time.time() - 100

    async def fail_start():
        raise RuntimeError("cannot start")

    original_start = agent.start
    agent.start = fail_start  # recovery fails
    await ft.check_once()
    assert ft.health[agent.id].recovery_attempts == 1
    agent._last_heartbeat = time.time() - 100
    await ft.check_once()  # capped: no second attempt
    assert ft.health[agent.id].recovery_attempts == 1
    agent.start = original_start


# ----------------------------- integrated lifecycle --------------------- #

@pytest.mark.asyncio
async def test_services_wired_into_serve_lifecycle():
    serve = Serve(
        name="wired",
        agents=[worker(), worker()],
        manager_llm=LLMHandler(LLMConfig(provider="mock")),
        config=ServeConfig(
            load_balancing_enabled=True,
            dynamic_scaling_enabled=True,
            fault_tolerance_enabled=True,
        ),
    )
    await serve.start()
    try:
        assert serve.load_balancer is not None
        assert serve.dynamic_scaling is not None
        assert serve.fault_tolerance is not None
        result = await serve.execute_task("work under full services", timeout=30)
        assert result.success
    finally:
        await serve.stop()
    assert serve.load_balancer._task is None  # loops actually stopped
