"""HF checkpoint loader tests (VERDICT r2 missing #3 / next-step 7+9).

``models/loader.py`` is the only path by which real Llama/Gemma weights
enter the system (reference's provider onboarding:
``pilott/engine/llm.py:129-151``); until now no test touched it. These
tests write a tiny synthetic HF-layout safetensors checkpoint in-test
(no network), load it back, and assert:

* forward parity with the source pytree on one device;
* sharded load onto the 8-device mesh keeps the logical shardings and
  the same logits;
* ``quantize_params(donate=True)`` on the *sharded* loaded tree — the
  exact 8B-on-mesh path — still serves;
* the gemma2 name overrides (pre/post feedforward norms) map correctly;
* the Embedder really uses checkpoint-derived weights (fails if the
  loader silently fell back to random init — keeps BASELINE config #2's
  "Gemma-2B encoder" claim honest).
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pilottai_tpu.models.common import init_params, param_logical_axes
from pilottai_tpu.models.loader import load_hf_checkpoint
from pilottai_tpu.models.registry import get_model_config
from pilottai_tpu.models.transformer import forward_prefill


def _to_hf_layout(cfg, params):
    """Convert our stacked pytree to HF per-layer tensors (the inverse of
    load_hf_checkpoint's mapping)."""
    tensors = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(
            params["final_norm"]["scale"], np.float32
        ),
    }
    layers = params["layers"]
    gemma2 = cfg.family == "gemma2"
    for i in range(cfg.n_layers):
        pre = f"model.layers.{i}."
        tensors[pre + "input_layernorm.weight"] = np.asarray(
            layers["ln1"]["scale"][i], np.float32
        )
        if gemma2:
            tensors[pre + "post_attention_layernorm.weight"] = np.asarray(
                layers["ln1_post"]["scale"][i], np.float32
            )
            tensors[pre + "pre_feedforward_layernorm.weight"] = np.asarray(
                layers["ln2"]["scale"][i], np.float32
            )
            tensors[pre + "post_feedforward_layernorm.weight"] = np.asarray(
                layers["ln2_post"]["scale"][i], np.float32
            )
        else:
            tensors[pre + "post_attention_layernorm.weight"] = np.asarray(
                layers["ln2"]["scale"][i], np.float32
            )
        for ours, hf in (
            ("wq", "self_attn.q_proj"), ("wk", "self_attn.k_proj"),
            ("wv", "self_attn.v_proj"), ("wo", "self_attn.o_proj"),
        ):
            # ours [in,out] -> HF [out,in]. ascontiguousarray matters:
            # safetensors 0.8.0 silently serializes the base buffer of a
            # non-contiguous view (shape says transposed, bytes are not).
            tensors[pre + hf + ".weight"] = np.ascontiguousarray(
                np.asarray(layers["attn"][ours][i], np.float32).T
            )
        for ours, hf in (
            ("wg", "mlp.gate_proj"), ("wu", "mlp.up_proj"),
            ("wd", "mlp.down_proj"),
        ):
            tensors[pre + hf + ".weight"] = np.ascontiguousarray(
                np.asarray(layers["mlp"][ours][i], np.float32).T
            )
    if not cfg.tie_embeddings:
        tensors["lm_head.weight"] = np.ascontiguousarray(
            np.asarray(params["lm_head"], np.float32).T
        )
    return tensors


def _write_checkpoint(tmp_path, cfg, params, sharded_files=1):
    from safetensors.numpy import save_file

    tensors = _to_hf_layout(cfg, params)
    if sharded_files == 1:
        save_file(tensors, str(tmp_path / "model.safetensors"))
    else:
        # Multi-shard layout with an index file, like every real >2GB HF
        # checkpoint ships.
        names = sorted(tensors)
        per = -(-len(names) // sharded_files)
        weight_map = {}
        for s in range(sharded_files):
            fname = f"model-{s + 1:05d}-of-{sharded_files:05d}.safetensors"
            chunk = {n: tensors[n] for n in names[s * per: (s + 1) * per]}
            save_file(chunk, str(tmp_path / fname))
            for n in chunk:
                weight_map[n] = fname
        (tmp_path / "model.safetensors.index.json").write_text(
            json.dumps({"weight_map": weight_map})
        )
    return tmp_path


@pytest.fixture(scope="module")
def llama_ckpt(tmp_path_factory):
    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(7), dtype=jnp.float32)
    path = _write_checkpoint(
        tmp_path_factory.mktemp("llama_ckpt"), cfg, params, sharded_files=2
    )
    return cfg, params, path


def _logits(cfg, params, seed=0):
    B, T = 2, 16
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(2, cfg.vocab_size, (B, T)), jnp.int32)
    positions = jnp.broadcast_to(jnp.arange(T)[None], (B, T)).astype(jnp.int32)
    valid = jnp.asarray([T, T - 5], jnp.int32)
    out, _, _ = forward_prefill(
        params, cfg, tokens, positions, valid, use_flash=False
    )
    return np.asarray(out)


def test_loader_roundtrip_forward_parity(llama_ckpt):
    cfg, src, path = llama_ckpt
    loaded = load_hf_checkpoint(cfg, path, dtype=jnp.float32)
    np.testing.assert_allclose(
        _logits(cfg, loaded), _logits(cfg, src), rtol=1e-5, atol=1e-5
    )


def test_loader_sharded_mesh_parity(llama_ckpt):
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
    from pilottai_tpu.parallel.sharding import named_sharding

    cfg, src, path = llama_ckpt
    mesh = create_mesh(MeshConfig(model=2, fsdp=2, data=2))
    loaded = load_hf_checkpoint(cfg, path, mesh=mesh, dtype=jnp.float32)
    # Every leaf carries the logical sharding the axes table prescribes.
    axes = param_logical_axes(cfg)

    def check(ax, leaf):
        assert leaf.sharding == named_sharding(mesh, ax), (
            f"leaf sharded {leaf.sharding} want {named_sharding(mesh, ax)}"
        )

    jax.tree.map(
        check, axes, loaded,
        is_leaf=lambda x: isinstance(x, tuple) and all(
            a is None or isinstance(a, str) for a in x
        ),
    )
    np.testing.assert_allclose(
        _logits(cfg, loaded), _logits(cfg, src), rtol=1e-4, atol=1e-4
    )


def test_loader_sharded_then_quantized_serves(llama_ckpt):
    """The 8B production path in miniature: load sharded, quantize the
    sharded tree with donation, and run prefill — never exercised before
    (VERDICT r2 Weak #6)."""
    from pilottai_tpu.models.quant import quantize_params
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh

    cfg, src, path = llama_ckpt
    mesh = create_mesh(MeshConfig(model=4), jax.devices()[:4])
    loaded = load_hf_checkpoint(cfg, path, mesh=mesh, dtype=jnp.float32)
    quant = quantize_params(loaded, dtype=jnp.float32, donate=True)
    # int8 carries ~0.4% relative error; compare coarsely but meaningfully.
    got, want = _logits(cfg, quant), _logits(cfg, src)
    assert np.mean(np.abs(got - want)) < 0.05 * (np.std(want) + 1e-6)


def test_loader_gemma2_name_overrides(tmp_path):
    """gemma2 checkpoints use pre/post_feedforward_layernorm names; the
    loader's override table must land them on ln2/ln2_post (a silent
    mis-mapping would produce a 'working' model with wrong norms)."""
    cfg = get_model_config("gemma-tiny")
    src = init_params(cfg, jax.random.PRNGKey(3), dtype=jnp.float32)
    # Make the four norm families distinguishable: random, not all-zeros
    # (gemma rms_offset init is zeros — any permutation would "match").
    k = jax.random.PRNGKey(11)
    for i, group in enumerate(("ln1", "ln2", "ln1_post", "ln2_post")):
        src["layers"][group]["scale"] = 0.1 * jax.random.normal(
            jax.random.fold_in(k, i),
            src["layers"][group]["scale"].shape,
            dtype=jnp.float32,
        )
    path = _write_checkpoint(tmp_path, cfg, src)
    loaded = load_hf_checkpoint(cfg, path, dtype=jnp.float32)
    for group in ("ln1", "ln2", "ln1_post", "ln2_post"):
        np.testing.assert_allclose(
            np.asarray(loaded["layers"][group]["scale"]),
            np.asarray(src["layers"][group]["scale"]),
            rtol=1e-6,
            err_msg=f"norm group {group} mis-mapped",
        )
    np.testing.assert_allclose(
        _logits(cfg, loaded), _logits(cfg, src), rtol=1e-5, atol=1e-5
    )


def test_embedder_uses_checkpoint_weights(llama_ckpt):
    """BASELINE config #2 honesty check: an Embedder given a checkpoint
    must produce checkpoint-derived embeddings — this fails if the loader
    path silently falls back to random init."""
    from pilottai_tpu.memory.embedder import Embedder, _encode_batch

    cfg, src, path = llama_ckpt
    emb = Embedder("llama-tiny", checkpoint_path=str(path))
    texts = ["semantic memory check", "a different sentence"]
    got = emb.encode(texts)

    # Ground truth: same encode pipeline, source params directly.
    ids = [emb.tokenizer.encode(t)[: emb.max_len] for t in texts]
    T = emb._bucket(max(len(i) for i in ids))
    batch = np.zeros((len(ids), T), np.int32)
    valid = np.zeros((len(ids),), np.int32)
    for row, seq in enumerate(ids):
        batch[row, : len(seq)] = seq
        valid[row] = len(seq)
    want = np.asarray(_encode_batch(
        src, emb.cfg, jnp.asarray(batch), jnp.asarray(valid)
    ))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)

    # And it is NOT the random-init encoder's output.
    rand = Embedder("llama-tiny", seed=5)
    rand_out = rand.encode(texts)
    assert not np.allclose(got, rand_out, atol=1e-3)


def test_engine_serves_from_checkpoint(llama_ckpt):
    """End-to-end: NativeEngine boots from checkpoint_path (the native.py
    branch no test previously entered) and generates."""
    import asyncio

    from pilottai_tpu.core.config import LLMConfig
    from pilottai_tpu.engine.handler import LLMHandler
    from pilottai_tpu.engine.types import ChatMessage, GenerationParams

    cfg, _, path = llama_ckpt

    async def run():
        handler = LLMHandler(LLMConfig(
            model_name="llama-tiny",
            provider="cpu",
            checkpoint_path=str(path),
            engine_slots=2,
            engine_max_seq=128,
            engine_chunk=4,
            dtype="float32",
        ))
        await handler.start()
        try:
            resp = await handler.generate_response(
                [ChatMessage(role="user", content="hello from a checkpoint")],
                params=GenerationParams(max_new_tokens=6, temperature=0.0),
            )
            return resp
        finally:
            await handler.stop()

    resp = asyncio.run(run())
    assert resp.usage.completion_tokens >= 1
