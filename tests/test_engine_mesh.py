"""Multi-chip SERVING certification (VERDICT r2 next-step 1).

Round 2 certified multi-chip *training* (dryrun + sharded train step);
the serving path — ``NativeEngine``/``ContinuousBatcher`` with sharded
params, ``admit_group``/``decode_chunk`` under a mesh, int8 ``QTensor``
leaves, the paged cache — had zero >1-device coverage. These tests run
the full engine end-to-end on the virtual 8-device CPU mesh
(tests/conftest.py) and assert generation parity with the single-device
engine. BASELINE.md's target hardware is v5e-8: serving on a mesh is the
framework's headline claim, so it gets the same treatment training got.
"""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from pilottai_tpu.core.config import LLMConfig
from pilottai_tpu.engine.batcher import ContinuousBatcher, GenRequest
from pilottai_tpu.engine.handler import LLMHandler
from pilottai_tpu.engine.types import ChatMessage, GenerationParams
from pilottai_tpu.models.common import init_params
from pilottai_tpu.models.registry import get_model_config

PROMPTS = [
    "alpha beta gamma delta",
    "the quick brown fox jumps over",
    "zeta",
    "multi chip serving parity check",
]


async def _generate_all(
    mesh_shape,
    model_name="llama-tiny",
    quantize=None,
    paged=False,
    max_new=10,
):
    cfg = LLMConfig(
        model_name=model_name,
        provider="cpu",
        mesh_shape=mesh_shape,
        quantize=quantize,
        engine_slots=4,
        engine_max_seq=128,
        engine_chunk=4,
        engine_paged_kv=paged,
        engine_page_size=16,
        dtype="float32",  # greedy argmax parity across shardings
    )
    handler = LLMHandler(cfg)
    await handler.start()
    try:
        resps = await asyncio.gather(*[
            handler.generate_response(
                [ChatMessage(role="user", content=p)],
                params=GenerationParams(max_new_tokens=max_new, temperature=0.0),
            )
            for p in PROMPTS
        ])
        return [r.content for r in resps]
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_serving_parity_tp2_dp2():
    """Dense bf16→fp32 engine on a {model:2, data:2} mesh produces the
    same greedy generations as the single-device engine."""
    single = await _generate_all({"data": 1})
    meshed = await _generate_all({"model": 2, "data": 2})
    assert meshed == single
    assert any(s for s in single)  # not all-empty


@pytest.mark.asyncio
async def test_serving_parity_tp4_int8_paged():
    """The 8B-on-mesh configuration in miniature: int8-quantized sharded
    params + paged KV cache on a pure-TP {model:4} mesh. This is the exact
    path VERDICT r2 Weak #6 flagged as never having run on >1 device
    (quantize_params on a sharded tree)."""
    single = await _generate_all({"data": 1}, quantize="int8", paged=True)
    meshed = await _generate_all({"model": 4}, quantize="int8", paged=True)
    assert meshed == single


@pytest.mark.asyncio
async def test_serving_parity_moe_tp2():
    """MoE serving on a mesh: expert-parallel rides the model axis."""
    single = await _generate_all({"data": 1}, model_name="moe-tiny")
    meshed = await _generate_all({"model": 2}, model_name="moe-tiny")
    assert meshed == single


def test_quantize_params_sharded_tree_preserves_shardings():
    """quantize_params on an already-sharded tree must keep each leaf's
    NamedSharding (scale reduction must not silently reshard) and match
    the values of quantizing the unsharded tree."""
    import numpy as np

    from pilottai_tpu.models.common import param_logical_axes
    from pilottai_tpu.models.quant import QTensor, quantize_params
    from pilottai_tpu.parallel.mesh import MeshConfig, create_mesh
    from pilottai_tpu.parallel.sharding import shard_params

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    q_plain = quantize_params(params, dtype=jnp.float32)

    mesh = create_mesh(MeshConfig(model=2, data=2), jax.devices()[:4])
    sharded = shard_params(params, param_logical_axes(cfg), mesh)
    shardings_before = jax.tree.map(
        lambda a: a.sharding, sharded,
        is_leaf=lambda x: isinstance(x, jax.Array),
    )
    q_sharded = quantize_params(sharded, dtype=jnp.float32)

    flat_plain = jax.tree.leaves(
        q_plain, is_leaf=lambda x: isinstance(x, QTensor)
    )
    flat_sharded = jax.tree.leaves(
        q_sharded, is_leaf=lambda x: isinstance(x, QTensor)
    )
    assert len(flat_plain) == len(flat_sharded)
    for a, b in zip(flat_plain, flat_sharded):
        if isinstance(a, QTensor):
            assert isinstance(b, QTensor)
            np.testing.assert_array_equal(np.asarray(a.q), np.asarray(b.q))
            np.testing.assert_allclose(
                np.asarray(a.s), np.asarray(b.s), rtol=1e-6
            )
            # The int8 payload must stay sharded the way the weight was.
            assert not b.q.sharding.is_fully_replicated or (
                a.q.ndim < 2
            ), "sharded weight lost its sharding through quantize"


def test_rebuild_requeues_later_groups():
    """ADVICE r2 (medium): when a failed donated admission forces a device-
    state rebuild mid-wave, the REMAINING groups of that wave hold page
    allocations from the dead allocator — they must be requeued (and then
    complete correctly), not prefilled against the fresh allocator's
    sentinel rows (which silently produced garbage completions)."""
    import pilottai_tpu.engine.batcher as bmod

    cfg = get_model_config("llama-tiny")
    params = init_params(cfg, jax.random.PRNGKey(0), dtype=jnp.float32)
    batcher = ContinuousBatcher(
        cfg, params, n_slots=2, max_seq_len=64, cache_dtype=jnp.float32,
        admit_batch=1, paged=True, page_size=8,
        # Recovery off: this regression pins the REQUEUE of later groups
        # after a mid-wave rebuild; with recovery on, req1 would simply
        # re-admit and complete too (that contract is test_chaos.py's).
        recovery_max_attempts=0,
    )
    real_admit = bmod.admit_group
    calls = {"n": 0}

    def poison_once(params_, cfg_, cache, dstate, sampling, *a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            for k_, v_ in cache.layers:
                k_.delete()
                v_.delete()
            cache.lengths.delete()
            raise RuntimeError("tunnel dropped mid-dispatch")
        return real_admit(params_, cfg_, cache, dstate, sampling, *a, **k)

    bmod.admit_group = poison_once
    try:
        # Submit BOTH before start so one admission wave builds two
        # single-request groups (admit_batch=1).
        req1 = GenRequest(prompt_ids=[3, 4, 5], max_new_tokens=4)
        req2 = GenRequest(prompt_ids=[6, 7, 8, 9], max_new_tokens=4)
        batcher.submit(req1)
        batcher.submit(req2)
        batcher.start()
        with pytest.raises(RuntimeError, match="tunnel dropped"):
            req1.future.result(timeout=60)
        # req2 was requeued and admitted against the REBUILT allocator:
        # it completes with real tokens (admission actually ran again).
        out2 = req2.future.result(timeout=60)
        assert isinstance(out2, list) and 1 <= len(out2) <= 4
        assert calls["n"] >= 2
        # Fresh allocator bookkeeping is consistent after completion.
        assert batcher.alloc is not None
    finally:
        bmod.admit_group = real_admit
        batcher.stop()


# --------------------------------------------------------------------- #
# Scaling harness (VERDICT r4 #8): parity proves correctness; this
# records parallel *efficiency* so a TP/DP serving regression (a stray
# all-gather, a resharding copy in the decode hot path) shows up in CI
# as a rate collapse, not just in a hand-run profile. Absolute CPU-mesh
# numbers are meaningless; the sanity bound is deliberately loose.
# --------------------------------------------------------------------- #

MESH_LADDER = (
    {"data": 1},
    {"model": 2},
    {"model": 4, "data": 2},
)


async def _measure_mesh_rate(mesh_shape, steps=12, concurrency=4):
    import time

    cfg = LLMConfig(
        model_name="llama-tiny",
        provider="cpu",
        mesh_shape=mesh_shape,
        engine_slots=concurrency,
        engine_max_seq=128,
        engine_chunk=4,
        dtype="float32",
    )
    handler = LLMHandler(cfg)
    await handler.start()
    try:
        params = GenerationParams(max_new_tokens=16, temperature=0.0)

        async def one(i):
            await handler.generate_response(
                [ChatMessage(role="user", content=f"scale probe {i}")],
                params=params,
            )

        await asyncio.gather(*[one(i) for i in range(concurrency)])  # warm
        t0 = time.perf_counter()
        done = 0
        while done < steps:
            n = min(concurrency, steps - done)
            await asyncio.gather(*[one(100 + done + i) for i in range(n)])
            done += n
        return steps / (time.perf_counter() - t0)
    finally:
        await handler.stop()


@pytest.mark.asyncio
async def test_mesh_scaling_ladder_stays_serviceable():
    """Every rung of the serving-mesh ladder sustains throughput. The
    regression bound: no sharded config may collapse below 10% of the
    single-device rate (a resharding bug costs far more than mesh
    overhead on a virtual CPU mesh, where communication is memcpy).

    Deflaked: the bound is a RATE RATIO measured on a shared, noisy
    box — one loaded-CPU window can sink any single wall-clock
    measurement (observed failing at the seed commit in isolation while
    passing in suite order). A rung that lands under the bound
    re-measures, best-of-3, before the assertion decides; a real
    resharding regression fails all three attempts identically."""
    async def _best_rate(shape, floor=None, attempts=3):
        best = 0.0
        for _ in range(attempts):
            best = max(best, await _measure_mesh_rate(shape))
            if floor is None or best > floor:
                break  # already clears the bound — no retries needed
        return best

    base = await _best_rate({"data": 1})
    rates = {"data=1": base}
    for shape in MESH_LADDER[1:]:
        key = ",".join(f"{k}={v}" for k, v in shape.items())
        rates[key] = await _best_rate(shape, floor=0.1 * base)
    print("\nmesh scaling (virtual 8-CPU, llama-tiny):", rates)
    assert all(r > 0 for r in rates.values())
    for key, rate in rates.items():
        assert rate > 0.1 * base, (key, rates)
