"""CLI tests: arg surface, model listing, and the serve loop end to end
on the mock provider."""

import asyncio
import json

import pytest

from pilottai_tpu.cli import _build_parser, main, run_serve


def test_models_command(capsys):
    assert main(["models"]) == 0
    out = capsys.readouterr().out.splitlines()
    assert "llama3-8b-byte" in out and "gemma-2b" in out


def test_serve_args_parse():
    args = _build_parser().parse_args([
        "serve", "--model", "llama3-8b-byte", "--quantize", "int8",
        "--speculate", "6", "--max-seq", "4096", "--port", "9000",
        "--auth-token", "t",
    ])
    assert args.model == "llama3-8b-byte"
    assert args.quantize == "int8"
    assert args.speculate == 6
    assert args.max_seq == 4096


def test_train_command_synthetic(tmp_path, capsys):
    rc = main([
        "train", "--model", "llama-tiny", "--steps", "4",
        "--batch-size", "2", "--seq-len", "32", "--log-every", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"), "--save-every", "2",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "step 4/4 loss" in out
    # Checkpoints actually landed (steps 2 and 4) — the dir alone is
    # created by the constructor and proves nothing.
    from pilottai_tpu.checkpoint.train_io import TrainCheckpointer

    assert TrainCheckpointer(tmp_path / "ckpt").all_steps() == [2, 4]

    # Resume restores the latest step and continues to the new target.
    rc = main([
        "train", "--model", "llama-tiny", "--steps", "6",
        "--batch-size", "2", "--seq-len", "32", "--log-every", "2",
        "--checkpoint-dir", str(tmp_path / "ckpt"), "--resume",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert "step 6/6 loss" in out


def test_train_command_text_corpus(tmp_path, capsys):
    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog. " * 50)
    rc = main([
        "train", "--model", "llama-tiny", "--steps", "3",
        "--batch-size", "2", "--seq-len", "32", "--log-every", "3",
        "--data", str(corpus),
    ])
    assert rc == 0
    assert "step 3/3 loss" in capsys.readouterr().out


def test_parse_mesh_rejects_unknown_axis():
    from pilottai_tpu.cli import _parse_mesh

    with pytest.raises(SystemExit):
        _parse_mesh("bogus=2")
    mesh = _parse_mesh("fsdp=2,model=2")
    assert dict(mesh.shape) == {"data": 1, "fsdp": 2, "model": 2, "seq": 1}


@pytest.mark.asyncio
async def test_serve_loop_mock_end_to_end():
    args = _build_parser().parse_args(
        ["serve", "--provider", "mock", "--port", "0",
         "--dashboard-port", "0",  # constructor kwargs regression
         "--agents", "1"]          # attaches a Serve → /v1/tasks works
    )
    ready = asyncio.Event()
    stop = asyncio.Event()
    task = asyncio.create_task(run_serve(args, ready=ready, stop=stop))
    await asyncio.wait_for(ready.wait(), timeout=30)
    try:
        from tests.test_server import _request

        port = args._bound_port  # port 0 resolved at bind time
        status, _, body = await _request(port, "GET", "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"
        status, _, body = await _request(
            port, "POST", "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "hello"}]},
        )
        assert status == 200
        assert json.loads(body)["choices"][0]["message"]["content"]
        status, _, body = await _request(
            port, "POST", "/v1/tasks", {"task": "check the shelves"}
        )
        assert status == 200 and json.loads(body)["success"] is True
    finally:
        stop.set()
        await asyncio.wait_for(task, timeout=30)
